"""Elastic fleet membership — lease-based liveness on the Punchcard daemon.

The reference assumed an immortal Spark executor set; a TPU fleet is
preemptible.  This module adds the three pieces that make worker churn a
normal event instead of a crash:

* :class:`FleetMembership` — the daemon-side table behind the ``register`` /
  ``heartbeat`` / ``deregister`` / ``membership`` verbs.  Liveness is a
  lease: a worker that misses ``lease x miss_tolerance`` seconds of
  heartbeats is evicted.  Every join/leave/eviction bumps a monotonically
  increasing **membership epoch** — the single integer trainers poll to
  learn "the fleet changed".
* :class:`FleetWorker` — the worker-side client: registers, heartbeats from
  a daemon thread, re-registers transparently after an eviction.
* :class:`ElasticMembership` — the trainer-side poller: ``poll()`` returns
  the new desired worker count when the membership epoch moved, ``None``
  otherwise (including on transient daemon unreachability — elasticity is
  best-effort and must never kill a healthy run).

Preemption support: :func:`install_preemption_handler` turns SIGTERM into a
flag trainers check at epoch boundaries (:func:`preemption_requested`), so a
preempted worker drains to a boundary checkpoint and exits via
:class:`Preempted` instead of dying mid-step.
"""

from __future__ import annotations

import signal
import threading
import time
import uuid
from typing import Callable, Dict, Optional

__all__ = [
    "ElasticMembership",
    "FleetMembership",
    "FleetWorker",
    "Preempted",
    "install_preemption_handler",
    "preemption_requested",
    "reset_preemption",
]


# -- preemption (SIGTERM -> graceful boundary drain) -------------------------

_PREEMPTED = threading.Event()
_HANDLER_INSTALLED = False


class Preempted(RuntimeError):
    """Raised by trainers at the epoch boundary after SIGTERM: the boundary
    checkpoint is on disk, the process should exit and let a replacement
    resume from it."""


def _on_sigterm(signum, frame):  # pragma: no cover — exercised via raise path
    del signum, frame
    _PREEMPTED.set()


def install_preemption_handler() -> bool:
    """Install the SIGTERM→flag handler (idempotent).  Returns ``False``
    when it cannot be installed (non-main thread — signal handlers are a
    main-thread-only API), in which case preemption falls back to the
    default SIGTERM kill and recovery runs through the checkpoint path."""
    global _HANDLER_INSTALLED
    if _HANDLER_INSTALLED:
        return True
    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        return False
    _HANDLER_INSTALLED = True
    return True


def preemption_requested() -> bool:
    return _PREEMPTED.is_set()


def reset_preemption() -> None:
    """Clear the preemption flag (tests, or a worker that drained and is
    deliberately continuing)."""
    _PREEMPTED.clear()


# -- daemon-side membership table --------------------------------------------

class FleetMembership:
    """Lease-based membership table.  NOT self-locking: the daemon calls
    every method under its own condition variable (one lock domain for
    queue + jobs + fleet keeps the lock-order graph a single node).  The
    clock is injectable so lease expiry is testable without sleeping."""

    def __init__(self, lease: float = 10.0, miss_tolerance: int = 2,
                 clock: Callable[[], float] = time.monotonic):
        if lease <= 0:
            raise ValueError(f"lease must be > 0, got {lease}")
        if miss_tolerance < 1:
            raise ValueError(
                f"miss_tolerance must be >= 1, got {miss_tolerance}")
        self.lease = float(lease)
        self.miss_tolerance = int(miss_tolerance)
        self._clock = clock
        self.members: Dict[str, dict] = {}
        #: monotonically increasing; bumps on every join, leave, or eviction
        self.epoch = 0
        self.evictions = 0

    def _deadline(self) -> float:
        return self._clock() + self.lease * self.miss_tolerance

    def register(self, worker_id: Optional[str] = None, workers: int = 1,
                 host: Optional[str] = None,
                 meta: Optional[dict] = None) -> str:
        """Join (or re-join) the fleet; returns the worker id.  A re-register
        of a live member only refreshes its lease — the epoch moves only
        when the member set actually changes.  ``meta`` is an opaque
        JSON-safe dict carried through to :meth:`snapshot` (the serving
        tier tags replicas with their role/index here)."""
        wid = worker_id or uuid.uuid4().hex
        fresh = wid not in self.members
        self.members[wid] = {
            "workers": int(workers),
            "host": host,
            "meta": dict(meta) if meta else {},
            "deadline": self._deadline(),
        }
        if fresh:
            self.epoch += 1
        return wid

    def heartbeat(self, worker_id: str) -> bool:
        """Refresh the lease; ``False`` for an unknown (evicted or never
        registered) worker — the caller must re-register."""
        member = self.members.get(worker_id)
        if member is None:
            return False
        member["deadline"] = self._deadline()
        return True

    def deregister(self, worker_id: str) -> bool:
        if self.members.pop(worker_id, None) is None:
            return False
        self.epoch += 1
        return True

    def sweep(self) -> list:
        """Evict every member whose lease expired; returns the evicted ids.
        One epoch bump per sweep regardless of how many fell — pollers care
        that the set changed, not how many times."""
        now = self._clock()
        evicted = [wid for wid, m in self.members.items()
                   if m["deadline"] < now]
        for wid in evicted:
            del self.members[wid]
        if evicted:
            self.epoch += 1
            self.evictions += len(evicted)
        return evicted

    def workers_total(self) -> int:
        return sum(m["workers"] for m in self.members.values())

    def snapshot(self) -> dict:
        """JSON-safe view for the ``membership`` verb."""
        return {
            "epoch": self.epoch,
            "workers_total": self.workers_total(),
            "evictions": self.evictions,
            "members": {
                wid: {"workers": m["workers"], "host": m["host"],
                      **({"meta": m["meta"]} if m.get("meta") else {})}
                for wid, m in self.members.items()
            },
        }


# -- worker-side client ------------------------------------------------------

class FleetWorker:
    """Register with a Punchcard daemon and keep the lease alive from a
    background thread; transparently re-registers after an eviction (a
    stalled-then-recovered worker rejoins instead of staying a ghost)."""

    def __init__(self, host: str, port: int, secret: str = "",
                 workers: int = 1, worker_id: Optional[str] = None,
                 address: Optional[str] = None,
                 heartbeat_interval: Optional[float] = None):
        from distkeras_tpu.job_deployment import Job

        self._job = Job(host, port, secret=secret)
        self.worker_id = worker_id or uuid.uuid4().hex
        self.workers = int(workers)
        self.address = address
        self.lease: Optional[float] = None
        self.membership_epoch: Optional[int] = None
        self.rejoins = 0
        # register() runs on the caller's thread *and* on the heartbeat
        # thread (re-register after eviction); this lock keeps the
        # lease / epoch / rejoins triple coherent across both.
        self._state_lock = threading.Lock()
        self._interval = heartbeat_interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register(self) -> int:
        reply = self._job._rpc({
            "action": "register", "worker_id": self.worker_id,
            "workers": self.workers, "host": self.address,
        })
        if reply.get("status") != "ok":
            raise RuntimeError(f"register rejected: {reply}")
        with self._state_lock:
            self.lease = float(reply["lease"])
            self.membership_epoch = int(reply["epoch"])
            return self.membership_epoch

    def heartbeat(self) -> int:
        """One heartbeat round-trip; re-registers on eviction.  Returns the
        daemon's current membership epoch."""
        reply = self._job._rpc(
            {"action": "heartbeat", "worker_id": self.worker_id})
        if reply.get("status") == "unknown":
            # evicted (lease missed) — rejoin under the same id
            with self._state_lock:
                self.rejoins += 1
            return self.register()
        if reply.get("status") != "ok":
            raise RuntimeError(f"heartbeat rejected: {reply}")
        with self._state_lock:
            self.membership_epoch = int(reply["epoch"])
            return self.membership_epoch

    def deregister(self) -> None:
        self._job._rpc(
            {"action": "deregister", "worker_id": self.worker_id})

    def start(self) -> None:
        """Register now and heartbeat from a daemon thread at a third of the
        lease (so ``miss_tolerance`` misses take several lost beats)."""
        self.register()
        interval = self._interval or max(self.lease / 3.0, 0.02)

        def _beat():
            while not self._stop.wait(interval):
                try:
                    self.heartbeat()
                except (OSError, ConnectionError, ValueError, RuntimeError):
                    # transient control-plane failure: keep beating; the
                    # lease's miss tolerance absorbs it, and a real daemon
                    # outage evicts us exactly as designed
                    continue

        self._thread = threading.Thread(target=_beat, daemon=True)
        self._thread.start()

    def stop(self, deregister: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if deregister:
            try:
                self.deregister()
            except (OSError, ConnectionError, ValueError):
                pass  # daemon already gone; the lease will expire us


# -- trainer-side poller -----------------------------------------------------

class ElasticMembership:
    """Trainer-facing epoch-boundary poller over the ``membership`` verb.

    ``poll()`` contacts the daemon and returns the new desired worker count
    when the membership epoch changed since the last poll; ``None`` when
    the fleet is unchanged, on the first (baseline) poll, or when the
    daemon is transiently unreachable.  The count is the fleet's summed
    per-member ``workers``, clamped to ``[min_workers, max_workers]``.
    """

    def __init__(self, host: str, port: int, secret: str = "",
                 min_workers: int = 1, max_workers: Optional[int] = None):
        from distkeras_tpu.job_deployment import Job

        self._job = Job(host, port, secret=secret)
        self.min_workers = int(min_workers)
        self.max_workers = max_workers
        self.last_epoch: Optional[int] = None

    def poll(self) -> Optional[int]:
        try:
            reply = self._job._rpc({"action": "membership"})
        except (OSError, ConnectionError, ValueError):
            return None
        if reply.get("status") != "ok":
            return None
        epoch = int(reply["epoch"])
        if self.last_epoch == epoch:
            return None
        first = self.last_epoch is None
        self.last_epoch = epoch
        if first:
            return None  # baseline read, not a change
        n = max(self.min_workers, int(reply.get("workers_total") or 0))
        if self.max_workers is not None:
            n = min(n, int(self.max_workers))
        return n
