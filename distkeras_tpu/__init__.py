"""distkeras_tpu — a TPU-native distributed training framework with the
capabilities of dist-keras (SemanticBeeng/dist-keras).

The reference trains Keras models data-parallel on Apache Spark through a
socket parameter server; this framework keeps that user surface — Trainers
(``SingleTrainer``, ``AveragingTrainer``, ``EnsembleTrainer``, ``DOWNPOUR``,
``AEASGD``, ``EAMSGD``, ``ADAG``, ``DynSGD``), DataFrame transformers,
predictors, evaluators — on an idiomatic JAX/XLA stack: workers are TPU mesh
devices, the parameter-server center variable is replicated on-device, and
commit/pull round-trips are XLA collectives over ICI/DCN inside a single
compiled SPMD program.  See SURVEY.md for the reference analysis this build
follows.
"""

__version__ = "0.1.0"

from distkeras_tpu import chaos, fleet, frame, sanitizer, utils
from distkeras_tpu.evaluators import AccuracyEvaluator, LossEvaluator, PerplexityEvaluator
from distkeras_tpu.frame import (
    DataFrame,
    Row,
    from_numpy,
    from_pandas,
    from_rows,
    from_spark,
    read_csv,
    to_spark,
)
from distkeras_tpu.predictors import ModelPredictor
from distkeras_tpu.trainers import (
    ADAG,
    AEASGD,
    DOWNPOUR,
    AdaptiveDynSGD,
    AsynchronousDistributedTrainer,
    AveragingTrainer,
    DistributedTrainer,
    DynSGD,
    EAMSGD,
    EnsembleTrainer,
    SingleTrainer,
    Trainer,
)
from distkeras_tpu.transformers import (
    DenseTransformer,
    LabelIndexTransformer,
    MinMaxTransformer,
    OneHotTransformer,
    ReshapeTransformer,
    StandardScaleTransformer,
)

__all__ = [
    "DataFrame",
    "Row",
    "from_numpy",
    "from_pandas",
    "from_spark",
    "to_spark",
    "from_rows",
    "read_csv",
    "Trainer",
    "SingleTrainer",
    "AveragingTrainer",
    "EnsembleTrainer",
    "DistributedTrainer",
    "AsynchronousDistributedTrainer",
    "DOWNPOUR",
    "AEASGD",
    "EAMSGD",
    "ADAG",
    "DynSGD",
    "AdaptiveDynSGD",
    "ModelPredictor",
    "AccuracyEvaluator",
    "LossEvaluator",
    "PerplexityEvaluator",
    "LabelIndexTransformer",
    "OneHotTransformer",
    "MinMaxTransformer",
    "ReshapeTransformer",
    "DenseTransformer",
    "StandardScaleTransformer",
    "frame",
    "sanitizer",
    "utils",
]
