"""``distkeras_tpu.sanitizer`` — runtime twins of dklint's static rules.

Enabled by ``DISTKERAS_SANITIZE=1`` (record) or ``strict`` (raise):

* :mod:`.transfer` — DK101's twin: the jitted epoch/window dispatch runs
  under ``jax.transfer_guard`` plus a Python-level interposition on
  ``jax.Array``'s scalar-conversion methods, so a host sync hidden in the
  hot loop raises (strict) or bumps ``sanitizer_transfer_violations``
  (record), with the enclosing telemetry span named;
* :mod:`.donation` — DK103's twin: donated-but-still-live buffers are
  poisoned at engine step boundaries so post-donation reads fail on every
  backend, not just where donation really aliases;
* :mod:`.lockwatch` — DK105's twin: wrapped locks record per-thread
  acquisition order (inversions and off-lock cv-waits are flagged), and
  guarded shared dicts/sockets catch off-lock mutation and torn frames.

All guards collapse to no-ops when the flag is off; the engines read the
mode once at build (``self._sanitize``) so the disabled path stays a single
cached bool and lowered programs are byte-identical (pinned in
tests/test_sanitizer.py, the telemetry/dynamics convention).
"""

from __future__ import annotations

from distkeras_tpu.sanitizer import donation, lockwatch, runtime, transfer
from distkeras_tpu.sanitizer.lockwatch import LockOrderViolation
from distkeras_tpu.sanitizer.runtime import (
    SanitizerViolation,
    configure,
    enabled,
    mode,
    report,
    strict,
    violations,
)
from distkeras_tpu.sanitizer.transfer import TransferViolation

__all__ = [
    "LockOrderViolation",
    "SanitizerViolation",
    "TransferViolation",
    "configure",
    "donation",
    "enabled",
    "lockwatch",
    "mode",
    "report",
    "runtime",
    "strict",
    "transfer",
    "violations",
]
