"""Lock-order watchdog — DK105's runtime twin.

Instruments the daemon-thread locking in ``job_deployment`` / ``networking``:

* :func:`maybe_wrap` proxies a ``threading.Lock``/``Condition`` so every
  acquisition records into a per-thread held-set and a process-global
  acquisition-order graph.  Acquiring B while holding A records the edge
  A->B; a later acquire of A while holding B is an **inversion** (the
  classic two-thread deadlock shape) and is reported.  A ``cv.wait()`` or
  ``notify()`` without holding the wrapped lock is reported too (the lost
  wakeup DK105 hunts statically);
* :func:`guard_map` wraps a dict shared across threads so any *mutation*
  off the owning lock is reported — the direct runtime analogue of DK105's
  "guarded attribute written outside the lock";
* :func:`exclusive` guards single-owner resources (a socket carrying
  length-prefixed frames): concurrent use from two threads interleaves
  frames on the wire, a corruption DK105 cannot see statically.

Everything returns the raw object / is a no-op when the sanitizer is off,
so the daemon's disabled-path behaviour is byte-for-byte the stock
``threading`` types.
"""

from __future__ import annotations

import threading

from distkeras_tpu.sanitizer import runtime
from distkeras_tpu.sanitizer.runtime import SanitizerViolation

__all__ = [
    "LockOrderViolation",
    "GuardedLock",
    "GuardedMap",
    "exclusive",
    "guard_map",
    "maybe_wrap",
    "reset",
]

KIND = "lock"


class LockOrderViolation(SanitizerViolation):
    """Lock-order inversion, off-lock wait/notify, or off-lock mutation."""


class _Watch:
    """Process-global acquisition bookkeeping shared by every wrapped lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._edges = set()  # (first, second) lock names, acquisition order
        self._tls = threading.local()

    def held(self):
        stack = getattr(self._tls, "held", None)
        if stack is None:
            stack = self._tls.held = []
        return stack

    def before_acquire(self, name):
        held = self.held()
        for h in held:
            if h == name:
                continue
            with self._lock:
                inverted = (name, h) in self._edges
                self._edges.add((h, name))
            if inverted:
                runtime.report(
                    KIND,
                    f"lock-order inversion: acquiring '{name}' while holding "
                    f"'{h}', but the opposite order '{name}' -> '{h}' was "
                    "also observed — two threads interleaving these paths "
                    "deadlock",
                    LockOrderViolation,
                )

    def acquired(self, name):
        self.held().append(name)

    def released(self, name):
        held = self.held()
        # release-from-anywhere: remove the most recent matching entry
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def holds(self, name):
        return name in self.held()

    def reset(self):
        with self._lock:
            self._edges.clear()


_watch = _Watch()


def reset() -> None:
    """Clear the global acquisition-order graph (tests)."""
    _watch.reset()


class GuardedLock:
    """Proxy around a ``threading.Lock``/``RLock``/``Condition`` feeding the
    watchdog.  Supports the full Condition surface so it drops in for
    ``PunchcardServer._cv``."""

    def __init__(self, inner, name: str):
        self._inner = inner
        self._name = name

    # -- lock surface -------------------------------------------------------
    def acquire(self, *args, **kwargs):
        _watch.before_acquire(self._name)
        got = self._inner.acquire(*args, **kwargs)
        if got:
            _watch.acquired(self._name)
        return got

    def release(self):
        _watch.released(self._name)
        return self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def holds(self) -> bool:
        """True when the calling thread holds this lock (watchdog view)."""
        return _watch.holds(self._name)

    # -- condition surface --------------------------------------------------
    def _require_held(self, op):
        if not _watch.holds(self._name):
            runtime.report(
                KIND,
                f"{op} on '{self._name}' without holding it — the wakeup "
                "(or the predicate it protects) races",
                LockOrderViolation,
            )

    def wait(self, timeout=None):
        self._require_held(f"cv.wait(timeout={timeout})")
        return self._inner.wait(timeout)

    def wait_for(self, predicate, timeout=None):
        self._require_held("cv.wait_for()")
        return self._inner.wait_for(predicate, timeout)

    def notify(self, n=1):
        self._require_held("cv.notify()")
        return self._inner.notify(n)

    def notify_all(self):
        self._require_held("cv.notify_all()")
        return self._inner.notify_all()


def maybe_wrap(lock, name: str):
    """Wrap ``lock`` in a :class:`GuardedLock` when the sanitizer is on;
    return it untouched otherwise."""
    if not runtime.enabled():
        return lock
    return GuardedLock(lock, name)


class GuardedMap(dict):
    """Dict whose mutations must happen while ``lock`` is held by the
    calling thread (reads stay free — CPython dict reads are atomic and the
    daemon's status polls rely on that).

    Known blind spot: only mutations of *this* mapping are policed.
    Mutating a value fetched from it (``guarded[k]["field"] = v``) is an
    ordinary inner-dict write the guard never sees — exactly the shape of
    the daemon races fixed in PR 11 (``_runner_loop`` flipping
    ``job["status"]`` off-lock).  Discipline for nested state must hold by
    construction: fetch under the lock, mutate under the lock."""

    def __init__(self, data, lock: GuardedLock, name: str):
        super().__init__(data)
        self._lock = lock
        self._name = name

    def _check(self, op):
        if not self._lock.holds():
            runtime.report(
                KIND,
                f"off-lock write: {op} on '{self._name}' without holding "
                f"'{self._lock._name}'",
                LockOrderViolation,
            )

    def __setitem__(self, key, value):
        self._check(f"[{key!r}] = ...")
        super().__setitem__(key, value)

    def __delitem__(self, key):
        self._check(f"del [{key!r}]")
        super().__delitem__(key)

    def pop(self, *args):
        self._check("pop()")
        return super().pop(*args)

    def popitem(self):
        self._check("popitem()")
        return super().popitem()

    def clear(self):
        self._check("clear()")
        return super().clear()

    def update(self, *args, **kwargs):
        self._check("update()")
        return super().update(*args, **kwargs)

    def setdefault(self, key, default=None):
        self._check(f"setdefault({key!r})")
        return super().setdefault(key, default)


def guard_map(data, lock, name: str):
    """A :class:`GuardedMap` over ``data`` when the sanitizer is on AND the
    lock is wrapped; the plain dict otherwise."""
    if not runtime.enabled() or not isinstance(lock, GuardedLock):
        return dict(data)
    return GuardedMap(data, lock, name)


# -- single-owner resources (sockets) ---------------------------------------

_excl_lock = threading.Lock()
_excl = {}  # (id(resource), operation) -> (thread ident, depth)


class _Exclusive:
    """Context manager asserting single-threaded use of one resource for
    the duration of an operation (e.g. one length-prefixed frame).  Keyed
    by (resource, operation) so full-duplex use — one thread sending while
    another receives — stays legal; only same-direction concurrency tears
    the framing."""

    __slots__ = ("_obj", "_what", "_active")

    def __init__(self, obj, what):
        self._obj = obj
        self._what = what
        self._active = False

    def __enter__(self):
        if not runtime.enabled():
            return self
        me = threading.get_ident()
        key = (id(self._obj), self._what)
        with _excl_lock:
            owner = _excl.get(key)
            if owner is None:
                _excl[key] = (me, 1)
                self._active = True
            elif owner[0] == me:
                _excl[key] = (me, owner[1] + 1)
                self._active = True
        if not self._active:
            runtime.report(
                KIND,
                f"concurrent {self._what} from two threads — length-prefixed "
                "frames interleave on the wire and the stream is torn",
                LockOrderViolation,
            )
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._active:
            key = (id(self._obj), self._what)
            with _excl_lock:
                tid, depth = _excl[key]
                if depth <= 1:
                    del _excl[key]
                else:
                    _excl[key] = (tid, depth - 1)
        return False


def exclusive(obj, what: str) -> _Exclusive:
    return _Exclusive(obj, what)
