"""Transfer guard — DK101's runtime twin.

Wraps the jitted epoch/window dispatch so a host<->device round-trip inside
the hot loop is caught *as it executes*, not just statically:

* ``jax.transfer_guard("disallow")`` arms XLA's own guard for the dynamic
  extent of the dispatch (strict mode only): on accelerator backends any
  implicit device-to-host or host-to-device copy raises.  On the CPU
  backend arrays are host-resident and XLA's d2h guard never fires — which
  is exactly why the second layer exists;
* a Python-level interposition on ``jax.Array``'s scalar-conversion
  methods (``item``/``tolist``/``__float__``/``__int__``/``__index__``/
  ``__array__``): while a guard region is open on the current thread, any
  of these on a concrete array is a host sync hidden in the hot loop (the
  classic ``.item()`` in a jitted body, executing at trace time) and is
  reported with the innermost open telemetry span attached.

The interposition is installed once, only when the sanitizer is enabled —
a disabled process never patches anything — and the patched methods cost
one thread-local read when no guard is open.
"""

from __future__ import annotations

import contextlib
import threading

from distkeras_tpu.sanitizer import runtime
from distkeras_tpu.sanitizer.runtime import SanitizerViolation

__all__ = ["TransferViolation", "guard"]

KIND = "transfer"

# jax.Array methods whose execution on a concrete array forces a
# device->host materialisation (mirrors DK101's HOST_SYNC_METHODS).
_SYNC_METHODS = ("item", "tolist", "__float__", "__int__", "__index__",
                 "__array__")

_tls = threading.local()  # .depth (int), .label (str)
_install_lock = threading.Lock()
_installed = False


class TransferViolation(SanitizerViolation):
    """A host<->device transfer executed inside a guarded hot loop."""


def _span_context(label):
    """'span <name>' when a telemetry span is open on this thread, else the
    guard's static label — the violation message must name where in the
    pipeline the sync happened either way."""
    from distkeras_tpu import telemetry

    span = telemetry.trace.current()
    return f"span '{span}'" if span else f"guard '{label}'"


def _violate(what):
    label = getattr(_tls, "label", "?")
    runtime.report(
        KIND,
        f"host transfer inside the hot loop ({_span_context(label)}): {what}",
        TransferViolation,
    )


def _wrap(name, orig):
    def guarded(self, *args, **kwargs):
        if getattr(_tls, "depth", 0):
            _violate(f"jax.Array.{name}() forces a device->host sync")
        return orig(self, *args, **kwargs)

    guarded.__name__ = name
    guarded.__qualname__ = f"ArrayImpl.{name}"
    return guarded


def _install():
    """Patch the concrete jax.Array class once per process (enabled mode
    only).  ArrayImpl is the single concrete class behind every committed
    array, so one patch covers all of them."""
    global _installed
    with _install_lock:
        if _installed:
            return
        try:
            from jax._src.array import ArrayImpl
        except ImportError:  # jax moved the class; fall back to a live array
            import jax.numpy as jnp

            ArrayImpl = type(jnp.zeros(()))
        for name in _SYNC_METHODS:
            orig = getattr(ArrayImpl, name, None)
            if orig is not None:
                setattr(ArrayImpl, name, _wrap(name, orig))
        _installed = True


@contextlib.contextmanager
def guard(label: str):
    """Guard the dynamic extent of one hot-loop dispatch.

    No-op when the sanitizer is off.  In strict mode XLA's transfer guard is
    armed as well, and its errors are re-raised as :class:`TransferViolation`
    with the span context attached."""
    if not runtime.enabled():
        yield
        return
    _install()
    prev_label = getattr(_tls, "label", None)
    _tls.label = label
    _tls.depth = getattr(_tls, "depth", 0) + 1
    try:
        if runtime.strict():
            import jax

            try:
                with jax.transfer_guard("disallow"):
                    yield
            except TransferViolation:
                raise
            except Exception as e:  # XlaRuntimeError is backend-defined
                text = str(e)
                if "Disallowed" in text and "transfer" in text:
                    raise TransferViolation(
                        f"host transfer inside the hot loop "
                        f"({_span_context(label)}): {text.splitlines()[0]}"
                    ) from e
                raise
        else:
            yield
    finally:
        _tls.depth -= 1
        _tls.label = prev_label
