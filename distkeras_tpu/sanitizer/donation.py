"""Donation guard — DK103's runtime twin.

``run_epoch``'s program donates the input state (``donate_argnums=(0,)``).
On backends where donation really aliases buffers, JAX already deletes the
donated inputs and a stale read raises.  But donation can silently *not*
happen — a sharding/layout mismatch, or a backend (CPU in some versions)
that ignores the hint — and then a read-after-donate bug sits latent until
the code first runs on a TPU.  The guard closes that gap: at every engine
step boundary it **poisons** whatever the runtime left alive, so a
post-donation read fails deterministically on every backend, right where
DK103 would have flagged it statically.

Poisoning uses ``Array.delete()`` — the donated handles are either already
deleted (true aliasing) or about to be unreachable from the caller (the
``run_epoch`` contract), so deleting them never changes a correct program.
"""

from __future__ import annotations

import threading

from distkeras_tpu.sanitizer import runtime

__all__ = ["poison", "stats", "reset_stats"]

KIND = "donation"

_lock = threading.Lock()
_stats = {"poisoned": 0, "already_deleted": 0, "boundaries": 0}


def poison(tree, label: str = "donated state") -> int:
    """Delete every live ``jax.Array`` leaf of a donated pytree.

    Returns how many leaves were still alive (i.e. the runtime did NOT
    donate them — each one is a latent cross-backend divergence, counted in
    the ``sanitizer_donation_poisoned`` gauge-like counter).  No-op when the
    sanitizer is off."""
    if not runtime.enabled():
        return 0
    import jax

    poisoned = already = 0
    for leaf in jax.tree.leaves(tree):
        if not isinstance(leaf, jax.Array):
            continue
        try:
            if leaf.is_deleted():
                already += 1
                continue
            leaf.delete()
            poisoned += 1
        except RuntimeError:  # deleted concurrently / non-deletable view
            already += 1
    with _lock:
        _stats["poisoned"] += poisoned
        _stats["already_deleted"] += already
        _stats["boundaries"] += 1
    if poisoned:
        from distkeras_tpu.telemetry.metrics import metrics as _registry

        _registry.counter(
            "sanitizer_donation_poisoned",
            help="donated-but-still-live buffers the sanitizer poisoned",
        ).inc(poisoned)
    return poisoned


def stats() -> dict:
    with _lock:
        return dict(_stats)


def reset_stats() -> None:
    with _lock:
        for k in _stats:
            _stats[k] = 0
