"""Sanitizer on/off/strict switch and violation routing.

The sanitizer is opt-in through ``DISTKERAS_SANITIZE``:

* unset / ``0`` / ``false`` — **off**: every guard is a no-op and the
  engines' build-time check is a single cached bool (pinned by test, the
  same zero-cost convention as ``DISTKERAS_TELEMETRY``/``DISTKERAS_DYNAMICS``);
* ``1`` / ``true`` — **record**: violations increment ``sanitizer_*``
  counters in the telemetry registry (and warn once per guard kind), but
  execution continues unchanged;
* ``strict`` — violations raise, naming the enclosing telemetry span.

``mode()`` caches its answer after the first read, exactly like
``telemetry.runtime.enabled()`` — the engines read it once at build and
store the bool, so the per-epoch cost of a disabled sanitizer is zero.
Tests flip the switch with :func:`configure` instead of mutating
``os.environ``.
"""

from __future__ import annotations

import os
import threading
import warnings

__all__ = [
    "MODES",
    "SanitizerViolation",
    "configure",
    "enabled",
    "mode",
    "report",
    "strict",
    "violations",
]

_FALSEY = ("", "0", "false", "no")
MODES = ("off", "record", "strict")

# None = not yet resolved from the environment; one of MODES once resolved
# or forced via configure().
_MODE = None

# record-mode log: (kind, message) tuples, bounded so a hot loop that
# violates every step cannot grow memory without bound
_VIOLATIONS: list = []
_VIOLATIONS_CAP = 200
_WARNED_KINDS: set = set()
_LOCK = threading.Lock()


class SanitizerViolation(RuntimeError):
    """Base class for everything the sanitizer raises in strict mode."""


def mode() -> str:
    """Resolved sanitizer mode; cached after the first environment read."""
    global _MODE
    if _MODE is None:
        raw = os.environ.get("DISTKERAS_SANITIZE", "").lower()
        if raw in _FALSEY:
            _MODE = "off"
        elif raw == "strict":
            _MODE = "strict"
        else:
            _MODE = "record"
    return _MODE


def enabled() -> bool:
    return mode() != "off"


def strict() -> bool:
    return mode() == "strict"


def configure(new_mode=None) -> None:
    """Force the mode (``"off"``/``"record"``/``"strict"``) or reset to
    env-driven (``None``, re-read lazily on the next :func:`mode` call).
    Also clears the recorded-violation log."""
    global _MODE
    if new_mode is not None and new_mode not in MODES:
        raise ValueError(f"mode must be one of {MODES}, got {new_mode!r}")
    with _LOCK:
        _MODE = new_mode
        _VIOLATIONS.clear()
        _WARNED_KINDS.clear()


def violations(kind=None) -> list:
    """Recorded (kind, message) violations, optionally filtered by kind."""
    with _LOCK:
        out = list(_VIOLATIONS)
    if kind is not None:
        out = [v for v in out if v[0] == kind]
    return out


def _flightdeck_note(kind: str, message: str) -> None:
    # Violations land in the flight-recorder ring (and, for strict mode, a
    # blackbox dump) only when telemetry is on — with it off this is one
    # cached-bool check, and report() itself only runs under an active guard.
    from distkeras_tpu.telemetry import runtime as _tel_runtime

    if not _tel_runtime.enabled():
        return
    from distkeras_tpu.telemetry.flightdeck.recorder import recorder as _rec

    _rec.record_sanitizer(kind, message, strict())


def report(kind: str, message: str, exc_type=SanitizerViolation) -> None:
    """Route one violation: raise in strict mode; in record mode bump the
    ``sanitizer_<kind>_violations`` counter, remember the message, and warn
    the first time each kind fires."""
    _flightdeck_note(kind, message)
    if strict():
        from distkeras_tpu.telemetry import runtime as _tel_runtime

        if _tel_runtime.enabled():
            from distkeras_tpu.telemetry.flightdeck.recorder import on_crash

            on_crash(f"sanitizer strict violation [{kind}]: {message}")
        raise exc_type(message)
    # record mode — the counter lives in the telemetry registry so the
    # existing exporters (Prometheus / JSONL / fleet merge) pick it up; the
    # registry is a process-global dict, usable whether or not telemetry
    # file output is on
    from distkeras_tpu.telemetry.metrics import metrics as _registry

    _registry.counter(
        f"sanitizer_{kind}_violations",
        help=f"runtime sanitizer violations ({kind} guard)",
    ).inc()
    with _LOCK:
        if len(_VIOLATIONS) < _VIOLATIONS_CAP:
            _VIOLATIONS.append((kind, message))
        first = kind not in _WARNED_KINDS
        _WARNED_KINDS.add(kind)
    if first:
        warnings.warn(f"sanitizer [{kind}]: {message}", RuntimeWarning,
                      stacklevel=3)
