"""Ring attention — sequence/context parallelism over the device mesh.

The reference has no long-context story (SURVEY.md §5.7: MLP/CNN-scale models
only); this module is the TPU-native extension that makes sequence length a
shardable dimension, so the framework scales to contexts that do not fit one
chip's HBM.

Design (Liu et al., "Ring Attention with Blockwise Transformers", 2023 —
re-derived here for ``shard_map``): the sequence axis is sharded over a mesh
axis; every device holds one Q/K/V block.  K/V blocks rotate around the ring
with ``lax.ppermute`` (neighbour hops over ICI) while each device accumulates
its Q block's attention with a numerically-stable online softmax
(flash-attention-style running max / denominator).  Compute for block *t*
overlaps the transfer of block *t+1* — XLA overlaps the collective-permute
with the matmuls, so the ring latency hides behind the FLOPs.

No step materialises the full [seq, seq] score matrix, and per-device memory
is O(block² + block·d) instead of O(seq²).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from distkeras_tpu.utils.compat import axis_size, shard_map
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["ring_attention", "local_attention", "ring_attention_sharded",
           "attention"]


def _block_attention(q, k, v, carry, block_mask):
    """One online-softmax accumulation step.

    q: [b, h, lq, d]; k/v: [b, h, lk, d];
    carry = (num [b,h,lq,d], den [b,h,lq], m [b,h,lq]);
    block_mask: [lq, lk] additive mask (0 or -inf) or None.
    """
    num, den, m = carry
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if block_mask is not None:
        s = s + block_mask
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard fully-masked rows: exp(-inf - -inf) -> exp(0); zero them via p
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    num = num * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    den = den * alpha + p.sum(axis=-1)
    return num, den, m_new


def local_attention(q, k, v, causal: bool = False, segment_ids=None):
    """Reference (single-device) attention with the same layout
    ([batch, seq, heads, dim]); used by tests and the non-sharded fallback.

    ``segment_ids`` (``[batch, seq]`` ints, the sequence-packing convention
    of :mod:`distkeras_tpu.datapipe.packing`) additionally restricts token
    *i* to keys with the same segment id — each packed segment attends as
    if it were alone in the row.  The diagonal is always in-segment
    (``seg[i] == seg[i]``), so no softmax row is fully masked, pad rows
    included.  With ``segment_ids=None`` the math (and the bits) are
    unchanged."""
    qt = jnp.moveaxis(q, 1, 2)  # [b,h,l,d]
    kt = jnp.moveaxis(k, 1, 2)
    vt = jnp.moveaxis(v, 1, 2)
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    if causal or segment_ids is not None:
        lq, lk = s.shape[-2], s.shape[-1]
        mask = (jnp.tril(jnp.ones((lq, lk), bool)) if causal
                else jnp.ones((lq, lk), bool))
        if segment_ids is not None:
            seg = jnp.asarray(segment_ids)
            mask = mask & (seg[:, None, :, None] == seg[:, None, None, :])
        s = jnp.where(mask, s, -jnp.inf)
    out = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), vt)
    return jnp.moveaxis(out, 1, 2)


def attention(q, k, v, causal: bool = False, use_flash: Optional[bool] = None,
              segment_ids=None):
    """Single-device attention dispatcher ([batch, seq, heads, dim]).

    On the TPU backend this routes to the fused Pallas flash kernel
    (:mod:`distkeras_tpu.ops.pallas`) — tiled online softmax, no [seq, seq]
    HBM materialisation; elsewhere (CPU test meshes) it uses the jnp
    reference path, which XLA:CPU handles better than the Pallas interpreter.

    ``segment_ids`` (sequence packing) forces the reference path: the flash
    kernel has no segment-mask tiling.
    """
    if segment_ids is not None:
        return local_attention(q, k, v, causal=causal, segment_ids=segment_ids)
    if use_flash is None:
        use_flash = jax.default_backend() == "tpu"
    if use_flash:
        from distkeras_tpu.ops.pallas import flash_attention

        return flash_attention(q, k, v, causal)
    return local_attention(q, k, v, causal=causal)


def ring_attention(q, k, v, axis_name: str, causal: bool = False):
    """Blockwise ring attention inside ``shard_map``.

    Args: per-device blocks [batch, block_len, heads, dim] with the sequence
    axis sharded over ``axis_name``.  Returns the attention output for this
    device's Q block, exactly equal (up to float assoc.) to full attention
    over the gathered sequence.
    """
    n = axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    qt = jnp.moveaxis(q, 1, 2)  # [b,h,lq,d]
    kt = jnp.moveaxis(k, 1, 2)
    vt = jnp.moveaxis(v, 1, 2)
    b, h, lq, d = qt.shape
    lk = kt.shape[2]

    neg = jnp.asarray(-jnp.inf, qt.dtype)
    num0 = jnp.zeros((b, h, lq, d), qt.dtype)
    den0 = jnp.zeros((b, h, lq), qt.dtype)
    m0 = jnp.full((b, h, lq), neg, qt.dtype)
    perm = [(i, (i + 1) % n) for i in range(n)]
    tri = jnp.tril(jnp.ones((lq, lk), bool)) if causal else None

    def body(t, state):
        kt_cur, vt_cur, carry = state
        # kv currently held originated at device (my_idx - t) mod n
        src = (my_idx - t) % n
        if causal:
            # block-level causal structure: full attend when src < my block,
            # diagonal causal mask when src == my block, skip when src > mine.
            diag = jnp.where(tri, 0.0, -jnp.inf).astype(qt.dtype)
            block_mask = jnp.where(
                src == my_idx, diag, jnp.where(src < my_idx, 0.0, -jnp.inf)
            ).astype(qt.dtype)
        else:
            block_mask = None
        carry = _block_attention(qt, kt_cur, vt_cur, carry, block_mask)
        kt_nxt = lax.ppermute(kt_cur, axis_name, perm)
        vt_nxt = lax.ppermute(vt_cur, axis_name, perm)
        return kt_nxt, vt_nxt, carry

    _, _, (num, den, m) = lax.fori_loop(0, n, body, (kt, vt, (num0, den0, m0)))
    out = num / jnp.where(den == 0, 1.0, den)[..., None]
    return jnp.moveaxis(out, 1, 2)


def ring_attention_sharded(q, k, v, mesh: Mesh, axis_name: Optional[str] = None,
                           causal: bool = False):
    """Convenience wrapper: global [batch, seq, heads, dim] arrays, sequence
    axis sharded over ``axis_name``; runs :func:`ring_attention` under
    ``shard_map``."""
    axis_name = axis_name or mesh.axis_names[0]
    spec = P(None, axis_name)
    fn = shard_map(
        partial(ring_attention, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
