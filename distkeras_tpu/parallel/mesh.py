"""Device-mesh construction — the TPU replacement for Spark's executor pool.

In the reference, parallelism = Spark tasks (one Python worker per partition,
``distkeras/trainers.py :: DistributedTrainer.train`` repartitions then calls
``mapPartitionsWithIndex``).  Here a *worker* is a position along the
``workers`` axis of a ``jax.sharding.Mesh``: worker-local state is sharded
along that axis, the parameter-server center variable is replicated across it,
and commit/pull round-trips become XLA collectives over ICI/DCN.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "WORKER_AXIS",
    "SEQ_AXIS",
    "make_mesh",
    "make_mesh_grid",
    "worker_sharding",
    "replicated_sharding",
    "local_device_count",
]

WORKER_AXIS = "workers"
SEQ_AXIS = "seq"


def local_device_count() -> int:
    return jax.device_count()


def make_mesh(
    num_workers: Optional[int] = None,
    axis_name: str = WORKER_AXIS,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """A 1-D data-parallel mesh of ``num_workers`` devices.

    ``num_workers`` defaults to every visible device (the analogue of the
    reference's ``num_workers`` trainer kwarg, except workers map 1:1 onto
    chips instead of Spark tasks).  Multi-host processes contribute their
    devices automatically via ``jax.devices()`` after
    ``jax.distributed.initialize`` (see :mod:`distkeras_tpu.networking`).
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_workers is None:
        num_workers = len(devices)
    if num_workers > len(devices):
        raise ValueError(
            f"num_workers={num_workers} exceeds visible devices ({len(devices)}). "
            "On CPU, set XLA_FLAGS=--xla_force_host_platform_device_count=N."
        )
    return Mesh(np.array(devices[:num_workers]), (axis_name,))


def make_mesh_grid(
    *dims: int,
    axis_names: tuple = (WORKER_AXIS, SEQ_AXIS),
    devices: Optional[Sequence] = None,
) -> Mesh:
    """N-D mesh grid, one named axis per dim: (workers, seq) for data x
    sequence parallelism (ring attention's neighbour hops ride ICI),
    (workers, stages) for the pipeline, (workers, stages, model) for the
    three-axis dp x pp x tp composition."""
    if len(dims) != len(axis_names):
        raise ValueError(f"{len(dims)} mesh dims for axis names {axis_names}")
    devices = list(devices if devices is not None else jax.devices())
    need = int(np.prod(dims))
    if need > len(devices):
        raise ValueError(
            f"mesh {'x'.join(map(str, dims))} needs {need} devices, "
            f"have {len(devices)}"
        )
    grid = np.array(devices[:need]).reshape(dims)
    return Mesh(grid, axis_names)


def worker_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for per-worker state: leading axis split over the worker axis."""
    return NamedSharding(mesh, P(mesh.axis_names[0]))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for the center variable: replicated on every worker."""
    return NamedSharding(mesh, P())
