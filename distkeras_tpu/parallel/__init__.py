"""Parallel runtime: mesh construction, the SPMD windowed engine, and ring
attention (sequence parallelism)."""

from distkeras_tpu.parallel.engine import TrainState, WindowedEngine, plan_workers
from distkeras_tpu.parallel.gspmd import TP_AXIS, GSPMDEngine
from distkeras_tpu.parallel.pipeline import PP_AXIS, PipelineEngine
from distkeras_tpu.parallel.mesh import (
    SEQ_AXIS,
    WORKER_AXIS,
    make_mesh,
    make_mesh_grid,
    replicated_sharding,
    worker_sharding,
)
from distkeras_tpu.parallel.ring import (
    attention,
    local_attention,
    ring_attention,
    ring_attention_sharded,
)

__all__ = [
    "WindowedEngine",
    "GSPMDEngine",
    "TP_AXIS",
    "PipelineEngine",
    "PP_AXIS",
    "TrainState",
    "plan_workers",
    "make_mesh",
    "make_mesh_grid",
    "worker_sharding",
    "replicated_sharding",
    "WORKER_AXIS",
    "SEQ_AXIS",
    "attention",
    "ring_attention",
    "ring_attention_sharded",
    "local_attention",
]
