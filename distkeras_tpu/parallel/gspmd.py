"""GSPMD engine: windowed async-SGD with compiler-partitioned tensor
parallelism.

The reference has no tensor parallelism at all (its only strategy is the
socket-parameter-server data parallelism of ``distkeras/trainers.py`` /
``distkeras/parameter_servers.py``); SURVEY.md §2 marks TP as the idiomatic
TPU stretch goal "via pjit param sharding".  This module is that goal: a
second engine with the *same* windowed commit semantics as
:class:`~distkeras_tpu.parallel.engine.WindowedEngine`, built the pjit way
instead of the shard_map way —

  * the mesh is 2-D ``(workers, model)``;
  * per-worker state carries its leading ``[num_workers]`` axis sharded over
    ``workers`` (data parallelism), and every large parameter leaf is
    *additionally* sharded over ``model`` (tensor parallelism) via
    ``with_sharding_constraint``;
  * there is no ``shard_map`` and no hand-placed collective for TP: the
    worker dimension is a ``vmap`` with an axis name (so the commit rules'
    ``psum`` still means "sum over workers"), and XLA's SPMD partitioner
    inserts the all-gathers/reduce-scatters implied by the param shardings.

Because partitioning is sharding-propagation rather than hand-written
collectives, any model works unmodified — TP needs no ``seq_axis``-style
model surgery.  The trade: communication placement is the compiler's choice,
so the shard_map engine remains the default for pure data parallelism.

``commit_schedule`` staleness simulation works here too (same per-step
masked-commit body as the shard_map engine).  Not supported: ``seq_shards``
ring attention, which is a hand-placed-collective design by nature — use
``WindowedEngine`` for sequence parallelism.

``fsdp=True`` additionally shards the *center variable* over the workers
axis (ZeRO-3 / gather-at-use: all-gather at the window-boundary pull,
reduce-scatter after the commit psum, both placed by the partitioner) —
the replicated parameter-server copy stops costing ``num_devices x`` HBM.
Composes with ``tp_shards`` (a leaf can shard over both axes) and is a pure
layout change: trajectories equal the data-parallel run within float
tolerance (reduction order may shift under partitioning — tests/test_fsdp.py).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu import telemetry
from distkeras_tpu.algorithms.base import UpdateRule
from distkeras_tpu.models.adapter import ModelAdapter
from distkeras_tpu.parallel.engine import (
    VWORKER_AXIS,
    TrainState,
    WindowedEngine,
    plan_workers,
)
from distkeras_tpu.parallel.mesh import WORKER_AXIS

__all__ = ["GSPMDEngine", "TP_AXIS", "default_tp_dim"]

TP_AXIS = "model"


def default_tp_dim(shape, tp_shards: int):
    """The ONE default tensor-parallel placement rule, shared by every
    engine that shards over a model axis (GSPMD default spec, pipeline
    staged-leaf tails): shard the LAST dim of any >=2-D leaf that splits
    evenly and is at least two lanes per shard; return its index or None.
    Any placement is *correct* under GSPMD — this default puts matmul
    output channels (Dense/Conv kernels, embeddings) on the model axis,
    Megatron column-parallel style."""
    if (
        tp_shards > 1
        and len(shape) >= 2
        and shape[-1] % tp_shards == 0
        and shape[-1] >= 2 * tp_shards
    ):
        return len(shape) - 1
    return None


class GSPMDEngine(WindowedEngine):
    """Drop-in engine with data x tensor parallelism over a (workers, model)
    mesh.  Same public surface as :class:`WindowedEngine` (``init_state``,
    ``run_epoch``, ``shard_batches``, ``average_workers``, ...)."""

    _regather_fn = None
    _slice_fn = None

    def __init__(
        self,
        adapter: ModelAdapter,
        loss,
        worker_optimizer,
        rule: UpdateRule,
        num_workers: Optional[int] = None,
        *,
        tp_shards: int = 1,
        fsdp: bool = False,
        spec_fn=None,
        metrics: Sequence = ("accuracy",),
        compute_dtype: Optional[Any] = None,
        sync_model_state: bool = True,
        commit_schedule: Optional[np.ndarray] = None,
        devices: Optional[Sequence] = None,
        remat: bool = False,
        unroll=1,
    ):
        devices = list(devices if devices is not None else jax.devices())
        self.tp_shards = int(tp_shards)
        # ZeRO-3-style center sharding: store the center variable sharded
        # over the *workers* axis instead of replicated (center-rule state is
        # NOT constrained — every shipped rule keeps only a scalar counter
        # there).  The partitioner materialises it with an
        # all-gather at the window-boundary pull and a reduce-scatter after
        # the commit psum — gather-at-use, the idiomatic TPU form of FSDP.
        # Per-worker local state is untouched (each worker's copy is distinct
        # by construction in this algorithm family — there is no redundancy
        # over the workers axis to eliminate there).
        self.fsdp = bool(fsdp)
        # Optional placement override: shape -> PartitionSpec, or None to
        # fall through to the default Megatron-style rule.  This is how
        # expert parallelism rides this engine (models/moe.expert_partition
        # puts the leading [num_experts] axis on the model mesh axis).
        self.spec_fn = spec_fn
        if len(devices) % self.tp_shards:
            raise ValueError(
                f"tp_shards={tp_shards} does not divide device count {len(devices)}"
            )
        worker_devices = len(devices) // self.tp_shards
        self.adapter = adapter
        self.rule = rule
        self.num_workers = num_workers or worker_devices
        # Same tiling policy as the shard_map engine: largest worker-axis
        # size that divides num_workers; extra logical workers ride as
        # leading-dim shards per device.
        worker_devices, virtual = plan_workers(self.num_workers, worker_devices)
        grid = np.array(devices[: worker_devices * self.tp_shards]).reshape(
            worker_devices, self.tp_shards
        )
        self.mesh = Mesh(grid, (WORKER_AXIS, TP_AXIS))
        self.axis = WORKER_AXIS
        self.seq_axis = None
        self.seq_shards = 1
        self.n_dev, self.virtual = worker_devices, virtual
        # The worker dimension is ONE vmap over all logical workers (XLA
        # splits it across the mesh axis by sharding propagation), so the
        # commit rules' psum reduces over just the vmap axis name.
        self.both_axes = (VWORKER_AXIS,)
        self._rep = NamedSharding(self.mesh, P())
        self._shard = NamedSharding(self.mesh, P(WORKER_AXIS))
        self._finish_init(
            loss, worker_optimizer, metrics, compute_dtype,
            sync_model_state, commit_schedule, remat, unroll,
        )

    # ------------------------------------------------------------- shardings
    def _tp_spec(self, shape, path=()) -> P:
        """Shape-based TP placement: shard the last dim of any >=2-D leaf that
        splits evenly across the model axis.  Any placement is *correct* under
        GSPMD (the partitioner inserts whatever collectives the placement
        implies); this default puts matmul output channels — Dense/Conv
        kernels, embeddings — on the model axis, Megatron column-parallel
        style.  ``spec_fn(shape, path)`` overrides leaf placement first;
        ``path`` is the tuple of pytree key names so rules can match specific
        params (a bare-shape rule cannot tell an expert stack from an
        attention-heads kernel that coincidentally leads with num_experts)."""
        if self.spec_fn is not None:
            spec = self.spec_fn(tuple(shape), path)
            if spec is not None:
                for dim, name in zip(shape, spec):
                    on_model = name == TP_AXIS or (
                        isinstance(name, tuple) and TP_AXIS in name
                    )
                    if on_model and dim % self.tp_shards:
                        raise ValueError(
                            f"spec_fn placed the model axis on a dim of size "
                            f"{dim}, not divisible by tp_shards={self.tp_shards} "
                            f"(leaf shape {tuple(shape)}, path {path})"
                        )
                return spec
        # tp_shards == 1: a size-1 model axis is a layout no-op, but naming it
        # would block _center_spec from giving that dim to the workers axis
        # under fsdp — leave every dim free instead.
        dim = default_tp_dim(tuple(shape), self.tp_shards)
        if dim is not None:
            return P(*([None] * dim), TP_AXIS)
        return P()

    @staticmethod
    def _key_names(path) -> tuple:
        return tuple(
            str(getattr(k, "key", getattr(k, "idx", getattr(k, "name", k))))
            for k in path
        )

    def _center_spec(self, shape, path=()) -> P:
        """TP placement plus, under ``fsdp=True``, the workers axis on the
        largest still-free evenly-splitting dim — each device then stores
        ``1/n_dev`` of the center variable.  Leaves with no such dim stay
        replicated (correct either way; sharding is a layout choice)."""
        spec = list(self._tp_spec(shape, path))
        spec += [None] * (len(shape) - len(spec))
        taken = {
            n for entry in spec if entry is not None
            for n in (entry if isinstance(entry, tuple) else (entry,))
        }
        # A custom spec_fn may already have placed the workers axis (e.g. an
        # FSDP-style override); assigning it a second dim would be an invalid
        # PartitionSpec surfacing as an opaque partitioner error.
        if self.fsdp and self.n_dev > 1 and WORKER_AXIS not in taken:
            free = [
                d for d, name in enumerate(spec)
                if name is None and shape[d] % self.n_dev == 0
                and shape[d] >= 2 * self.n_dev
            ]
            if free:
                spec[max(free, key=lambda d: shape[d])] = WORKER_AXIS
        return P(*spec)

    def _constrain_center(self, tree):
        return jax.tree_util.tree_map_with_path(
            lambda path, x: lax.with_sharding_constraint(
                x, NamedSharding(self.mesh, self._center_spec(x.shape, self._key_names(path)))
            ),
            tree,
        )

    def _constrain_worker(self, tree):
        """Per-worker trees ([num_workers, ...] leaves): workers axis on dim 0
        plus the TP spec of the per-worker shape."""

        def strip_workers(entry):
            # spec_fn may place WORKER_AXIS (FSDP-style override) — valid for
            # center leaves, but per-worker leaves already spend the workers
            # axis on their leading dim, so it must come out of the TP spec
            if entry == WORKER_AXIS:
                return None
            if isinstance(entry, tuple):
                rest = tuple(n for n in entry if n != WORKER_AXIS)
                return rest if rest else None
            return entry

        def one(path, x):
            if x.ndim >= 1 and x.shape[0] == self.num_workers:
                tp = self._tp_spec(x.shape[1:], self._key_names(path))
                spec = P(WORKER_AXIS, *(strip_workers(e) for e in tp))
            else:
                spec = P()
            return lax.with_sharding_constraint(x, NamedSharding(self.mesh, spec))

        return jax.tree_util.tree_map_with_path(one, tree)

    # ------------------------------------------------------------------ init
    # state assembly is the base class recipe; this engine only redirects
    # the _constrain_center/_constrain_worker placement hooks (below)

    def _state_shardings(self, build_fn, params, model_state):
        # placement comes from the with_sharding_constraint calls inside
        # _assemble_state; let jit infer the outputs from those
        del build_fn, params, model_state
        return None

    # ------------------------------------------------------------------ epoch
    def _build_epoch_core(self, n_windows: int, window: int, do_commit: bool, xs_ndim: int = 5):
        """Un-jitted one-epoch function; the base class jits it directly
        (``_make_epoch_fn``) or scans it (``run_epochs``)."""
        vmapped = jax.vmap(
            self._window_fn(do_commit, window),
            in_axes=(None, None, 0, 0),
            out_axes=(0, 0, 0, 0, 0, 0) if self._dynamics else (0, 0, 0, 0, 0),
            axis_name=VWORKER_AXIS,
        )

        def epoch_fn(state: TrainState, xs, ys):
            xs = jnp.moveaxis(xs, 1, 0)  # scan over windows
            ys = jnp.moveaxis(ys, 1, 0)
            local = (state.local_params, state.opt_state, state.model_state,
                     state.rule_local, state.rng)

            def window_body(carry, wdata):
                center_params, center_rule, local = carry
                if self._dynamics:
                    centers_p, centers_r, local, loss, mets, dyn = vmapped(
                        center_params, center_rule, local, wdata
                    )
                else:
                    centers_p, centers_r, local, loss, mets = vmapped(
                        center_params, center_rule, local, wdata
                    )
                    dyn = ()
                # psum over the vmap axis makes every worker's center copy
                # identical; collapse the stacked dim and re-pin the TP
                # sharding so the scan carry stays partitioned.  The whole
                # local tuple is re-pinned: opt_state and rule_local carry
                # param-shaped leaves as large as the params themselves, and
                # an unconstrained carry would let the partitioner replicate
                # them across the model axis.
                center_params = self._constrain_center(
                    jax.tree.map(lambda x: x[0], centers_p)
                )
                center_rule = jax.tree.map(lambda x: x[0], centers_r)
                local = self._constrain_worker(local)
                return (center_params, center_rule, local), (loss, mets, dyn)

            # see the shard_map engine: unroll=True propagates to this loop
            (center_params, center_rule, local), (losses, mets, dyn) = lax.scan(
                window_body,
                (state.center_params, state.center_rule, local),
                (xs, ys), unroll=self.unroll is True,
            )
            local_params, opt_state, model_state, rule_local, rng = local
            # losses/mets carry a [n_windows, num_workers] leading block; the
            # mean over workers is a plain reduction (XLA all-reduces it).
            stats = {
                "loss": jnp.mean(losses, axis=1),
                "metrics": jnp.mean(mets, axis=1),
            }
            if self._dynamics:
                # the vmap already spans every logical worker: plain
                # reductions, no psum (the partitioner all-reduces them)
                dyn_global, dyn_worker = self._dyn_reduce(dyn)
                stats["dynamics"] = {**dyn_global, **dyn_worker}
            new_state = TrainState(
                center_params=center_params,
                center_rule=center_rule,
                local_params=local_params,
                opt_state=opt_state,
                model_state=model_state,
                rule_local=rule_local,
                rng=rng,
                epoch=state.epoch + 1,
            )
            return new_state, stats

        return epoch_fn

    def _make_stepwise_epoch_fn(self, n_steps: int, xs_ndim: int = 4):
        """Staleness simulation under TP: the same per-step masked-commit body
        as the shard_map engine, vmapped over all logical workers under jit."""
        vmapped = jax.vmap(
            self._step_fn(),
            in_axes=(None, None, 0, 0, 0, None, 0),
            out_axes=(0, 0, 0, 0, 0, 0) if self._dynamics else (0, 0, 0, 0, 0),
            axis_name=VWORKER_AXIS,
        )
        schedule_arr = jnp.asarray(self.commit_schedule, jnp.int32)

        def epoch_fn(state: TrainState, xs, ys):
            xs = jnp.moveaxis(xs, 1, 0)  # [n_steps, workers, batch, ...]
            ys = jnp.moveaxis(ys, 1, 0)
            local = (state.local_params, state.opt_state, state.model_state,
                     state.rule_local, state.rng)

            def step_body(carry, inp):
                t, batch = inp
                center_params, center_rule, local, since = carry
                if self._dynamics:
                    centers_p, centers_r, local, since, loss, dyn = vmapped(
                        center_params, center_rule, local, since, batch, t,
                        schedule_arr
                    )
                else:
                    centers_p, centers_r, local, since, loss = vmapped(
                        center_params, center_rule, local, since, batch, t,
                        schedule_arr
                    )
                    dyn = ()
                center_params = self._constrain_center(
                    jax.tree.map(lambda x: x[0], centers_p)
                )
                center_rule = jax.tree.map(lambda x: x[0], centers_r)
                local = self._constrain_worker(local)  # see windowed epoch fn
                return (center_params, center_rule, local, since), (loss, dyn)

            since0 = jnp.zeros((self.num_workers,), jnp.int32)
            (center_params, center_rule, local, _), (losses, dyn) = lax.scan(
                step_body,
                (state.center_params, state.center_rule, local, since0),
                (jnp.arange(n_steps), (xs, ys)), unroll=self.unroll,
            )
            local_params, opt_state, model_state, rule_local, rng = local
            new_state = TrainState(
                center_params=center_params,
                center_rule=center_rule,
                local_params=local_params,
                opt_state=opt_state,
                model_state=model_state,
                rule_local=rule_local,
                rng=rng,
                epoch=state.epoch + 1,
            )
            stats = {"loss": jnp.mean(losses, axis=1),
                     "metrics": jnp.zeros((0,))}
            if self._dynamics:
                dyn_global, dyn_worker = self._dyn_reduce(dyn)
                stats["dynamics"] = {**dyn_global, **dyn_worker}
            return new_state, stats

        return jax.jit(epoch_fn, donate_argnums=(0,))

    # ----------------------------------------------------------------- export
    def gather_center(self, state: TrainState):
        """Re-replicate the model-axis-sharded center leaves so every host
        process can ``np.asarray`` them (trainer finalisation, PS attach)."""
        # cached programs: a fresh jit wrapper per call would re-trace on
        # every checkpoint save / finalisation (per-call-closure trap)
        if self._regather_fn is None:
            self._regather_fn = jax.jit(lambda t: t, out_shardings=self._rep)
        with self.mesh:
            return self._regather_fn(state.center_params)

    def worker_slice(self, tree, index: int):
        # index rides along as a traced operand so one compiled program
        # serves every worker slot (a closed-over index would retrace per i)
        if self._slice_fn is None:
            self._slice_fn = jax.jit(
                lambda t, i: jax.tree.map(lambda x: x[i], t),
                out_shardings=self._rep,
            )
        with self.mesh:
            sliced = self._slice_fn(tree, index)
        return jax.tree.map(np.asarray, sliced)

    # --------------------------------------------------------------- sharding
    def shard_batches(self, xs: np.ndarray, ys: np.ndarray):
        sharding = NamedSharding(self.mesh, P(WORKER_AXIS))

        def _put():
            with self.mesh:
                return (
                    jax.make_array_from_callback(xs.shape, sharding, lambda idx: xs[idx]),
                    jax.make_array_from_callback(ys.shape, sharding, lambda idx: ys[idx]),
                )

        if not telemetry.enabled():
            return _put()
        # same honest-transfer span as the base class (blocks so the span
        # covers the copy, not just the enqueue) — parity for bench.py's
        # phase breakdown under the GSPMD engine
        with telemetry.trace.span("h2d", phase="h2d",
                                  bytes=int(xs.nbytes) + int(ys.nbytes)):
            out = _put()
            jax.block_until_ready(out)
        return out
