"""The SPMD training engine: windowed local SGD + collective commits.

This module is the TPU-native replacement for the entire runtime half of the
reference — the Spark job (``distkeras/trainers.py :: DistributedTrainer.train``
shipping pickled Workers into executors), the worker training loop
(``distkeras/workers.py :: *.train``), and the socket parameter-server service
loop (``distkeras/parameter_servers.py :: SocketParameterServer.run``).

Design (SURVEY.md §7):
  * a *worker* is a logical training replica.  Workers tile onto hardware as
    ``num_workers = n_devices x virtual_per_device``: the device dimension is
    a ``shard_map`` over the ``workers`` mesh axis, the virtual dimension a
    ``vmap`` with its own collective axis name — the TPU form of the
    reference running more Spark tasks than machines;
  * the parameter-server center variable is *replicated* across the mesh;
  * one epoch is a single jitted program: ``lax.scan`` over commit windows,
    an inner ``lax.scan`` over local optimizer steps, and the rule's
    ``commit`` — a ``psum`` over ``(vmap axis, mesh axis)`` + replicated
    center update — at each window boundary.  The reference's per-window TCP
    pull/commit round-trip becomes one XLA collective over ICI;
  * asynchrony is *modeled*: the staleness-simulation mode gives each worker
    its own commit period (per-step masked commits), reproducing parameter-
    server race semantics deterministically (SURVEY.md §7 "hard parts").

Everything is static-shaped and trace-once; there is no per-step Python.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax import lax
from jax.sharding import PartitionSpec as P

from distkeras_tpu import sanitizer as sanitizer_mod
from distkeras_tpu import telemetry
from distkeras_tpu.algorithms.base import CommitCtx, UpdateRule
from distkeras_tpu.telemetry import dynamics as dynamics_mod
from distkeras_tpu.models.adapter import ModelAdapter
from distkeras_tpu.ops import get_loss, get_metric, get_optimizer
from distkeras_tpu.parallel.mesh import (
    SEQ_AXIS,
    make_mesh,
    make_mesh_grid,
    replicated_sharding,
    worker_sharding,
)
from distkeras_tpu.utils.compat import shard_map
from distkeras_tpu.utils.pytree import tree_cast, tree_where

__all__ = ["TrainState", "WindowedEngine", "plan_workers",
           "zero_shard_dim", "zero_gather_tree"]

VWORKER_AXIS = "vworkers"


def zero_shard_dim(shape, shards: int) -> int:
    """The ONE ZeRO shard-placement policy: the largest dim of ``shape``
    that splits evenly over ``shards`` with >=2 rows per shard, or -1 to
    stay replicated.  Shared by the seq-axis fsdp (WindowedEngine) and the
    stage-axis fsdp (PipelineEngine) so the two engines — and checkpoints
    resumed across them — can never disagree on where a leaf shards."""
    free = [d for d, s in enumerate(shape)
            if s % shards == 0 and s >= 2 * shards]
    return max(free, key=lambda d: shape[d]) if free else -1


def init_on_mesh(adapter, rng, sample_input, mesh, seq_axis: str):
    """Init a seq-axis-aware model INSIDE the mesh program with the
    sample's sequence (last) axis sharded — ring-attention blocks use
    ``lax.axis_index``/``ppermute`` during their forward pass, so init
    cannot run outside ``shard_map``.  The one recipe both the windowed
    and the pipeline engine's sp paths use."""
    sample = jnp.asarray(sample_input)
    spec = P(*([None] * (sample.ndim - 1)), seq_axis)
    return shard_map(
        lambda smp: adapter.init(rng, smp),
        mesh=mesh, in_specs=(spec,), out_specs=P(), check_vma=False,
    )(sample)


def zero_gather_tree(dims, tree, axis: str):
    """Inside shard_map: materialise full leaves from their ``axis`` shards
    (gather-at-use; ``dims`` is the int-tree ``zero_shard_dim`` produced).
    ``all_gather``'s transpose is ``psum_scatter``, so differentiating
    through this hands each shard its own summed-gradient block."""
    return jax.tree.map(
        lambda d, x: x if d < 0 else lax.all_gather(x, axis, axis=d, tiled=True),
        dims, tree,
    )


def plan_workers(num_workers: int, n_devices: int) -> tuple[int, int]:
    """Tile ``num_workers`` logical workers onto hardware: returns
    ``(devices_used, virtual_per_device)`` with ``d * v == num_workers``,
    maximising the device dimension (collectives over ICI beat vmap serial
    execution whenever chips are available)."""
    d = min(num_workers, n_devices)
    while num_workers % d:
        d -= 1
    return d, num_workers // d


@struct.dataclass
class TrainState:
    """Full training state.  ``center_*`` leaves are replicated over the mesh;
    all other leaves carry a leading ``[num_workers]`` axis sharded over it."""

    center_params: Any
    center_rule: Any
    local_params: Any
    opt_state: Any
    model_state: Any
    rule_local: Any
    rng: jnp.ndarray
    epoch: jnp.ndarray  # replicated scalar


class WindowedEngine:
    """Builds and owns the jitted epoch functions for one (model, rule) pair."""

    # Mesh axes the engine's shard_map programs are *manual* over (hand-
    # placed collectives).  Empty = all axes (jax.shard_map's default).  The
    # pipeline engine under tensor parallelism sets this to (workers, stages)
    # so its third mesh axis stays *auto*: XLA's SPMD partitioner partitions
    # the stage matmuls from the state's model-axis shardings while the
    # ppermute pipeline and commit psums stay hand-written.
    _manual_axes: frozenset = frozenset()
    # seq-axis ZeRO center sharding — off unless __init__ enables it, and
    # class-level defaults keep subclasses with their own __init__ (GSPMD,
    # pipeline) on the replicated-center path.  ``fsdp`` is the public
    # "center is sharded" flag every engine exposes (GSPMD sets its own);
    # ``_fsdp_seq`` is the internal discriminator the SHARED code paths
    # (_window_fn/_step_fn/_center_in_specs) gate on, because GSPMD's fsdp
    # is partitioner-placed over the workers axis and must NOT trigger the
    # hand-placed seq-axis gathers.
    _fsdp_seq: bool = False
    _center_fsdp_dims = None
    _fsdp_regather = None
    _avg_fn = None
    _final_ms_fn = None
    fsdp: bool = False

    def __init__(
        self,
        adapter: ModelAdapter,
        loss,
        worker_optimizer,
        rule: UpdateRule,
        num_workers: Optional[int] = None,
        *,
        metrics: Sequence = ("accuracy",),
        compute_dtype: Optional[Any] = None,
        commit_schedule: Optional[np.ndarray] = None,
        sync_model_state: bool = True,
        mesh=None,
        seq_shards: int = 1,
        fsdp: bool = False,
        remat: bool = False,
        unroll=1,
    ):
        self.adapter = adapter
        self.rule = rule
        self.seq_shards = int(seq_shards)
        # ZeRO-style center sharding over the SEQ axis (fsdp x sp in one
        # mesh): on the (workers, seq) grid the center variable is otherwise
        # replicated seq_shards x — pure redundancy, since the seq axis
        # exists for activations.  With fsdp=True each seq-row device stores
        # 1/seq_shards of every evenly-splitting center leaf; the window
        # commit all-gathers the shards at use and re-slices after (the
        # hand-placed-collective form of the GSPMD engine's gather-at-use
        # fsdp — trajectory-identical to the replicated layout).  fsdp
        # without sequence parallelism is the GSPMD engine's job.
        self._fsdp_seq = bool(fsdp)
        self.fsdp = self._fsdp_seq
        if self._fsdp_seq and self.seq_shards <= 1:
            raise ValueError(
                "fsdp=True on WindowedEngine shards the center over the seq "
                "axis and needs seq_shards>1; for fsdp without sequence "
                "parallelism use the GSPMD engine (trainers route it there)"
            )
        n_devices = jax.device_count() if mesh is None else mesh.devices.size
        if self.seq_shards > 1:
            # combined data x sequence parallelism: 2-D mesh, worker state on
            # axis 0, sequence blocks on axis 1 (requires a seq-axis-aware
            # model, e.g. TransformerClassifier(seq_axis='seq'))
            worker_devices = n_devices // self.seq_shards
            self.num_workers = num_workers or worker_devices
            self.n_dev, self.virtual = plan_workers(self.num_workers, worker_devices)
            self.mesh = make_mesh_grid(self.n_dev, self.seq_shards)
            self.seq_axis = SEQ_AXIS
        else:
            self.num_workers = num_workers or n_devices
            self.n_dev, self.virtual = plan_workers(self.num_workers, n_devices)
            self.mesh = (
                mesh
                if (mesh is not None and mesh.devices.size == self.n_dev)
                else make_mesh(self.n_dev)
            )
            self.seq_axis = None
        self.axis = self.mesh.axis_names[0]
        self.both_axes = (VWORKER_AXIS, self.axis)
        self._rep = replicated_sharding(self.mesh)
        self._shard = worker_sharding(self.mesh)
        self._finish_init(
            loss, worker_optimizer, metrics, compute_dtype,
            sync_model_state, commit_schedule, remat, unroll,
        )

    def _finish_init(
        self, loss, worker_optimizer, metrics, compute_dtype,
        sync_model_state, commit_schedule, remat=False, unroll=1,
    ):
        """Mesh-independent setup shared with subclasses (GSPMDEngine):
        optimizer/loss/metric resolution and commit-schedule validation.
        Requires ``self.adapter`` and ``self.num_workers`` to be set."""
        self.optimizer = get_optimizer(worker_optimizer)
        self.loss_fn = get_loss(loss, from_logits=self.adapter.outputs_logits)
        if getattr(self.adapter, "per_token_labels", False):
            from distkeras_tpu.ops.metrics import per_token_metric_names

            metrics = per_token_metric_names(metrics)
        self.metric_fns = [get_metric(m) for m in metrics]
        self.compute_dtype = compute_dtype
        # Rematerialise the forward pass on the backward (jax.checkpoint):
        # trades FLOPs for activation memory — the HBM lever for deep models
        # (ResNet-scale+) whose per-window activations outgrow the chip.
        self.remat = bool(remat)
        # Unroll factor for the per-step scans (int, or True = full unroll).
        # On TPU a small unroll lets XLA pipeline across steps; on the CPU
        # test mesh full unroll avoids XLA:CPU's pathological compile times
        # for conv bodies inside while-loops (measured: a 4-step scanned
        # CIFARCNN step compiles ~75s as a loop, ~5s fully unrolled).
        self.unroll = unroll
        self.sync_model_state = sync_model_state
        # Per-worker commit periods (staleness simulation).  None => uniform
        # synchronous windows, one collective per window.
        self.commit_schedule = (
            None if commit_schedule is None else np.asarray(commit_schedule, np.int32)
        )
        if self.commit_schedule is not None and len(self.commit_schedule) != self.num_workers:
            raise ValueError(
                f"commit_schedule has {len(self.commit_schedule)} entries for "
                f"{self.num_workers} workers"
            )
        # Training-dynamics stats (telemetry.dynamics).  Resolved ONCE at
        # engine build so the trace-time branches in the window/step bodies
        # are stable for the life of the cached epoch programs; with the
        # flag off not a single extra op is traced — the jitted program is
        # identical to a build without the feature (pinned in
        # tests/test_dynamics.py).
        self._dynamics = dynamics_mod.enabled()
        # Runtime sanitizer (distkeras_tpu.sanitizer), same convention: one
        # cached bool read at build, zero per-dispatch cost when off and
        # byte-identical lowered programs either way (the guards are pure
        # host-side wrappers — pinned in tests/test_sanitizer.py).
        self._sanitize = sanitizer_mod.enabled()
        self._epoch_fns = {}
        #: filled by :meth:`run_epoch_streaming`: source/transfer timing and
        #: the link-bound verdict for the last streamed epoch (bench reads it)
        self.last_stream_report = None
        self._link_warned = False

    # ------------------------------------------------------------------ init
    def init_state(self, rng: jax.Array, sample_input) -> TrainState:
        if self.seq_axis is not None:
            params, model_state = init_on_mesh(
                self.adapter, rng, sample_input, self.mesh, self.seq_axis
            )
        else:
            params, model_state = self.adapter.init(rng, sample_input)
        self._record_fsdp_dims(params)

        def _build(params, model_state):
            return self._assemble_state(rng, params, model_state)

        shardings = self._state_shardings(_build, params, model_state)
        with self.mesh:
            return jax.jit(_build, out_shardings=shardings)(params, model_state)

    # ---------------------------------------------- fsdp (seq-axis ZeRO center)
    def _record_fsdp_dims(self, params):
        """Choose, per center leaf, which dim the seq axis shards: the
        largest dim that splits evenly with >=2 rows per shard, or -1 to
        stay replicated (a tree of ints — ``None`` is not a pytree leaf).
        Recorded from the real param shapes at ``init_state`` /
        ``state_from_center``; every later spec/gather/slice reads this one
        table so block-shape recomputation can never pick a different dim."""
        if not self._fsdp_seq:
            return
        self._center_fsdp_dims = jax.tree.map(
            lambda x: zero_shard_dim(np.shape(x), self.seq_shards), params
        )
        if all(d < 0 for d in jax.tree.leaves(self._center_fsdp_dims)):
            # fsdp=True with nothing shardable would silently store the
            # full center replicated — exactly the HBM redundancy the flag
            # exists to remove.  Say so instead of OOMing mysteriously.
            import warnings

            warnings.warn(
                f"fsdp=True: no center leaf has a dim divisible by "
                f"seq_shards={self.seq_shards} (with >=2 rows per shard); "
                "the center stays fully replicated", stacklevel=3,
            )

    def _fsdp_leaf_spec(self, d) -> P:
        return P() if d < 0 else P(*([None] * d), SEQ_AXIS)

    def _fsdp_center_specs(self):
        if self._center_fsdp_dims is None:
            raise RuntimeError(
                "fsdp=True center placement is recorded from the param "
                "shapes; build the state via init_state/state_from_center "
                "before running epochs"
            )
        return jax.tree.map(self._fsdp_leaf_spec, self._center_fsdp_dims)

    def _fsdp_gather(self, tree):
        """Inside shard_map: materialise the full center from its seq-axis
        shards (gather-at-use, the window-commit analogue of ZeRO-3's
        pre-layer all-gather)."""
        if not self._fsdp_seq:
            return tree
        return zero_gather_tree(self._center_fsdp_dims, tree, SEQ_AXIS)

    def _fsdp_shard(self, tree):
        """Inside shard_map: keep only this seq-row's block of the updated
        center (the commit math ran full-size; storage goes back to
        1/seq_shards)."""
        if not self._fsdp_seq:
            return tree
        idx = lax.axis_index(SEQ_AXIS)

        def one(d, x):
            if d < 0:
                return x
            block = x.shape[d] // self.seq_shards
            return lax.dynamic_slice_in_dim(x, idx * block, block, axis=d)

        return jax.tree.map(one, self._center_fsdp_dims, tree)

    def _constrain_center(self, tree):
        """Placement hook for center leaves inside state assembly — identity
        unless seq-axis fsdp is on (then each leaf pins to its recorded
        seq-shard layout); the GSPMD engine overrides it with TP/fsdp
        sharding constraints."""
        if not self._fsdp_seq:
            return tree
        from jax.sharding import NamedSharding

        return jax.tree.map(
            lambda d, x: lax.with_sharding_constraint(
                x, NamedSharding(self.mesh, self._fsdp_leaf_spec(d))),
            self._center_fsdp_dims, tree,
        )

    def _constrain_worker(self, tree):
        """Placement hook for per-worker ``[num_workers, ...]`` leaves —
        identity here; GSPMD adds workers-axis + TP constraints."""
        return tree

    def _assemble_state(self, rng, params, model_state) -> TrainState:
        """Pure state assembly (jittable): tile per-worker leaves, init the
        optimizer and rule states.  The single recipe for every engine —
        subclasses redirect placement via the ``_constrain_*`` hooks."""
        n = self.num_workers
        params = self._constrain_center(params)
        center_rule = self.rule.init_center_state()
        rule_local = self.rule.init_local_state(params)
        tile = lambda t: jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), t
        )
        local_params = self._constrain_worker(tile(params))
        opt_state = self._constrain_worker(jax.vmap(self.optimizer.init)(local_params))
        rngs = jax.random.split(jax.random.fold_in(rng, 1), n)
        return TrainState(
            center_params=params,
            center_rule=center_rule,
            local_params=local_params,
            opt_state=opt_state,
            model_state=self._constrain_worker(tile(model_state)),
            rule_local=self._constrain_worker(tile(rule_local)),
            rng=rngs,
            epoch=jnp.zeros((), jnp.int32),
        )

    def state_from_center(
        self, rng: jax.Array, center_params, center_rule, model_state, epoch
    ) -> TrainState:
        """Elastic resume: rebuild full training state around a restored
        center variable at THIS engine's worker count (which may differ from
        the count the checkpoint was written at).

        Local replicas adopt the center — the semantics of the reference's
        worker retry, which reconnects to the PS and pulls
        (``distkeras/workers.py``; SURVEY.md §5.3 "a retried worker
        reconnects and keeps training") — optimizer and rule local state
        re-initialise, and the center-side rule state (commit counters) and
        epoch survive.  Exact same-count resume should use the bitwise
        checkpoint restore instead (``CheckpointManager.restore(like=...)``).
        """
        # host trees go straight into the jitted build: jit places the args
        # under their constrained shardings in one transfer (an eager
        # asarray here would first materialise the full center replicated
        # on one device — the spike fsdp exists to avoid)
        self._record_fsdp_dims(center_params)

        def _build(params, ms):
            st = self._assemble_state(rng, params, ms)
            return st.replace(
                center_rule=center_rule,
                epoch=jnp.asarray(epoch, jnp.int32),
            )

        shardings = self._state_shardings(_build, center_params, model_state)
        with self.mesh:
            return jax.jit(_build, out_shardings=shardings)(center_params, model_state)

    def _state_shardings(self, build_fn, params, model_state):
        """out_shardings for the initial state: center leaves replicated,
        per-worker leaves split on the worker axis.  The pipeline engine
        overrides this with per-leaf shardings (stage-stacked leaves shard
        over the stages axis too)."""
        del build_fn, params, model_state
        center = self._rep
        if self._fsdp_seq:
            from jax.sharding import NamedSharding

            center = jax.tree.map(
                lambda d: NamedSharding(self.mesh, self._fsdp_leaf_spec(d)),
                self._center_fsdp_dims,
            )
        return TrainState(
            center_params=center,
            center_rule=self._rep,
            local_params=self._shard,
            opt_state=self._shard,
            model_state=self._shard,
            rule_local=self._shard,
            rng=self._shard,
            epoch=self._rep,
        )

    # ------------------------------------------------------------- local step
    def _local_step(self, carry, batch):
        params, opt_state, model_state, rng = carry
        rng, sub = jax.random.split(rng)
        x, y = batch

        def compute_loss(p, ms):
            if self.compute_dtype is not None:
                p = tree_cast(p, self.compute_dtype)
                x_c = x.astype(self.compute_dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x
            else:
                x_c = x
            out, new_ms = self.adapter.apply(p, ms, x_c, training=True, rng=sub)
            out = out.astype(jnp.float32)
            loss = self.loss_fn(out, y) + self.adapter.aux_loss(new_ms)
            mets = (
                jnp.stack([m(out, y) for m in self.metric_fns])
                if self.metric_fns
                else jnp.zeros((0,), jnp.float32)
            )
            return loss, (new_ms, mets)

        if self.remat:
            compute_loss = jax.checkpoint(compute_loss)
        (loss, (model_state, mets)), grads = jax.value_and_grad(compute_loss, has_aux=True)(
            params, model_state
        )
        grads = self._sync_grads(grads)
        if self._dynamics:
            # per-step health leaves ride the scan ys; reduced to per-window
            # scalars in the window body (no per-step collective)
            dstep = {
                "grad_sq": dynamics_mod.tree_sq_norm(grads),
                "grad_nonfinite": dynamics_mod.tree_nonfinite_count(grads),
            }
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if self._dynamics:
            return (params, opt_state, model_state, rng), (loss, mets, dstep)
        return (params, opt_state, model_state, rng), (loss, mets)

    def _sync_grads(self, grads):
        """Cross-model-axis gradient sync hook (worker-axis reduction is the
        commit rules' job, not this one's).

        Sequence parallelism: each shard's backward pass yields seq_shards x
        (its partial gradient): the loss is computed replicated on every shard
        and psum's transpose inside shard_map is itself a psum, so every
        replica's cotangent lands on each shard.  pmean over the axis =
        psum(partials)/shards = the exact total gradient (verified against
        the unsharded model in tests/test_sequence_parallel.py).

        The pipeline engine overrides this with its stage-axis sync
        (:meth:`distkeras_tpu.parallel.pipeline.PipelineEngine._sync_grads`).
        """
        if self.seq_axis is not None:
            grads = jax.tree.map(lambda g: lax.pmean(g, self.seq_axis), grads)
        return grads

    def _local_in_spec(self):
        """shard_map spec (or per-leaf spec tree) for the per-worker ``local``
        5-tuple.  A single ``P(workers)`` prefix here; the pipeline engine
        returns full per-leaf trees (stage-stacked leaves shard over the
        stages axis too)."""
        return P(self.axis)

    def _center_in_specs(self):
        """shard_map specs (or per-leaf spec trees) for
        ``(center_params, center_rule)`` — replicated here (per-leaf
        seq-shard specs under fsdp); the pipeline engine shards
        stage-stacked center leaves over the stages axis."""
        if self._fsdp_seq:
            return self._fsdp_center_specs(), P()
        return P(), P()

    def _make_ctx(self, mask, steps_in_window) -> CommitCtx:
        """Commit context whose psum totals over BOTH the vmap (virtual
        worker) axis and the mesh (device) axis."""
        psum = lambda t: jax.tree.map(lambda v: lax.psum(v, self.both_axes), t)
        return CommitCtx(
            psum=psum,
            mask=jnp.asarray(mask),
            steps_in_window=jnp.asarray(steps_in_window, jnp.float32),
            num_workers=self.num_workers,
        )

    def _sync_model_state(self, ctx: CommitCtx, model_state):
        if not self.sync_model_state or not jax.tree.leaves(model_state):
            return model_state
        mean = jax.tree.map(lambda x: ctx.psum(x) / self.num_workers, model_state)
        return tree_where(ctx.mask, mean, model_state)

    @property
    def _per_token(self) -> bool:
        """Model emits per-token outputs with per-token labels (LMs —
        ``ModelAdapter.per_token_labels``): labels shard over the seq axis
        with the tokens, and per-shard loss/metric values are block-local
        (not replicated) so epoch stats need a seq-axis mean."""
        return bool(self.adapter.per_token_labels)

    def _reduce_seq_stats(self, *stats):
        """Average block-local stats over the seq axis (no-op when outputs
        are already replicated across it — the classifier's psum-pooled
        logits)."""
        if self.seq_axis is not None and self._per_token:
            stats = tuple(lax.pmean(s, self.seq_axis) for s in stats)
        return stats if len(stats) > 1 else stats[0]

    def _data_specs(self, xs_ndim: int):
        """Partition specs for (xs, ys): worker axis leading; for sequence
        parallelism the sequence (last) axis of xs also shards — and so do
        the labels when the model declares them per-token (language models:
        labels mirror the token array, each shard keeps its block's
        targets)."""
        if self.seq_axis is not None:
            xs_spec = P(self.axis, *([None] * (xs_ndim - 2)), self.seq_axis)
            return xs_spec, (xs_spec if self._per_token else P(self.axis))
        return P(self.axis), P(self.axis)

    def _window_fn(self, do_commit: bool, window: int):
        """Build the one-worker window body: inner scan of local steps, then
        commit.  Runs under ``vmap(axis_name=VWORKER_AXIS)`` — inside
        ``shard_map`` here, or under plain jit in the GSPMD engine."""
        rule = self.rule

        def per_worker_window(center_params, center_rule, local, wdata):
            local_params, opt_state, model_state, rule_local, rng = local
            if self._dynamics:
                (local_params, opt_state, model_state, rng), (losses, mets, dstep) = lax.scan(
                    self._local_step, (local_params, opt_state, model_state, rng),
                    wdata, unroll=self.unroll,
                )
            else:
                (local_params, opt_state, model_state, rng), (losses, mets) = lax.scan(
                    self._local_step, (local_params, opt_state, model_state, rng),
                    wdata, unroll=self.unroll,
                )
            dyn = None
            if self._dynamics:
                # pre-commit snapshot: worker<->center drift and the rule's
                # own staleness clocks, measured before the commit rewrites
                # them.  All worker-local scalars — the end-of-epoch psum
                # reduces them with the loss (no extra collective here).
                full_center = self._fsdp_gather(center_params)
                dyn = {
                    "grad_sq": jnp.sum(dstep["grad_sq"]),
                    "nonfinite_grads": jnp.sum(dstep["grad_nonfinite"]),
                    "nonfinite_params": dynamics_mod.tree_nonfinite_count(local_params),
                    "divergence_sq": dynamics_mod.tree_sq_dist(local_params, full_center),
                    "staleness": jnp.asarray(float(window), jnp.float32),
                    "update_sq": jnp.zeros((), jnp.float32),
                }
                dyn.update(rule.dynamics(
                    self._make_ctx(do_commit, float(window)),
                    local_params, full_center, rule_local, center_rule,
                ))
            if do_commit:
                # seq-axis fsdp: the commit is the one place the full center
                # is needed — gather the shards at use, run the rule's math
                # unchanged (so trajectories match the replicated layout
                # exactly), keep only this row's block after
                center_params = (full_center if self._dynamics
                                 else self._fsdp_gather(center_params))
                center_before = center_params
                ctx = self._make_ctx(True, float(window))
                res = rule.commit(ctx, local_params, center_params, rule_local, center_rule)
                local_params, center_params = res.local_params, res.center_params
                rule_local, center_rule = res.local_state, res.center_state
                if self._dynamics:
                    dyn["update_sq"] = dynamics_mod.tree_sq_dist(
                        center_params, center_before)
                center_params = self._fsdp_shard(center_params)
                model_state = self._sync_model_state(ctx, model_state)
            # Window stats stay worker-local here; one psum at the end of the
            # epoch reduces them (a per-window collective in the scan body
            # would serialise every window on the slowest device).
            loss_mean = jnp.mean(losses)
            mets_mean = jnp.mean(mets, axis=0)
            local = (local_params, opt_state, model_state, rule_local, rng)
            if self._dynamics:
                return center_params, center_rule, local, loss_mean, mets_mean, dyn
            return center_params, center_rule, local, loss_mean, mets_mean

        return per_worker_window

    def _dyn_reduce(self, dyn, psum_axis=None):
        """Reduce stacked dynamics leaves ``[T, v]`` (T windows or steps,
        v workers in this trace) to the epoch-stats layout: *global* series
        — grad norm, non-finite counts, center update norm, each ``[T]`` —
        and *per-worker* series (divergence, staleness, rule extras), each
        ``[T, v]``.  ``psum_axis`` totals the global leaves across mesh
        devices (the windowed engine calls inside shard_map); the GSPMD
        engine's vmap already spans every worker and passes None."""
        total = (lambda a: jnp.sum(a, axis=1)) if psum_axis is None else (
            lambda a: lax.psum(jnp.sum(a, axis=1), psum_axis))
        dyn = dict(dyn)
        dyn_global = {
            "grad_norm": jnp.sqrt(total(dyn.pop("grad_sq"))),
            # the committed center is identical across workers (psum'd):
            # any column of the stacked leaf is the global value
            "update_norm": jnp.sqrt(dyn.pop("update_sq")[:, 0]),
            "nonfinite_grads": total(dyn.pop("nonfinite_grads")),
            "nonfinite_params": total(dyn.pop("nonfinite_params")),
        }
        dyn_worker = dict(dyn)
        dyn_worker["divergence"] = jnp.sqrt(dyn_worker.pop("divergence_sq"))
        return dyn_global, dyn_worker

    # ------------------------------------------------------- epoch (windowed)
    def _build_epoch_core(self, n_windows: int, window: int, do_commit: bool, xs_ndim: int = 5):
        """The un-jitted one-epoch function ``(state, xs, ys) -> (state, stats)``.

        ``_make_epoch_fn`` jits it directly; ``_make_multi_epoch_fn`` scans it
        so a whole training run is ONE dispatch (see :meth:`run_epochs`)."""
        vmapped = jax.vmap(
            self._window_fn(do_commit, window),
            in_axes=(None, None, 0, 0),
            out_axes=(0, 0, 0, 0, 0, 0) if self._dynamics else (0, 0, 0, 0, 0),
            axis_name=VWORKER_AXIS,
        )

        def worker_fn(center_params, center_rule, local, xs, ys):
            # block shapes: local leaves [v, ...]; xs [v, n_windows, window, batch, ...]
            xs = jnp.moveaxis(xs, 1, 0)  # scan over windows
            ys = jnp.moveaxis(ys, 1, 0)

            def window_body(carry, wdata):
                center_params, center_rule, local = carry
                if self._dynamics:
                    centers_p, centers_r, local, loss, mets, dyn = vmapped(
                        center_params, center_rule, local, wdata
                    )
                else:
                    centers_p, centers_r, local, loss, mets = vmapped(
                        center_params, center_rule, local, wdata
                    )
                    dyn = ()
                # psum over both axes makes every virtual worker's center
                # identical; collapse the vmap dim.
                center_params = jax.tree.map(lambda x: x[0], centers_p)
                center_rule = jax.tree.map(lambda x: x[0], centers_r)
                return (center_params, center_rule, local), (loss, mets, dyn)

            # full unroll propagates to the window loop too (unroll=True is
            # the XLA:CPU compile-time escape hatch; ints stay step-only)
            (center_params, center_rule, local), (losses, mets, dyn) = lax.scan(
                window_body, (center_params, center_rule, local), (xs, ys),
                unroll=self.unroll is True,
            )
            # losses: [n_windows, v]; mets: [n_windows, v, M].  Single
            # end-of-epoch reduction over virtual workers + mesh devices.
            losses = lax.psum(jnp.sum(losses, axis=1), self.axis) / self.num_workers
            mets = lax.psum(jnp.sum(mets, axis=1), self.axis) / self.num_workers
            losses, mets = self._reduce_seq_stats(losses, mets)
            if self._dynamics:
                dyn_global, dyn_worker = self._dyn_reduce(dyn, self.axis)
                return (center_params, center_rule, local, losses, mets,
                        dyn_global, dyn_worker)
            return center_params, center_rule, local, losses, mets

        xs_spec, ys_spec = self._data_specs(xs_ndim)
        center_spec, center_rule_spec = self._center_in_specs()
        local_spec = self._local_in_spec()
        # dynamics outputs: globals replicated (post-psum), per-worker series
        # concatenate over the worker axis — [n_windows, num_workers] global
        dyn_out_specs = (P(), P(None, self.axis)) if self._dynamics else ()
        mapped = shard_map(
            worker_fn,
            mesh=self.mesh,
            in_specs=(center_spec, center_rule_spec, local_spec, xs_spec, ys_spec),
            out_specs=(center_spec, center_rule_spec, local_spec, P(), P())
            + dyn_out_specs,
            check_vma=False,
            **({"axis_names": self._manual_axes} if self._manual_axes else {}),
        )

        def epoch_fn(state: TrainState, xs, ys):
            local = (state.local_params, state.opt_state, state.model_state,
                     state.rule_local, state.rng)
            if self._dynamics:
                (center_params, center_rule, local, losses, mets,
                 dyn_global, dyn_worker) = mapped(
                    state.center_params, state.center_rule, local, xs, ys
                )
            else:
                center_params, center_rule, local, losses, mets = mapped(
                    state.center_params, state.center_rule, local, xs, ys
                )
            local_params, opt_state, model_state, rule_local, rng = local
            new_state = TrainState(
                center_params=center_params,
                center_rule=center_rule,
                local_params=local_params,
                opt_state=opt_state,
                model_state=model_state,
                rule_local=rule_local,
                rng=rng,
                epoch=state.epoch + 1,
            )
            stats = {"loss": losses, "metrics": mets}
            if self._dynamics:
                stats["dynamics"] = {**dyn_global, **dyn_worker}
            return new_state, stats

        return epoch_fn

    def _make_epoch_fn(self, n_windows: int, window: int, do_commit: bool, xs_ndim: int = 5):
        return jax.jit(
            self._build_epoch_core(n_windows, window, do_commit, xs_ndim),
            donate_argnums=(0,),
        )

    def _make_multi_epoch_fn(
        self, n_windows: int, window: int, do_commit: bool, xs_ndim: int,
        n_epochs: int, shuffle_seed: Optional[int],
    ):
        """N epochs as ONE jitted program: ``lax.scan`` over the epoch core.

        Dispatching per epoch pays a fixed host/runtime cost per call (~13%
        of epoch wall time for the headline bench config, measured on TPU
        v5e through the axon tunnel — the device-side trace shows epochs
        executing back-to-back, so the gap is pure dispatch).  Scanning the
        epoch body amortises that cost over the whole run.

        With ``shuffle_seed`` set, each epoch draws a fresh ON-DEVICE global
        permutation of the flattened step stream (workers x windows x window
        x batch), keyed by the epoch counter so the permutation stream
        survives checkpoint/resume.  The reference reshuffles by Spark
        ``shuffle()`` between epochs (SURVEY.md §3.1) — a full cluster
        round-trip; here it is a single on-device gather.  One deliberate
        difference from the host-side reshuffle (``data.epoch_arrays``): the
        permutation acts on the padded stream, so when the dataset does not
        divide workers x batch x window evenly, the *same* wrap-pad
        duplicates recur every epoch (the host path re-draws them).  Pad a
        divisible dataset — or use ``Trainer.train``'s host loop — when that
        bias matters.
        """
        epoch_core = self._build_epoch_core(n_windows, window, do_commit, xs_ndim)

        def multi_fn(state: TrainState, xs, ys):
            def shuffled(epoch_key, xs, ys):
                sample_shape = xs.shape[4:]
                n_total = int(np.prod(xs.shape[:4]))
                perm = jax.random.permutation(epoch_key, n_total)
                xs_s = xs.reshape((n_total,) + sample_shape)[perm].reshape(xs.shape)
                ys_s = ys.reshape((n_total,) + ys.shape[4:])[perm].reshape(ys.shape)
                return xs_s, ys_s

            def body(st, epoch_key):
                if shuffle_seed is not None:
                    xs_e, ys_e = shuffled(epoch_key, xs, ys)
                else:
                    xs_e, ys_e = xs, ys
                st, stats = epoch_core(st, xs_e, ys_e)
                return st, stats

            keys = (
                jax.vmap(lambda e: jax.random.fold_in(jax.random.PRNGKey(shuffle_seed), e))(
                    state.epoch + jnp.arange(n_epochs)
                )
                if shuffle_seed is not None
                else jnp.zeros((n_epochs, 2), jnp.uint32)
            )
            state, stats = lax.scan(body, state, keys)
            # stats leaves are stacked [n_epochs, ...]; flatten the epoch dim
            # into the existing per-window/per-metric leading dim so shapes
            # match ``n_epochs`` sequential run_epoch calls concatenated.
            # (Explicit sizes, not -1: metrics leaves can be zero-size.)
            stats = jax.tree.map(
                lambda a: a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:]), stats
            )
            return state, stats

        return jax.jit(multi_fn, donate_argnums=(0,))

    def _step_fn(self):
        """Build the one-worker masked-commit step body (staleness-sim mode).
        Runs under ``vmap(axis_name=VWORKER_AXIS)`` — inside ``shard_map``
        here, or under plain jit in the GSPMD engine."""
        rule = self.rule

        def per_worker_step(center_params, center_rule, local, since, batch, t, my_window):
            local_params, opt_state, model_state, rule_local, rng = local
            if self._dynamics:
                (local_params, opt_state, model_state, rng), (loss, _, dstep) = self._local_step(
                    (local_params, opt_state, model_state, rng), batch
                )
            else:
                (local_params, opt_state, model_state, rng), (loss, _) = self._local_step(
                    (local_params, opt_state, model_state, rng), batch
                )
            since = since + 1
            mask = (t + 1) % my_window == 0
            ctx = self._make_ctx(mask, 1.0)
            ctx = ctx._replace(steps_in_window=since.astype(jnp.float32))
            # seq-axis fsdp: gather-at-use around the masked commit (a
            # masked-off step updates nothing, so gather->slice is identity)
            center_params = self._fsdp_gather(center_params)
            dyn = None
            if self._dynamics:
                # effective staleness is the live counter itself: steps
                # since this worker's last (masked) commit
                dyn = {
                    "grad_sq": dstep["grad_sq"],
                    "nonfinite_grads": dstep["grad_nonfinite"],
                    "nonfinite_params": dynamics_mod.tree_nonfinite_count(local_params),
                    "divergence_sq": dynamics_mod.tree_sq_dist(local_params, center_params),
                    "staleness": since.astype(jnp.float32),
                    "update_sq": jnp.zeros((), jnp.float32),
                }
                dyn.update(rule.dynamics(
                    ctx, local_params, center_params, rule_local, center_rule))
            center_before = center_params
            res = rule.commit(ctx, local_params, center_params, rule_local, center_rule)
            local_params, center_params = res.local_params, res.center_params
            rule_local, center_rule = res.local_state, res.center_state
            if self._dynamics:
                dyn["update_sq"] = dynamics_mod.tree_sq_dist(
                    center_params, center_before)
            center_params = self._fsdp_shard(center_params)
            model_state = self._sync_model_state(ctx, model_state)
            since = jnp.where(mask, 0, since)
            local = (local_params, opt_state, model_state, rule_local, rng)
            if self._dynamics:
                return center_params, center_rule, local, since, loss, dyn
            return center_params, center_rule, local, since, loss

        return per_worker_step

    # ---------------------------------------------- epoch (staleness-sim mode)
    def _make_stepwise_epoch_fn(self, n_steps: int, xs_ndim: int = 4):
        """Per-step masked commits with a per-worker commit period: the
        faithful deterministic model of parameter-server asynchrony."""
        vmapped = jax.vmap(
            self._step_fn(),
            in_axes=(None, None, 0, 0, 0, None, 0),
            out_axes=(0, 0, 0, 0, 0, 0) if self._dynamics else (0, 0, 0, 0, 0),
            axis_name=VWORKER_AXIS,
        )

        def worker_fn(center_params, center_rule, local, xs, ys, schedule):
            # xs: [v, n_steps, batch, ...]
            xs = jnp.moveaxis(xs, 1, 0)
            ys = jnp.moveaxis(ys, 1, 0)
            schedule = schedule.reshape(-1)  # [v]

            def step_body(carry, inp):
                t, batch = inp
                center_params, center_rule, local, since = carry
                if self._dynamics:
                    centers_p, centers_r, local, since, loss, dyn = vmapped(
                        center_params, center_rule, local, since, batch, t, schedule
                    )
                else:
                    centers_p, centers_r, local, since, loss = vmapped(
                        center_params, center_rule, local, since, batch, t, schedule
                    )
                    dyn = ()
                center_params = jax.tree.map(lambda x: x[0], centers_p)
                center_rule = jax.tree.map(lambda x: x[0], centers_r)
                return (center_params, center_rule, local, since), (loss, dyn)

            since0 = jnp.zeros((schedule.shape[0],), jnp.int32)
            (center_params, center_rule, local, _), (losses, dyn) = lax.scan(
                step_body, (center_params, center_rule, local, since0),
                (jnp.arange(n_steps), (xs, ys)), unroll=self.unroll,
            )
            # losses: [n_steps, v] — one end-of-epoch reduction (see the
            # windowed epoch fn for why this is not done per step).
            losses = lax.psum(jnp.sum(losses, axis=1), self.axis) / self.num_workers
            losses = self._reduce_seq_stats(losses)
            if self._dynamics:
                dyn_global, dyn_worker = self._dyn_reduce(dyn, self.axis)
                return (center_params, center_rule, local, losses,
                        dyn_global, dyn_worker)
            return center_params, center_rule, local, losses

        xs_spec, ys_spec = self._data_specs(xs_ndim)
        center_spec, center_rule_spec = self._center_in_specs()
        local_spec = self._local_in_spec()
        dyn_out_specs = (P(), P(None, self.axis)) if self._dynamics else ()
        mapped = shard_map(
            worker_fn,
            mesh=self.mesh,
            in_specs=(center_spec, center_rule_spec, local_spec, xs_spec, ys_spec,
                      P(self.axis)),
            out_specs=(center_spec, center_rule_spec, local_spec, P())
            + dyn_out_specs,
            check_vma=False,
            **({"axis_names": self._manual_axes} if self._manual_axes else {}),
        )

        schedule_arr = jnp.asarray(self.commit_schedule, jnp.int32)

        def epoch_fn(state: TrainState, xs, ys):
            local = (state.local_params, state.opt_state, state.model_state,
                     state.rule_local, state.rng)
            if self._dynamics:
                (center_params, center_rule, local, losses,
                 dyn_global, dyn_worker) = mapped(
                    state.center_params, state.center_rule, local, xs, ys,
                    schedule_arr
                )
            else:
                center_params, center_rule, local, losses = mapped(
                    state.center_params, state.center_rule, local, xs, ys,
                    schedule_arr
                )
            local_params, opt_state, model_state, rule_local, rng = local
            new_state = TrainState(
                center_params=center_params,
                center_rule=center_rule,
                local_params=local_params,
                opt_state=opt_state,
                model_state=model_state,
                rule_local=rule_local,
                rng=rng,
                epoch=state.epoch + 1,
            )
            stats = {"loss": losses, "metrics": jnp.zeros((0,))}
            if self._dynamics:
                stats["dynamics"] = {**dyn_global, **dyn_worker}
            return new_state, stats

        return jax.jit(epoch_fn, donate_argnums=(0,))

    # ----------------------------------------------------------------- public
    def _dispatch(self, fn, state, xs, ys):
        """Dispatch one donating epoch program.

        With ``DISTKERAS_SANITIZE`` on, the dispatch (including any cache-miss
        trace) runs under the sanitizer's transfer guard — a host sync hidden
        in the hot loop raises in strict mode, naming the enclosing telemetry
        span — and the donated input state is poisoned afterwards so a stale
        read fails on every backend, not just where donation really aliases
        (DK101/DK103's runtime twins)."""
        if not self._sanitize:
            return fn(state, xs, ys)
        from distkeras_tpu.sanitizer import donation, transfer

        with transfer.guard("epoch_dispatch"):
            out = fn(state, xs, ys)
        donation.poison(state, label="epoch state (donate_argnums=0)")
        return out

    def _dispatch_with_spans(self, fn, state, xs, ys, n_windows):
        """Telemetry-enabled dispatch: wrap the (normally fully async) epoch
        program in window/step/commit spans.

        Phase attribution needs host-visible completion points, so this path
        blocks on the dispatch outputs — trading async-dispatch overlap for
        observability.  The trajectory is unchanged (same program, same
        inputs; asserted in tests/test_telemetry.py).  "step" covers dispatch
        through loss readiness; "commit" is the residual wait for the
        committed center params after the losses are already on host — with
        one fused XLA program that residual is usually small, which is itself
        the measurement.  Only ever called with telemetry enabled; the
        disabled path dispatches directly with zero added syncs."""
        with telemetry.trace.span("window", windows=n_windows):
            with telemetry.trace.span("step", phase="step"):
                new_state, stats = self._dispatch(fn, state, xs, ys)
                jax.block_until_ready(stats["loss"])
            with telemetry.trace.span("commit", phase="commit"):
                jax.block_until_ready(new_state.center_params)
        return new_state, stats

    def run_epoch(self, state: TrainState, xs: jnp.ndarray, ys: jnp.ndarray,
                  *, sync_telemetry: bool = True):
        """Run one epoch.  ``xs``/``ys`` leading dims: [num_workers, n_windows,
        window, batch] (uniform mode) or [num_workers, n_steps, batch]
        (staleness mode).

        ``sync_telemetry=False`` keeps the dispatch fully asynchronous even
        when telemetry is enabled (no spans recorded here); the streaming
        path uses it so double buffering survives and records its own spans
        at its real sync points instead."""
        if self.commit_schedule is not None:
            key = ("step", xs.shape[1], xs.ndim)
            if key not in self._epoch_fns:
                self._epoch_fns[key] = self._make_stepwise_epoch_fn(xs.shape[1], xs.ndim)
        else:
            n_windows, window = xs.shape[1], xs.shape[2]
            do_commit = self.rule.communication_window > 0
            key = ("win", n_windows, window, do_commit, xs.ndim)
            if key not in self._epoch_fns:
                self._epoch_fns[key] = self._make_epoch_fn(n_windows, window, do_commit, xs.ndim)
        fn = self._epoch_fns[key]
        with self.mesh:
            if sync_telemetry and telemetry.enabled():
                return self._dispatch_with_spans(fn, state, xs, ys, int(xs.shape[1]))
            return self._dispatch(fn, state, xs, ys)

    def run_epochs(
        self,
        state: TrainState,
        xs: jnp.ndarray,
        ys: jnp.ndarray,
        num_epochs: int,
        *,
        shuffle_seed: Optional[int] = None,
    ):
        """Run ``num_epochs`` epochs over in-memory data as ONE jitted program.

        Equivalent to ``num_epochs`` sequential :meth:`run_epoch` calls
        (bit-identical trajectory when ``shuffle_seed`` is None — asserted in
        tests/test_run_epochs.py) but with a single dispatch, eliminating the
        per-epoch host round-trip; with ``shuffle_seed`` set, epochs reshuffle
        the sample stream on device (see ``_make_multi_epoch_fn``).  Stats
        leaves concatenate along the leading axis exactly like consecutive
        ``run_epoch`` results.  Uniform-window mode only: the staleness
        simulation already scans its whole epoch in one program.
        """
        if self.commit_schedule is not None:
            raise ValueError(
                "run_epochs runs uniform windows; the staleness simulation "
                "dispatches per epoch (run_epoch)"
            )
        num_epochs = int(num_epochs)
        if num_epochs < 1:
            raise ValueError(f"num_epochs must be >= 1, got {num_epochs}")
        n_windows, window = xs.shape[1], xs.shape[2]
        do_commit = self.rule.communication_window > 0
        key = ("multi", n_windows, window, do_commit, xs.ndim, num_epochs, shuffle_seed)
        if key not in self._epoch_fns:
            self._epoch_fns[key] = self._make_multi_epoch_fn(
                n_windows, window, do_commit, xs.ndim, num_epochs, shuffle_seed
            )
        fn = self._epoch_fns[key]
        with self.mesh:
            if telemetry.enabled():
                return self._dispatch_with_spans(fn, state, xs, ys, n_windows)
            return self._dispatch(fn, state, xs, ys)

    def clear_program_cache(self, keep_multi: Optional[tuple] = None) -> None:
        """Drop cached compiled epoch programs.

        A live executable that is not the one being measured degrades
        steady-state TPU throughput ~15-20% until collected (measured on
        v5e — bench.py's round-2 lesson); benchmark harnesses call this
        between calibration and the timed region, then ``gc.collect()``.
        ``keep_multi=(num_epochs, shuffle_seed)`` retains a matching
        :meth:`run_epochs` program — the one about to be timed — so a
        calibration that landed on the same rep count is not recompiled.
        State/data buffers are unaffected."""
        if keep_multi is None:
            self._epoch_fns.clear()
            return
        for key in list(self._epoch_fns):
            if not (key[0] == "multi" and key[-2:] == tuple(keep_multi)):
                del self._epoch_fns[key]

    def stream_put(self, block):
        """Cast + shard one streamed window block ``(xs, ys)`` shaped
        ``[num_workers, window, batch, ...]`` onto the mesh — the h2d half
        of the streaming path, factored out so the datapipe
        :class:`~distkeras_tpu.datapipe.PrefetchRing` can run it as its
        device-put stage on the producer thread (h2d then overlaps the next
        gather); :meth:`run_epoch_streaming` recognises blocks that arrive
        already device-resident and skips its own put.

        Float features ship pre-cast to the compute dtype: the first thing
        the local step does with x is cast it (``_local_step``), so casting
        on host instead is value-identical — and through a bandwidth-bound
        link (axon tunnel: ~35-85 MB/s measured; even PCIe at dataset
        scale) bf16 halves the bytes of the dominant cost (PERF.md §8).
        """
        xs, ys = block
        cast = self.compute_dtype
        if cast is not None and jnp.issubdtype(xs.dtype, jnp.floating):
            # copy=False: blocks from the fused native gather+cast
            # (data.epoch_window_iter(feature_dtype=...)) arrive already
            # in the compute dtype — don't pay a second host copy
            xs = xs.astype(cast, copy=False)
        return self.shard_batches(xs[:, None], ys[:, None])

    def run_epoch_streaming(self, state: TrainState, window_iter,
                            prefetch: int = 2, strict_link=None,
                            on_window=None):
        """Run one epoch from a host-side iterator of per-window blocks
        ``(xs, ys)`` shaped ``[num_workers, window, batch, ...]`` (see
        :func:`distkeras_tpu.data.epoch_window_iter`).

        The whole-epoch array is never materialised on device: each block is
        device_put as it's consumed, and because dispatch is asynchronous the
        next block's host gather + transfer overlaps the current block's
        compute (double buffering).  Device-resident blocks are bounded at
        ~2x ``prefetch``: up to ``prefetch`` undispatched blocks wait in the
        buffer while up to ``prefetch`` dispatched windows are in flight.
        The per-window program is the n_windows=1 epoch program, so the
        training trajectory is the math of :meth:`run_epoch` exactly
        (asserted bit-for-bit in tests/test_streaming.py).

        **Link guardrail**: overlap only *hides* source latency while the
        source is faster than the device; a link slower than compute makes
        the accelerators idle every window and no prefetch depth can fix it
        (PERF.md §8 — the axon-tunnel lesson).  This method times the
        source pulls it already makes (no extra syncs), and when the
        steady-state unhideable source fraction exceeds 25% it warns once —
        or raises when ``strict_link=True`` (default: the
        ``DISTKERAS_STREAMING_STRICT`` env var).  The measured report is
        kept on ``self.last_stream_report`` for bench/debug.

        ``on_window(state, n)`` (optional) fires after window ``n`` (1-based)
        has been dispatched — the trainers' mid-epoch checkpoint hook (model
        state + datapipe block cursor).  ``window_iter.close()``, when it
        exists (generators, the datapipe PrefetchRing), is called on every
        exit path, so an error mid-epoch drains a prefetch ring instead of
        orphaning its thread.
        """
        if self.commit_schedule is not None:
            raise ValueError(
                "streaming runs uniform windows; the staleness simulation "
                "needs the whole epoch in one program (run_epoch)"
            )
        import os
        import time
        import warnings
        from collections import deque

        if strict_link is None:
            strict_link = os.environ.get(
                "DISTKERAS_STREAMING_STRICT", "").lower() not in ("", "0", "false")

        it = iter(window_iter)
        buf = deque()
        stats_list = []
        steps_list = []  # per-window step counts (ragged tail weighting)
        n_windows = 0
        depth = max(1, prefetch)
        # Source/link accounting: time only the pulls the loop already makes
        # (next(it) + host cast + transfer dispatch) — never an added sync.
        # Steady state starts after the first backpressure wait completes:
        # before that, compile + initial prefill dominate and would
        # misattribute one-time costs to the link.
        src_seconds = 0.0
        steady_src = 0.0
        steady_t0 = None

        def pull():
            nonlocal src_seconds, steady_src
            t0 = time.perf_counter()
            block = next(it, None)
            if block is not None:
                if isinstance(block[0], jax.Array):
                    # the datapipe ring's device-put stage already ran
                    # stream_put on its producer thread: the block arrives
                    # sharded [num_workers, 1, window, batch, ...]
                    steps_list.append(int(block[0].shape[2]))
                else:
                    steps_list.append(block[0].shape[1])
                    block = self.stream_put(block)
            dt = time.perf_counter() - t0
            src_seconds += dt
            if steady_t0 is not None:
                steady_src += dt
            return block

        try:
            while True:
                if not buf:
                    block = pull()
                    if block is None:
                        break
                    buf.append(block)
                xs, ys = buf.popleft()
                # async dispatch; sync_telemetry=False because blocking here
                # would serialise the pipeline — spans are recorded at the real
                # sync point (the backpressure wait) instead
                with telemetry.trace.span("window_dispatch", window=n_windows):
                    state, stats = self.run_epoch(
                        state, xs, ys, sync_telemetry=False)
                n_windows += 1
                stats_list.append(stats)
                if on_window is not None:
                    on_window(state, n_windows)
                # Backpressure: dispatch is async, so without a sync the host
                # would device_put the whole epoch ahead of the device and defeat
                # the memory bound.  Waiting on the loss of the window dispatched
                # `prefetch` calls ago caps in-flight windows at prefetch (plus
                # up to prefetch buffered undispatched blocks — see docstring).
                if n_windows > depth:
                    with telemetry.trace.span("window_wait", phase="step",
                                              window=n_windows - 1 - depth):
                        jax.block_until_ready(stats_list[n_windows - 1 - depth]["loss"])
                    if steady_t0 is None:
                        steady_t0 = time.perf_counter()
                # Refill AFTER dispatching (first window included): the very
                # first window's compute then hides the rest of the initial
                # prefill's source latency — measured, not assumed, in
                # tests/test_streaming_overlap.py.
                while len(buf) < depth:
                    block = pull()
                    if block is None:
                        break
                    buf.append(block)
        finally:
            close = getattr(window_iter, "close", None)
            if close is not None:
                close()
        if not stats_list:
            raise ValueError("empty window iterator")
        self._report_stream_link(src_seconds, steady_src, steady_t0,
                                 n_windows, strict_link, time.perf_counter())
        # generic over the stats pytree (loss/metrics, plus the dynamics
        # subtree when enabled): concatenate every leaf along the window axis
        stats = jax.tree.map(lambda *leaves: jnp.concatenate(leaves), *stats_list)
        # per-window step counts ride along as a host leaf so the history
        # can weight a ragged tail window by its actual steps (PARITY.md)
        stats = dict(stats)
        stats["window_steps"] = np.asarray(steps_list, np.int64)
        # each window ran as its own "epoch" program (epoch += n_windows);
        # restore whole-epoch semantics (+1).  The input state was donated by
        # the first window's call, so arithmetic uses the live output state.
        state = state.replace(epoch=state.epoch - (n_windows - 1))
        return state, stats

    def _report_stream_link(self, src_seconds, steady_src, steady_t0,
                            n_windows, strict_link, now):
        """Judge the last streamed epoch's source/compute balance.

        Over the steady-state region (first backpressure wait -> epoch end)
        the loop alternates pulling source blocks and waiting on the device;
        source time hidden behind compute shows up as wall time NOT spent in
        pulls, so ``unhideable = steady_src - (steady_wall - steady_src)``
        is the part of the link cost the device actually waited out.  A
        fraction > 0.25 of steady wall time means the link, not the model,
        bounds throughput — warn loudly (once per engine) or raise in
        strict mode.  Short epochs that never hit backpressure measure
        nothing and never trip the guardrail."""
        import warnings

        steady_wall = (now - steady_t0) if steady_t0 is not None else 0.0
        if steady_wall > 0:
            hidden = max(0.0, steady_wall - steady_src)
            unhideable = max(0.0, steady_src - hidden)
            fraction = unhideable / steady_wall
        else:
            unhideable, fraction = 0.0, 0.0
        link_bound = fraction > 0.25
        self.last_stream_report = {
            "windows": n_windows,
            "source_seconds": src_seconds,
            "steady_wall_seconds": steady_wall,
            "steady_source_seconds": steady_src,
            "unhideable_fraction": fraction,
            "link_bound": link_bound,
        }
        if not link_bound:
            return
        msg = (
            f"streaming source is the bottleneck: {fraction:.0%} of "
            f"steady-state wall time ({steady_src:.2f}s of "
            f"{steady_wall:.2f}s over {n_windows} windows) is source/"
            "transfer latency no prefetch depth can hide — the devices are "
            "idling on the link.  Stage the dataset closer (local disk / "
            "in-memory), widen the link, or grow per-window compute "
            "(larger window/batch).  See engine.last_stream_report."
        )
        if strict_link:
            raise RuntimeError(msg)
        if not self._link_warned:
            self._link_warned = True
            warnings.warn(msg, RuntimeWarning, stacklevel=3)

    def average_workers(self, state: TrainState):
        """One-shot synchronous weight average (AveragingTrainer's final step)."""

        # cached program: a fresh jit wrapper per call would re-trace every
        # time (same per-call-closure trap as _fsdp_regather below)
        if self._avg_fn is None:
            def _avg(state):
                mean_p = jax.tree.map(
                    lambda x: jnp.mean(x, axis=0), state.local_params)
                mean_ms = jax.tree.map(
                    lambda x: jnp.mean(x, axis=0), state.model_state)
                return state.replace(center_params=mean_p), mean_ms

            self._avg_fn = jax.jit(_avg, out_shardings=(None, self._rep))
        with self.mesh:
            new_state, mean_ms = self._avg_fn(state)
        return new_state, mean_ms

    def final_model_state(self, state: TrainState):
        """Replicated model state for the returned model (mean of workers)."""
        if self._final_ms_fn is None:
            self._final_ms_fn = jax.jit(
                lambda ms: jax.tree.map(lambda x: jnp.mean(x, axis=0), ms),
                out_shardings=self._rep,
            )
        with self.mesh:
            return self._final_ms_fn(state.model_state)

    def worker_slice(self, tree, index: int):
        """Fetch one worker's slice of per-worker state to host (Ensemble path)."""
        return jax.tree.map(lambda x: np.asarray(x[index]), tree)

    def gather_center(self, state: TrainState):
        """Center params as host-gatherable (replicated) arrays.  Already
        replicated in this engine unless seq-axis fsdp sharded them; the
        GSPMD engine re-replicates its model-axis-sharded leaves here."""
        if self._fsdp_seq:
            # one cached re-replication program — a fresh lambda per call
            # would miss jit's function-object cache and re-trace every
            # checkpoint save (the per-call-closure trap, generate.py doc)
            if self._fsdp_regather is None:
                self._fsdp_regather = jax.jit(lambda t: t, out_shardings=self._rep)
            with self.mesh:
                return self._fsdp_regather(state.center_params)
        return state.center_params

    # --------------------------------------------------------------- sharding
    def shard_batches(self, xs: np.ndarray, ys: np.ndarray):
        """Device-put epoch data: worker axis leading; sequence (last) axis of
        xs also sharded when sequence parallelism is on.

        Uses ``make_array_from_callback`` so the same code works multi-host
        (each process materialises only its addressable shards — the DCN
        analogue of Spark shipping partitions to executors)."""
        from jax.sharding import NamedSharding

        xs_spec, ys_spec = self._data_specs(xs.ndim)

        def _put():
            with self.mesh:
                return (
                    jax.make_array_from_callback(
                        xs.shape, NamedSharding(self.mesh, xs_spec), lambda idx: xs[idx]
                    ),
                    jax.make_array_from_callback(
                        ys.shape, NamedSharding(self.mesh, ys_spec), lambda idx: ys[idx]
                    ),
                )

        if not telemetry.enabled():
            return _put()
        # blocking makes the span honest (the transfer itself, not just the
        # enqueue); only taken when telemetry is on
        with telemetry.trace.span("h2d", phase="h2d",
                                  bytes=int(xs.nbytes) + int(ys.nbytes)):
            out = _put()
            jax.block_until_ready(out)
        return out
