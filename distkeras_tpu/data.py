"""Host-side batching: DataFrame columns -> mesh-shaped epoch arrays.

The reference streams partition row-iterators into per-worker minibatch loops
(``distkeras/workers.py`` minibatch iterator).  The TPU engine instead wants
the whole epoch as one statically-shaped array
``[num_workers, n_windows, window, batch, ...]`` so a single jitted
``shard_map`` program can scan it.  This module builds those arrays with
wrap-around padding (no sample dropped, matching the reference's
use-every-row behaviour) and per-epoch host-side shuffling.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = ["epoch_arrays", "plan_epoch"]


def plan_epoch(n: int, num_workers: int, batch_size: int, window: int) -> Tuple[int, int]:
    """(n_windows, padded_total): smallest window grid covering all n samples."""
    window = max(1, window)
    per_step = num_workers * batch_size
    steps = max(1, -(-n // per_step))  # ceil
    n_windows = max(1, -(-steps // window))
    return n_windows, n_windows * window * per_step


def epoch_arrays(
    features: np.ndarray,
    labels: np.ndarray,
    num_workers: int,
    batch_size: int,
    window: int,
    *,
    stepwise: bool = False,
    rng: Optional[np.random.Generator] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Shuffle + wrap-pad + reshape one epoch of data.

    Uniform mode: leaves shaped ``[num_workers, n_windows, window, batch, ...]``.
    Stepwise (staleness-sim) mode: ``[num_workers, n_steps, batch, ...]``.
    """
    n = len(features)
    if n == 0:
        raise ValueError("empty dataset")
    idx = np.arange(n)
    if rng is not None:
        rng.shuffle(idx)
    n_windows, total = plan_epoch(n, num_workers, batch_size, window)
    reps = -(-total // n)
    idx = np.tile(idx, reps)[:total]
    # Gather is the host-side hot path: multithreaded native kernel when the
    # C++ library is available, bit-identical numpy fallback otherwise.
    from distkeras_tpu import native

    xs = native.gather_rows(features, idx)
    ys = native.gather_rows(labels, idx)
    if stepwise:
        shape = (num_workers, n_windows * window, batch_size)
    else:
        shape = (num_workers, n_windows, window, batch_size)
    xs = xs.reshape(shape + features.shape[1:])
    ys = ys.reshape(shape + labels.shape[1:])
    return xs, ys
