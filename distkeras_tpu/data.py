"""Host-side batching: DataFrame columns -> mesh-shaped epoch arrays.

The reference streams partition row-iterators into per-worker minibatch loops
(``distkeras/workers.py`` minibatch iterator).  The TPU engine instead wants
the whole epoch as one statically-shaped array
``[num_workers, n_windows, window, batch, ...]`` so a single jitted
``shard_map`` program can scan it.  This module builds those arrays with
wrap-around padding (no sample dropped, matching the reference's
use-every-row behaviour) and per-epoch host-side shuffling.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = ["epoch_arrays", "epoch_window_iter", "plan_epoch"]


def plan_epoch(n: int, num_workers: int, batch_size: int, window: int) -> Tuple[int, int]:
    """(n_windows, padded_total): smallest window grid covering all n samples."""
    window = max(1, window)
    per_step = num_workers * batch_size
    steps = max(1, -(-n // per_step))  # ceil
    n_windows = max(1, -(-steps // window))
    return n_windows, n_windows * window * per_step


def epoch_arrays(
    features: np.ndarray,
    labels: np.ndarray,
    num_workers: int,
    batch_size: int,
    window: int,
    *,
    stepwise: bool = False,
    rng: Optional[np.random.Generator] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Shuffle + wrap-pad + reshape one epoch of data.

    Uniform mode: leaves shaped ``[num_workers, n_windows, window, batch, ...]``.
    Stepwise (staleness-sim) mode: ``[num_workers, n_steps, batch, ...]``.
    """
    n = len(features)
    if n == 0:
        raise ValueError("empty dataset")
    idx = np.arange(n)
    if rng is not None:
        rng.shuffle(idx)
    n_windows, total = plan_epoch(n, num_workers, batch_size, window)
    reps = -(-total // n)
    idx = np.tile(idx, reps)[:total]
    # Gather is the host-side hot path: multithreaded native kernel when the
    # C++ library is available, bit-identical numpy fallback otherwise.
    from distkeras_tpu import native, telemetry

    with telemetry.trace.span("epoch_arrays", phase="data", rows=int(total)):
        xs = native.gather_rows(features, idx)
        ys = native.gather_rows(labels, idx)
        if stepwise:
            shape = (num_workers, n_windows * window, batch_size)
        else:
            shape = (num_workers, n_windows, window, batch_size)
        xs = xs.reshape(shape + features.shape[1:])
        ys = ys.reshape(shape + labels.shape[1:])
    return xs, ys


def epoch_window_iter(
    features: np.ndarray,
    labels: np.ndarray,
    num_workers: int,
    batch_size: int,
    window: int,
    *,
    rng: Optional[np.random.Generator] = None,
    pad_to_window: bool = True,
    feature_dtype=None,
    start_block: int = 0,
):
    """Lazily yield one epoch as per-window blocks
    ``[num_workers, window, batch, ...]`` — the streaming twin of
    :func:`epoch_arrays`.

    Draws the identical shuffle from ``rng`` and emits rows in exactly the
    order ``epoch_arrays`` lays them out (asserted bit-for-bit in
    tests/test_streaming.py), but gathers only ``num_workers*window*batch``
    rows at a time, so the whole-epoch array never exists — on host or
    device.  This is the path for datasets approaching HBM size; the
    reference's analogue is Spark streaming partitions into executors
    (SURVEY.md §3.1) rather than collecting the dataset to the driver.

    ``pad_to_window=True`` wrap-pads the step count up to a window multiple
    (commit semantics need full windows — matches ``epoch_arrays``).  With
    ``pad_to_window=False`` the step count is planned at step granularity and
    the final block may be ragged: the right shape for no-commit trainers,
    where block boundaries are arbitrary and extra padded steps would change
    the trajectory.

    ``feature_dtype=bfloat16`` (with float32 features) emits each block
    through the fused native gather+cast (``native.gather_rows_bf16``):
    one pass over the data, half the bytes toward the device — the host
    half of the streaming path's compute-dtype transfer.  Value-identical
    to casting after the gather.

    ``start_block=k`` skips the first ``k`` windows by index arithmetic
    alone (no gather is paid for skipped blocks) while still drawing the
    full shuffle from ``rng`` — the datapipe resume path
    (:class:`distkeras_tpu.datapipe.DataState`): restore the RNG bit state
    captured before the epoch's shuffle, skip the consumed blocks, and the
    remaining blocks are bitwise the uninterrupted epoch's tail.
    """
    n = len(features)
    if n == 0:
        raise ValueError("empty dataset")
    idx = np.arange(n)
    if rng is not None:
        rng.shuffle(idx)
    if pad_to_window:
        n_windows, total = plan_epoch(n, num_workers, batch_size, window)
        steps = n_windows * window
    else:
        steps, total = plan_epoch(n, num_workers, batch_size, 1)
        n_windows = -(-steps // window)
    reps = -(-total // n)
    idx = np.tile(idx, reps)[:total]
    # epoch_arrays reshapes worker-major: worker k / window w covers the flat
    # slice idx2[k, w*window:(w+1)*window] below.
    idx2 = idx.reshape(num_workers, steps, batch_size)
    from distkeras_tpu import native, telemetry

    fused_bf16 = (
        feature_dtype is not None
        and np.dtype(feature_dtype).name == "bfloat16"
        and np.issubdtype(features.dtype, np.floating)
    )
    gather_x = native.gather_rows_bf16 if fused_bf16 else native.gather_rows
    start_block = int(start_block)
    if not 0 <= start_block <= n_windows:
        raise ValueError(
            f"start_block {start_block} outside this epoch's "
            f"[0, {n_windows}] window range"
        )
    for w in range(start_block, n_windows):
        block = idx2[:, w * window : (w + 1) * window]
        cur = block.shape[1]  # < window only for a ragged final block
        sel = np.ascontiguousarray(block).ravel()
        block_shape = (num_workers, cur, batch_size)
        with telemetry.trace.span("window_gather", phase="data",
                                  window=w, rows=int(sel.size)):
            xs = gather_x(features, sel).reshape(block_shape + features.shape[1:])
            ys = native.gather_rows(labels, sel).reshape(block_shape + labels.shape[1:])
        yield xs, ys
