"""Columnar DataFrame shim — the TPU-native replacement for Spark DataFrames.

The reference (``dist-keras``) keeps all user data in Spark ``DataFrame``s and
ships per-partition row iterators into executors (``distkeras/utils.py`` and
``DataFrame.rdd.mapPartitionsWithIndex`` call sites in
``distkeras/trainers.py``).  On TPU there is no Spark: the natural layout is a
*columnar* batch of host numpy arrays that can be reshaped/sharded straight
onto a device mesh.  This module provides a small DataFrame with the subset of
the Spark API the reference's transformers / predictors / evaluators and
example notebooks rely on (``select``, ``withColumn``, ``repartition``,
``collect``, ``count``, ``filter``, ``sample``, ...), backed by a dict of
numpy arrays instead of an RDD.

Unlike Spark rows, columns are whole numpy arrays, so feature transforms are
vectorised (orders of magnitude faster than the reference's per-row Python
loops) and handing data to JAX is a zero-copy ``device_put``.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, Iterator, List, Mapping, Sequence, Union

import numpy as np

__all__ = [
    "DataFrame", "Row", "from_rows", "from_numpy", "from_pandas",
    "from_spark", "to_spark", "read_csv",
]


class Row(dict):
    """Dict-like row with attribute access, mirroring ``pyspark.sql.Row``."""

    def __getattr__(self, name: str):
        try:
            return self[name]
        except KeyError as e:  # pragma: no cover - defensive
            raise AttributeError(name) from e

    def asDict(self) -> dict:
        return dict(self)

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        inner = ", ".join(f"{k}={v!r}" for k, v in self.items())
        return f"Row({inner})"


def _as_column(values, length_hint: int | None = None) -> np.ndarray:
    """Coerce a column to a numpy array; ragged data falls back to object dtype."""
    if isinstance(values, np.ndarray):
        return values
    values = list(values)
    try:
        arr = np.asarray(values)
        if arr.dtype == object and values and isinstance(values[0], (list, np.ndarray)):
            raise ValueError("ragged")
        return arr
    except ValueError:
        arr = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            arr[i] = np.asarray(v)
        return arr


class DataFrame:
    """Immutable columnar frame: a dict of equal-length numpy columns.

    ``num_partitions`` is carried as metadata (the analogue of Spark
    partitioning): trainers use it to decide how many workers see the data,
    and ``partitions()`` yields contiguous row-range shards.
    """

    def __init__(self, columns: Mapping[str, np.ndarray], num_partitions: int = 1):
        cols: Dict[str, np.ndarray] = {}
        n = None
        for name, values in columns.items():
            arr = _as_column(values)
            if n is None:
                n = len(arr)
            elif len(arr) != n:
                raise ValueError(
                    f"column {name!r} has length {len(arr)}, expected {n}"
                )
            cols[name] = arr
        self._columns = cols
        self._n = 0 if n is None else int(n)
        self.num_partitions = max(1, int(num_partitions))

    # -- schema ------------------------------------------------------------
    @property
    def columns(self) -> List[str]:
        return list(self._columns)

    def __contains__(self, name: str) -> bool:
        return name in self._columns

    def __len__(self) -> int:
        return self._n

    def count(self) -> int:
        return self._n

    def __getitem__(self, name: str) -> np.ndarray:
        return self._columns[name]

    def column(self, name: str) -> np.ndarray:
        """The raw numpy column."""
        return self._columns[name]

    def matrix(self, name: str, dtype=np.float32) -> np.ndarray:
        """Column as a dense stacked ndarray [n, ...] (object columns stacked)."""
        col = self._columns[name]
        if col.dtype == object:
            col = np.stack([np.asarray(v) for v in col])
        return np.asarray(col, dtype=dtype)

    # -- transforms (all return new frames) --------------------------------
    def select(self, *names: str) -> "DataFrame":
        return DataFrame({n: self._columns[n] for n in names}, self.num_partitions)

    def with_column(self, name: str, values) -> "DataFrame":
        cols = dict(self._columns)
        cols[name] = _as_column(values)
        return DataFrame(cols, self.num_partitions)

    # Spark-style alias used by the reference notebooks.
    withColumn = with_column

    def drop(self, *names: str) -> "DataFrame":
        return DataFrame(
            {n: c for n, c in self._columns.items() if n not in names},
            self.num_partitions,
        )

    def rename(self, old: str, new: str) -> "DataFrame":
        cols = {(new if n == old else n): c for n, c in self._columns.items()}
        return DataFrame(cols, self.num_partitions)

    withColumnRenamed = rename

    def filter(self, predicate: Union[np.ndarray, Callable[[Row], bool]]) -> "DataFrame":
        if callable(predicate):
            mask = np.fromiter(
                (bool(predicate(r)) for r in self.iter_rows()), dtype=bool, count=self._n
            )
        else:
            mask = np.asarray(predicate, dtype=bool)
        return DataFrame(
            {n: c[mask] for n, c in self._columns.items()}, self.num_partitions
        )

    where = filter

    def sample(self, fraction: float, seed: int | None = None) -> "DataFrame":
        rng = np.random.default_rng(seed)
        mask = rng.random(self._n) < fraction
        return self.filter(mask)

    def shuffle(self, seed: int | None = None) -> "DataFrame":
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self._n)
        return DataFrame(
            {n: c[perm] for n, c in self._columns.items()}, self.num_partitions
        )

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(
            {name: c[:n] for name, c in self._columns.items()}, self.num_partitions
        )

    def union(self, other: "DataFrame") -> "DataFrame":
        if set(self.columns) != set(other.columns):
            raise ValueError("union requires identical column sets")
        return DataFrame(
            {n: np.concatenate([self._columns[n], other._columns[n]]) for n in self.columns},
            self.num_partitions,
        )

    def split(self, fraction: float, seed: int | None = None):
        """Random (train, test) split — the notebooks' randomSplit equivalent."""
        rng = np.random.default_rng(seed)
        mask = rng.random(self._n) < fraction
        return self.filter(mask), self.filter(~mask)

    def randomSplit(self, weights: Sequence[float], seed: int | None = None):
        rng = np.random.default_rng(seed)
        w = np.asarray(weights, dtype=np.float64)
        w = w / w.sum()
        assignment = rng.choice(len(w), size=self._n, p=w)
        return [self.filter(assignment == i) for i in range(len(w))]

    # -- partitioning ------------------------------------------------------
    def repartition(self, n: int) -> "DataFrame":
        return DataFrame(self._columns, num_partitions=n)

    def coalesce(self, n: int) -> "DataFrame":
        return DataFrame(self._columns, num_partitions=min(n, self.num_partitions))

    def partitions(self) -> Iterator["DataFrame"]:
        """Contiguous row-range shards, one per partition."""
        bounds = np.linspace(0, self._n, self.num_partitions + 1).astype(int)
        for i in range(self.num_partitions):
            lo, hi = bounds[i], bounds[i + 1]
            yield DataFrame({n: c[lo:hi] for n, c in self._columns.items()}, 1)

    # -- materialisation ---------------------------------------------------
    def iter_rows(self) -> Iterator[Row]:
        names = self.columns
        cols = [self._columns[n] for n in names]
        for i in range(self._n):
            yield Row({n: c[i] for n, c in zip(names, cols)})

    def collect(self) -> List[Row]:
        return list(self.iter_rows())

    def take(self, n: int) -> List[Row]:
        return self.limit(n).collect()

    def first(self) -> Row:
        return self.take(1)[0]

    def cache(self) -> "DataFrame":  # Spark-compat no-op
        return self

    persist = cache

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame({n: list(c) for n, c in self._columns.items()})

    toPandas = to_pandas

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return (
            f"DataFrame[{self._n} rows x {len(self._columns)} cols, "
            f"{self.num_partitions} partitions: {self.columns}]"
        )


# -- constructors ----------------------------------------------------------

def from_rows(rows: Iterable[Mapping], num_partitions: int = 1) -> DataFrame:
    rows = list(rows)
    if not rows:
        return DataFrame({}, num_partitions)
    names = list(rows[0].keys())
    return DataFrame(
        {n: _as_column([r[n] for r in rows]) for n in names}, num_partitions
    )


def from_numpy(
    features: np.ndarray,
    labels: np.ndarray | None = None,
    features_col: str = "features",
    label_col: str = "label",
    num_partitions: int = 1,
) -> DataFrame:
    cols = {features_col: np.asarray(features)}
    if labels is not None:
        cols[label_col] = np.asarray(labels)
    return DataFrame(cols, num_partitions)


def from_pandas(pdf, num_partitions: int = 1) -> DataFrame:
    return DataFrame({c: _as_column(pdf[c].to_list()) for c in pdf.columns}, num_partitions)


def from_spark(sdf, columns: Sequence[str] | None = None) -> DataFrame:
    """Bridge a **pyspark** DataFrame into the columnar frame.

    The reference lived natively on Spark DataFrames; users migrating actual
    Spark pipelines call this once at the boundary
    (``dk.from_spark(spark_df)``) and keep the rest of the flow unchanged.
    Spark ML vector values (``DenseVector``/``SparseVector`` — the
    features/label columns the reference's transformers produce) are
    densified via their ``toArray``.  Prefers ``toPandas()`` (Arrow fast
    path) and falls back to ``collect()``; partitioning metadata carries
    over from ``rdd.getNumPartitions()`` when available.

    pyspark itself is NOT a dependency: this function only touches the
    object it's handed.
    """
    names = list(columns) if columns is not None else list(sdf.columns)

    def densify(v):
        return np.asarray(v.toArray(), np.float32) if hasattr(v, "toArray") else v

    try:
        pdf = sdf.toPandas()  # ONLY the transfer is fallible-by-design
    except Exception:
        pdf = None
    if pdf is not None:
        data = {c: [densify(v) for v in pdf[c].to_list()] for c in names}
    else:
        rows = sdf.collect()
        data = {c: [densify(r[c]) for r in rows] for c in names}
    try:
        num_partitions = int(sdf.rdd.getNumPartitions())
    except Exception:
        num_partitions = 1
    return DataFrame({c: _as_column(v) for c, v in data.items()}, num_partitions)


def to_spark(df: DataFrame, spark, columns: Sequence[str] | None = None):
    """Write the columnar frame back out as a **pyspark** DataFrame — the
    egress half of the Spark boundary (``from_spark`` is the ingress half).

    The reference's whole flow lived inside Spark DataFrames, so a pipeline
    could end with ``predictor.predict(df)`` feeding downstream Spark ML
    (SURVEY.md §2 Predictors row); migrating users close the loop with
    ``dk.to_spark(frame, spark)`` after training/inference here.

    Vector-valued columns (multi-dim or object rows — features, predictions)
    become per-row Python float lists, which Spark infers as ``array<double>``;
    scalar columns pass through.  Hands ``spark.createDataFrame`` a pandas
    frame when pandas imports (Arrow fast path, mirroring ``from_spark``'s
    ``toPandas`` preference), else a list of plain dict rows.

    pyspark itself is NOT a dependency: this function only calls
    ``createDataFrame`` on the session object it's handed.
    """
    names = list(columns) if columns is not None else df.columns

    def numeric_row(v) -> bool:
        arr = np.asarray(v)
        if arr.dtype != object:
            return np.issubdtype(arr.dtype, np.number) or arr.dtype == bool
        # object-dtype rows (how _as_column stores ragged vectors): look at
        # the scalar leaves themselves
        import numbers

        return all(isinstance(x, numbers.Number) for x in arr.ravel())

    def pyify(name):
        col = df.column(name)
        if col.dtype == object or col.ndim > 1:
            # Decide numeric-vs-not by inspecting element dtypes, not by
            # attempting the cast and catching: exception-driven dispatch
            # would coerce numeric-LOOKING strings ("1.5") to floats, and
            # one stray string deep in an otherwise-numeric column would
            # flip every row to the scalar branch mid-stream.
            if all(numeric_row(v) for v in col):
                return [np.asarray(v).ravel().astype(float).tolist()
                        for v in col]
            # non-numeric object column (strings, ids — ubiquitous in
            # Spark frames): pass the rows through as Python scalars
            # like the scalar branch does, don't force-cast to float
            return [v.item() if isinstance(v, np.generic) else v
                    for v in col]
        return col.tolist()

    data = {name: pyify(name) for name in names}
    try:
        import pandas as pd
    except ImportError:
        pd = None
    if pd is not None:
        return spark.createDataFrame(pd.DataFrame(data))
    rows = [{name: data[name][i] for name in names} for i in range(len(df))]
    return spark.createDataFrame(rows)


def read_csv(path: str, header: bool = True, num_partitions: int = 1) -> DataFrame:
    """Minimal CSV reader (numeric columns become float arrays)."""
    import csv

    with open(path, newline="") as f:
        reader = csv.reader(f)
        rows = list(reader)
    if not rows:
        return DataFrame({}, num_partitions)
    if header:
        names, rows = rows[0], rows[1:]
    else:
        names = [f"_c{i}" for i in range(len(rows[0]))]
    cols = {}
    for i, name in enumerate(names):
        raw = [r[i] for r in rows]
        try:
            cols[name] = np.asarray(raw, dtype=np.float64)
        except ValueError:
            cols[name] = np.asarray(raw, dtype=object)
    return DataFrame(cols, num_partitions)
