"""Data plane — sharded, prefetching, resumable input pipeline.

ROADMAP item 5: the training input path grows from the Spark-DataFrame-shaped
host loop (:mod:`distkeras_tpu.frame` / :mod:`distkeras_tpu.data`) into a
subsystem of its own, without changing a single trained bit:

* :mod:`~distkeras_tpu.datapipe.source` — where rows live: in-memory arrays /
  DataFrame columns, or memory-mapped ``.npy`` file shards, each host holding
  only its slice (sharding keyed on ``jax.process_index()``).
* :mod:`~distkeras_tpu.datapipe.ring` — :class:`PrefetchRing`, a bounded
  background-thread ring that pulls blocks through the existing
  ``epoch_window_iter`` layout (bitwise-identical row order, including the
  fused bf16 gather+cast) and optionally runs the engine's device-put stage
  off-thread, feeding ``run_epoch_streaming`` unchanged via its
  ``window_iter`` contract.
* :mod:`~distkeras_tpu.datapipe.packing` — :func:`pack_sequences`, bin-packing
  ragged token sequences into fixed-width rows with segment IDs for the
  intra-segment causal attention path in TransformerLM/StagedLM.
* :mod:`~distkeras_tpu.datapipe.state` — :class:`DataState`, the deterministic
  data checkpoint (epoch, block cursor, RNG bit-generator state) saved next to
  model checkpoints by :mod:`distkeras_tpu.checkpoint` so a killed run resumes
  mid-epoch on the identical remaining-block sequence.
"""

from distkeras_tpu.datapipe.packing import PackedBatch, pack_sequences
from distkeras_tpu.datapipe.ring import PrefetchRing
from distkeras_tpu.datapipe.source import (
    ArraySource,
    MemmapSource,
    Source,
    atomic_write_npy,
    host_shard,
)
from distkeras_tpu.datapipe.state import DataState

__all__ = [
    "ArraySource",
    "DataState",
    "MemmapSource",
    "PackedBatch",
    "PrefetchRing",
    "Source",
    "atomic_write_npy",
    "host_shard",
    "pack_sequences",
]
