"""Sequence packing — ragged token sequences into fixed-width rows.

A causal-LM batch of ragged sequences padded to max length wastes compute on
pad tokens (a 2x-skewed length distribution wastes ~half the FLOPs).  Packing
lays several sequences end-to-end in one fixed-width row and tags each token
with a **segment ID**; the attention mask then allows token *i* to attend
token *j* only when ``segment[i] == segment[j]`` (and ``j <= i``), so the
packed forward pass computes, for every segment, exactly the logits the
sequence would get alone (tests/test_datapipe.py pins this against the
unpacked path).  Positions restart at 0 per segment, matching the positional
embeddings a standalone sequence would see.

Deterministic first-fit-decreasing bin packing: sequences sorted by length
(stable on ties, so the same input always packs the same way) drop into the
first row with room.  FFD is within 22% of optimal in the worst case and
near-optimal on natural length distributions — and determinism matters more
here than the last few percent: packing feeds the resumable data path.

Model side: ``TransformerLM(packed=True)`` / ``StagedLM(packed=True)``
consume :meth:`PackedBatch.model_inputs` (``[rows, width, 2]`` —
token and segment-ID channels) and derive positions + the intra-segment
causal mask internally; train with ``loss="masked_token_crossentropy"`` so
the ``-1`` labels at pads and segment tails drop out of the mean.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["PackedBatch", "pack_sequences"]


@dataclasses.dataclass
class PackedBatch:
    """The packed epoch: ``[rows, width]`` int32 planes.

    ``segment_ids`` are 1-based per row (0 marks pad).  ``labels`` are
    next-token targets within each segment, ``-1`` at segment tails and pads
    (the ``masked_token_crossentropy`` ignore value).  ``positions`` restart
    at 0 per segment (informational — the packed models re-derive them from
    the segment IDs on device).
    """

    tokens: np.ndarray
    segment_ids: np.ndarray
    positions: np.ndarray
    labels: np.ndarray
    n_sequences: int
    total_tokens: int

    @property
    def efficiency(self) -> float:
        """Fraction of row slots holding real tokens (1.0 = no pad waste)."""
        return self.total_tokens / float(self.tokens.size) if self.tokens.size else 0.0

    def model_inputs(self) -> np.ndarray:
        """``[rows, width, 2]`` int32 (token, segment-ID) channels — the
        input convention of ``TransformerLM(packed=True)`` and
        ``StagedLM(packed=True)``."""
        return np.stack([self.tokens, self.segment_ids], axis=-1)


def pack_sequences(
    sequences: Sequence[np.ndarray],
    width: int,
    labels: Optional[Sequence[np.ndarray]] = None,
    pad_id: int = 0,
) -> PackedBatch:
    """First-fit-decreasing pack of ``sequences`` into ``width``-wide rows.

    ``labels=None`` derives next-token targets (``seq[1:]`` within the
    segment, ``-1`` at its last token); an explicit ``labels`` list must
    match the sequences element-for-element in length.  Sequences longer
    than ``width`` (or empty) are an error — truncation would silently
    change the training distribution.
    """
    width = int(width)
    if width < 1:
        raise ValueError(f"width must be >= 1, got {width}")
    seqs = [np.asarray(s, dtype=np.int64) for s in sequences]
    if not seqs:
        raise ValueError("no sequences to pack")
    lengths = np.array([len(s) for s in seqs])
    if (lengths == 0).any():
        raise ValueError("empty sequence cannot be packed")
    if (lengths > width).any():
        worst = int(lengths.max())
        raise ValueError(
            f"sequence of length {worst} exceeds pack width {width} — "
            "split long sequences upstream (truncation here would silently "
            "change the training distribution)"
        )
    if labels is not None:
        labels = [np.asarray(l, dtype=np.int64) for l in labels]
        if len(labels) != len(seqs):
            raise ValueError(
                f"{len(labels)} label sequences for {len(seqs)} token "
                "sequences"
            )
        for i, (s, l) in enumerate(zip(seqs, labels)):
            if len(s) != len(l):
                raise ValueError(
                    f"sequence {i}: {len(s)} tokens vs {len(l)} labels"
                )

    # stable sort on descending length: identical inputs pack identically
    order = np.argsort(-lengths, kind="stable")
    row_free: List[int] = []          # remaining slots per row
    row_items: List[List[int]] = []   # sequence indices per row, in order
    for si in order:
        need = int(lengths[si])
        for r, free in enumerate(row_free):
            if free >= need:
                row_items[r].append(int(si))
                row_free[r] = free - need
                break
        else:
            row_items.append([int(si)])
            row_free.append(width - need)

    rows = len(row_items)
    tokens = np.full((rows, width), pad_id, np.int32)
    segment_ids = np.zeros((rows, width), np.int32)
    positions = np.zeros((rows, width), np.int32)
    out_labels = np.full((rows, width), -1, np.int32)
    for r, items in enumerate(row_items):
        off = 0
        for seg, si in enumerate(items, start=1):
            s = seqs[si]
            n = len(s)
            tokens[r, off:off + n] = s
            segment_ids[r, off:off + n] = seg
            positions[r, off:off + n] = np.arange(n)
            if labels is not None:
                out_labels[r, off:off + n] = labels[si]
            elif n > 1:
                # next-token targets; the segment's last token has none
                out_labels[r, off:off + n - 1] = s[1:]
            off += n

    return PackedBatch(
        tokens=tokens,
        segment_ids=segment_ids,
        positions=positions,
        labels=out_labels,
        n_sequences=len(seqs),
        total_tokens=int(lengths.sum()),
    )
