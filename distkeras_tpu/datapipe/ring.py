"""PrefetchRing — bounded background prefetch over a window-block iterator.

``run_epoch_streaming`` already double-buffers: async dispatch lets the
*current* block's compute overlap the *next* block's host gather — but the
gather itself still runs on the dispatching thread, so a slow source inserts
its latency into the dispatch loop.  The ring moves the pull onto a worker
thread: blocks flow ``source -> [producer thread: gather (+ optional
device-put)] -> bounded queue -> consumer``, and the consumer is any code
written against the plain iterator contract — ``run_epoch_streaming`` feeds
from a ring with zero changes.

Guarantees (tests/test_datapipe.py):

* **Bitwise parity** — the ring reorders nothing and touches no block
  payload; the trajectory through ``epoch_window_iter`` + ring is the
  non-prefetched trajectory, bit for bit (float32 and fused-bf16 gathers).
* **No hangs, no orphans** — every queue wait is timeout-bounded (dklint
  DK112 exempts these; anything unbounded in this loop would stall training
  end-to-end).  A producer exception is captured and re-raised at the
  consumer's next pull; ``close()`` (also the generator-protocol ``close``
  that ``run_epoch_streaming``'s try/finally calls) drains the queue and
  joins the thread.
* **Observability** — with telemetry on, gathers record spans on the
  producer thread (their own tid in the merged Chrome trace, overlapping the
  main thread's ``step`` spans), the ``datapipe_prefetch_depth`` gauge tracks
  queue occupancy, and ``datapipe_stall_seconds`` accumulates consumer wait
  time.  ``ring.blocks`` / ``ring.stall_seconds`` mirror the counters as
  plain attributes for bench rows.

The optional ``put_fn`` (typically ``engine.stream_put``) runs the host→device
transfer on the producer thread too, so h2d overlaps the next gather; the
engine recognises device-resident blocks and skips its own put.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Optional

from distkeras_tpu import telemetry

__all__ = ["PrefetchRing"]

# Wait quantum for every blocking queue op: long enough to cost nothing
# measurable, short enough that close() is honoured promptly.
_TICK = 0.05

# End-of-stream marker (identity-compared; never leaks to the consumer).
_END = object()


class PrefetchRing:
    """Iterate ``window_iter``'s blocks through a ``depth``-bounded queue
    filled by a background thread.  Iterator in, iterator out — drop-in for
    :meth:`WindowedEngine.run_epoch_streaming`'s ``window_iter`` argument."""

    def __init__(self, window_iter, depth: int = 2,
                 put_fn: Optional[Callable] = None):
        self._it = iter(window_iter)
        self._put_fn = put_fn
        self._q: queue.Queue = queue.Queue(maxsize=max(1, int(depth)))
        self._closed = threading.Event()
        self._exc: Optional[BaseException] = None
        #: blocks delivered to the consumer so far
        self.blocks = 0
        #: cumulative seconds the consumer waited on an empty ring — the
        #: host-side twin of the datapipe_stall_seconds counter
        self.stall_seconds = 0.0
        self._thread = threading.Thread(
            target=self._produce, name="datapipe-prefetch", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------- producer
    def _produce(self):
        try:
            while not self._closed.is_set():
                # No phase= on this span: the underlying epoch_window_iter
                # records its own window_gather spans (phase="data") nested
                # inside — both land on THIS thread's tid, which is what
                # makes gather/step overlap visible in the merged trace.
                with telemetry.trace.span("datapipe_gather"):
                    try:
                        block = next(self._it)
                    except StopIteration:
                        break
                if self._put_fn is not None:
                    # device-put off the dispatch thread (engine.stream_put
                    # records its own h2d span); h2d now overlaps the next
                    # gather as well as the device compute
                    block = self._put_fn(block)
                if not self._offer(block):
                    return  # closed while waiting: drop the tail, exit
                if telemetry.enabled():
                    telemetry.metrics.gauge(
                        "datapipe_prefetch_depth",
                        help="window blocks buffered in the prefetch ring",
                    ).set(float(self._q.qsize()))
        except BaseException as e:  # re-raised at the consumer's next pull
            self._exc = e
        self._offer(_END)

    def _offer(self, item) -> bool:
        """Bounded-wait put: retries in _TICK quanta so a close() during
        backpressure is honoured instead of deadlocking the producer."""
        while not self._closed.is_set():
            try:
                self._q.put(item, timeout=_TICK)
                return True
            except queue.Full:
                continue
        return False

    # ------------------------------------------------------------- consumer
    def __iter__(self):
        return self

    def __next__(self):
        if self._closed.is_set():
            raise StopIteration
        t0 = time.perf_counter()
        while True:
            try:
                item = self._q.get(timeout=_TICK)
                break
            except queue.Empty:
                if not self._thread.is_alive():
                    # producer gone: drain whatever it left, then finish
                    try:
                        item = self._q.get(block=False)
                    except queue.Empty:
                        item = _END
                    break
                continue
        waited = time.perf_counter() - t0
        self.stall_seconds += waited
        if telemetry.enabled():
            telemetry.metrics.counter(
                "datapipe_stall_seconds",
                help="seconds the training loop waited on an empty "
                     "prefetch ring",
            ).inc(waited)
        if item is _END:
            self.close()
            if self._exc is not None:
                exc, self._exc = self._exc, None
                raise exc
            raise StopIteration
        self.blocks += 1
        return item

    # ------------------------------------------------------------ lifecycle
    def close(self):
        """Stop the producer and join it.  Idempotent; also the generator
        protocol hook run_epoch_streaming's try/finally calls, so a trainer
        error drains the ring instead of orphaning the thread."""
        self._closed.set()
        while True:  # unblock a producer stuck on a full queue
            try:
                self._q.get(block=False)
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.close()
        return False
