"""DataState — the deterministic data checkpoint.

A model checkpoint alone resumes training from the *epoch boundary*; the data
plane needs three more facts to resume from the exact block the run died on:
which epoch was in flight, how many window blocks of it were already consumed
(the cursor), and the host RNG's bit-generator state from *before* that
epoch's shuffle.  With those, ``epoch_window_iter(..., start_block=cursor)``
replays the identical permutation and yields exactly the remaining blocks —
the resumed trajectory is bitwise the uninterrupted one
(tests/test_datapipe.py).  This is the prerequisite ROADMAP item 3 (elastic
fleet training per ABS/DynSSP) names: joining or leaving workers restart from
a data checkpoint, not from epoch zero.

The state is a few hundred bytes of JSON (PCG64 state is two 128-bit ints);
:mod:`distkeras_tpu.checkpoint` writes it synchronously as a ``step_<n>_data
.json`` sidecar next to the async Orbax step directory.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np

__all__ = ["DataState"]


def _jsonable(obj: Any) -> Any:
    """Recursively coerce numpy scalars inside an rng-state dict to plain
    Python so ``json.dump`` round-trips it exactly (ints are arbitrary
    precision in both directions)."""
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    return obj


@dataclasses.dataclass
class DataState:
    """Position of a training run inside its data stream.

    ``epoch``: the epoch the cursor points into (== the epoch counter of the
    model state saved alongside).  ``block_cursor``: window blocks of that
    epoch already consumed — 0 for an epoch-boundary checkpoint.
    ``rng_state``: ``numpy.random.Generator.bit_generator.state`` captured
    *before* the cursor epoch's shuffle (None when the run doesn't shuffle),
    so the resumed iterator replays the identical permutation.
    """

    epoch: int = 0
    block_cursor: int = 0
    rng_state: Optional[dict] = None

    @classmethod
    def capture(cls, epoch: int, rng: Optional[np.random.Generator],
                block_cursor: int = 0) -> "DataState":
        """Snapshot ``rng`` (if any) at the current stream position."""
        return cls(
            epoch=int(epoch),
            block_cursor=int(block_cursor),
            rng_state=rng.bit_generator.state if rng is not None else None,
        )

    def restore_rng(self, rng: np.random.Generator) -> np.random.Generator:
        """Rewind ``rng`` to the captured bit-generator state (no-op when
        none was captured); returns ``rng`` for chaining."""
        if self.rng_state is not None:
            rng.bit_generator.state = self.rng_state
        return rng

    def to_json(self) -> dict:
        return {
            "epoch": int(self.epoch),
            "block_cursor": int(self.block_cursor),
            "rng_state": _jsonable(self.rng_state),
        }

    @classmethod
    def from_json(cls, d: dict) -> "DataState":
        return cls(
            epoch=int(d["epoch"]),
            block_cursor=int(d["block_cursor"]),
            rng_state=d.get("rng_state"),
        )
