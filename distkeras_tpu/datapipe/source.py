"""Sources — where training rows live, sharded per host.

The reference reads Spark DataFrame partitions on the executors
(SURVEY.md §3.1); on a TPU pod the analogue is each *host process* gathering
only its slice of the dataset while the SPMD program spans all of them.  A
``Source`` owns (a) the global row count and (b) this host's local feature /
label arrays; ``window_iter`` then streams the local slice through the exact
``epoch_window_iter`` layout, so everything downstream (PrefetchRing,
``run_epoch_streaming``) is source-agnostic.

Two concrete sources:

* :class:`ArraySource` — in-memory numpy arrays or DataFrame columns
  (``from_dataframe`` applies the same dtype rules as the trainers).
* :class:`MemmapSource` — ``.npy`` files opened with ``mmap_mode="r"``:
  a single file shards by row range (zero-copy view), a list of file shards
  shards round-robin by file.  Pages fault in as the gather touches them,
  so datasets larger than host RAM stream without a load step.

Sharding is keyed on ``jax.process_index()`` / ``jax.process_count()`` by
default (overridable for tests and non-JAX tooling).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["Source", "ArraySource", "MemmapSource", "atomic_write_npy",
           "host_shard"]


def _fsync_dir(path: str) -> None:
    """Make a directory entry durable (the rename itself, not just the
    renamed bytes).  Best-effort: not every filesystem lets you open or
    fsync a directory."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_npy(path: str, array: np.ndarray) -> str:
    """Publish one ``.npy`` shard atomically: tmp + fsync + ``os.replace`` +
    parent-dir fsync, the same discipline as checkpoint manifests (DK118).
    A cross-process reader — a :class:`MemmapSource` built by a window
    scheduler polling the shard directory — sees the old file or the new
    file, never a torn header or a half-written row, and the new bytes
    survive power loss once this returns.  Returns ``path``."""
    array = np.ascontiguousarray(array)
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        np.save(fh, array)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(os.path.abspath(path)))
    return path


def _process_slot(process_index: Optional[int], process_count: Optional[int]):
    if process_count is None:
        import jax

        return jax.process_index(), jax.process_count()
    return int(process_index or 0), int(process_count)


def host_shard(n: int, process_index: Optional[int] = None,
               process_count: Optional[int] = None) -> Tuple[int, int]:
    """Contiguous ``[lo, hi)`` row range owned by this host: balanced to
    within one row, every row owned by exactly one host."""
    idx, count = _process_slot(process_index, process_count)
    if not 0 <= idx < count:
        raise ValueError(f"process_index {idx} outside [0, {count})")
    base, rem = divmod(int(n), count)
    lo = idx * base + min(idx, rem)
    hi = lo + base + (1 if idx < rem else 0)
    return lo, hi


class Source:
    """A sharded dataset: global length + this host's local arrays.

    Subclasses set ``_features`` / ``_labels`` (the LOCAL slice) and
    ``_global_rows``; ``window_iter`` streams the local slice in the
    bitwise ``epoch_window_iter`` layout.
    """

    _features: np.ndarray
    _labels: np.ndarray
    _global_rows: int

    def __len__(self) -> int:
        """Global row count across all hosts."""
        return self._global_rows

    @property
    def local_rows(self) -> int:
        return len(self._features)

    def local_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """(features, labels) for this host only."""
        return self._features, self._labels

    def window_iter(self, num_workers: int, batch_size: int, window: int, *,
                    rng: Optional[np.random.Generator] = None,
                    pad_to_window: bool = True, feature_dtype=None,
                    start_block: int = 0):
        """This host's epoch as per-window blocks — exactly
        :func:`distkeras_tpu.data.epoch_window_iter` over ``local_arrays()``
        (same shuffle draw, same row order, same fused bf16 gather), so a
        Source drops into ``run_epoch_streaming`` / ``PrefetchRing``
        unchanged."""
        from distkeras_tpu.data import epoch_window_iter

        feats, labels = self.local_arrays()
        return epoch_window_iter(
            feats, labels, num_workers, batch_size, window,
            rng=rng, pad_to_window=pad_to_window,
            feature_dtype=feature_dtype, start_block=start_block,
        )


class ArraySource(Source):
    """In-memory rows, sliced per host.

    ``shard=False`` keeps the full arrays (single-host training, or data
    already sharded upstream); the slice is a view, never a copy.
    """

    def __init__(self, features, labels, *, shard: bool = True,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None):
        features = np.asarray(features)
        labels = np.asarray(labels)
        if len(features) != len(labels):
            raise ValueError(
                f"features ({len(features)} rows) and labels "
                f"({len(labels)} rows) disagree"
            )
        self._global_rows = len(features)
        if shard:
            lo, hi = host_shard(len(features), process_index, process_count)
            features, labels = features[lo:hi], labels[lo:hi]
        self._features, self._labels = features, labels

    @classmethod
    def from_dataframe(cls, dataframe, features_col: str = "features",
                       label_col: str = "label", **kwargs) -> "ArraySource":
        """Materialise DataFrame columns with the trainers' dtype rules
        (integer token features stay int32; everything else float32)."""
        f_raw = dataframe.column(features_col)
        if f_raw.dtype != object and np.issubdtype(f_raw.dtype, np.integer):
            feats = f_raw.astype(np.int32)
        else:
            feats = dataframe.matrix(features_col, dtype=np.float32)
        labels_raw = dataframe.column(label_col)
        if labels_raw.dtype == object:
            labels = dataframe.matrix(label_col, dtype=np.float32)
        elif np.issubdtype(labels_raw.dtype, np.integer):
            labels = labels_raw.astype(np.int32)
        else:
            labels = labels_raw.astype(np.float32)
        return cls(feats, labels, **kwargs)


class MemmapSource(Source):
    """Memory-mapped ``.npy`` rows, sharded per host.

    One file each: the host takes its row range as a zero-copy mmap view
    (the native gather reads straight out of the page cache).  A sequence
    of file shards: shards are assigned round-robin by
    ``paths[process_index::process_count]`` and a host's multiple shards
    concatenate on first access (a copy of the LOCAL slice only — prefer
    >= one shard per host to stay zero-copy).
    """

    def __init__(self, feature_paths, label_paths, *, shard: bool = True,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None):
        f_paths = self._as_paths(feature_paths)
        l_paths = self._as_paths(label_paths)
        if len(f_paths) != len(l_paths):
            raise ValueError(
                f"{len(f_paths)} feature shard(s) vs {len(l_paths)} label "
                "shard(s) — they must pair up"
            )
        f_maps = [np.load(p, mmap_mode="r") for p in f_paths]
        l_maps = [np.load(p, mmap_mode="r") for p in l_paths]
        for fp, fm, lm in zip(f_paths, f_maps, l_maps):
            if len(fm) != len(lm):
                raise ValueError(
                    f"shard {fp}: {len(fm)} feature rows vs {len(lm)} labels"
                )
        self._global_rows = sum(len(m) for m in f_maps)
        idx, count = _process_slot(process_index, process_count)
        if not shard:
            idx, count = 0, 1
        if len(f_maps) == 1:
            # single file: row-range sharding, zero-copy mmap views
            lo, hi = host_shard(self._global_rows, idx, count)
            self._features = f_maps[0][lo:hi]
            self._labels = l_maps[0][lo:hi]
        else:
            mine_f = f_maps[idx::count]
            mine_l = l_maps[idx::count]
            if not mine_f:
                raise ValueError(
                    f"host {idx}/{count} got zero of {len(f_maps)} file "
                    "shards — provide at least one shard per host"
                )
            if len(mine_f) == 1:
                self._features, self._labels = mine_f[0], mine_l[0]
            else:
                self._features = np.concatenate([np.asarray(m) for m in mine_f])
                self._labels = np.concatenate([np.asarray(m) for m in mine_l])

    @staticmethod
    def _as_paths(paths) -> Sequence[str]:
        if isinstance(paths, (str, bytes)):
            return [paths]
        out = list(paths)
        if not out:
            raise ValueError("empty shard list")
        return out
