"""Deterministic chaos harness — fault injection for the fleet layer.

Opt-in through ``DISTKERAS_CHAOS=<seed>:<spec>`` (same discipline as
``DISTKERAS_SANITIZE``): unset/falsey ⇒ **off** — every hook is one cached
bool check, the control-plane objects are stock, and the lowered training
program is byte-identical (pinned by test).  The harness never touches
jitted code: every fault fires on the host, at a named *site*, from seeded
per-site counters — so a chaos run is exactly reproducible, which is what
makes recovery paths provable in CI rather than asserted.

``<spec>`` is a comma-separated ``key=value`` list:

======================  =====================================================
``kill_epoch=N``        raise :class:`ChaosKilled` entering epoch number N
                        (0-based count of ``epoch`` faults; fires once)
``kill_block=N``        raise :class:`ChaosKilled` at the Nth streaming block
                        (global across epochs; fires once)
``stall_block=N``       sleep ``stall_secs`` at the Nth block (fires once)
``stall_secs=S``        stall duration for ``stall_block`` (default 0.05)
``refuse_connect=K``    first K ``connect`` sites raise ConnectionRefusedError
``drop_reply=K``        first K ``rpc_reply`` sites raise ConnectionError —
                        the request reached the daemon, the reply was lost
``drop_recv=K``         first K ``recv`` sites raise ConnectionError
``tear_send=K``         first K ``send`` sites put a truncated frame on the
                        wire (seeded split point) then raise ConnectionError
``delay_send_ms=M``     every ``send`` site sleeps M milliseconds first
``kill_replica=N``      raise :class:`ChaosKilled` at the Nth ``replica``
                        site (a serving replica's decode loop, hit only
                        while requests are in flight; fires once)
``stall_http=K``        first K ``http`` sites (health probes) sleep
                        ``stall_secs`` — a wedged ``/healthz``
``kill_commit=N``       raise :class:`ChaosKilled` at the Nth ``ckpt_commit``
                        site — after the orbax write landed but *before* the
                        manifest commit record was published (fires once)
``delay_commit_ms=M``   every ``ckpt_commit`` site sleeps M milliseconds
                        first — widens the committed-but-unpublished window
                        a cross-process watcher must never surface
``kill_rotate=N``       raise :class:`ChaosKilled` at the Nth ``window_rotate``
                        site — after a capture window's shard files landed
                        but *before* its manifest published (fires once)
``torn_ckpt=N``         truncate one seeded leaf file of the Nth *published*
                        checkpoint (post-commit torn write / lost page
                        cache; fires once)
``flip_ckpt=N``         flip one seeded bit in one seeded leaf file of the
                        Nth published checkpoint (bit rot — sizes intact,
                        only a full digest verify can catch it; fires once)
======================  =====================================================

Example: ``DISTKERAS_CHAOS=7:kill_block=5,refuse_connect=2``.

Tests flip the switch with :func:`configure` instead of mutating
``os.environ``, exactly like ``sanitizer.configure``.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, Iterable, Iterator, Optional

__all__ = [
    "ChaosConfig",
    "ChaosKilled",
    "configure",
    "counts",
    "enabled",
    "fault",
    "corrupt_ckpt",
    "spec",
    "tear_bytes",
    "wrap_blocks",
]

_FALSEY = ("", "0", "false", "no")

# integer-valued spec keys and their meaning; anything else is rejected so a
# typo'd fault name fails loudly instead of silently injecting nothing
_INT_KEYS = frozenset({
    "kill_epoch", "kill_block", "stall_block", "refuse_connect",
    "drop_reply", "drop_recv", "tear_send", "delay_send_ms",
    "kill_replica", "stall_http",
    "kill_commit", "delay_commit_ms", "torn_ckpt", "flip_ckpt",
    "kill_rotate",
})
_FLOAT_KEYS = frozenset({"stall_secs"})


class ChaosKilled(RuntimeError):
    """A seeded worker-kill fault fired (the injected analogue of a
    preempted/crashed worker mid-run)."""


class ChaosConfig:
    """Parsed ``<seed>:<spec>``; ``None`` spec values mean 'not armed'."""

    def __init__(self, seed: int, params: Dict[str, float]):
        self.seed = int(seed)
        self.params = dict(params)

    def get(self, key: str) -> Optional[float]:
        return self.params.get(key)

    @classmethod
    def parse(cls, raw: str) -> "ChaosConfig":
        head, _, rest = raw.partition(":")
        try:
            seed = int(head)
        except ValueError as e:
            raise ValueError(
                f"DISTKERAS_CHAOS must start with '<seed>:', got {raw!r}"
            ) from e
        params: Dict[str, float] = {}
        for item in filter(None, (p.strip() for p in rest.split(","))):
            key, sep, value = item.partition("=")
            if not sep:
                raise ValueError(f"chaos spec item {item!r} is not key=value")
            if key in _INT_KEYS:
                params[key] = int(value)
            elif key in _FLOAT_KEYS:
                params[key] = float(value)
            else:
                raise ValueError(
                    f"unknown chaos spec key {key!r} (known: "
                    f"{sorted(_INT_KEYS | _FLOAT_KEYS)})"
                )
        return cls(seed, params)


# None = not yet resolved from the environment; False = resolved off;
# a ChaosConfig once resolved on (or forced via configure()).
_CONFIG = None
_LOCK = threading.Lock()
_COUNTS: Dict[str, int] = {}
_FIRED: set = set()


def _resolve():
    global _CONFIG
    if _CONFIG is None:
        raw = os.environ.get("DISTKERAS_CHAOS", "")
        _CONFIG = ChaosConfig.parse(raw) if raw.lower() not in _FALSEY else False
    return _CONFIG


def enabled() -> bool:
    """Whether chaos injection is armed; cached after the first read."""
    return _resolve() is not False


def spec() -> Optional[ChaosConfig]:
    cfg = _resolve()
    return cfg if cfg is not False else None


def configure(raw: Optional[str] = None) -> None:
    """Force the spec (``"<seed>:<spec>"``), disable (``""``), or reset to
    env-driven (``None``).  Clears every site counter and fired-fault
    record, so each test starts from a clean chaos timeline."""
    global _CONFIG
    with _LOCK:
        if raw is None:
            _CONFIG = None
        elif raw.lower() in _FALSEY:
            _CONFIG = False
        else:
            _CONFIG = ChaosConfig.parse(raw)
        _COUNTS.clear()
        _FIRED.clear()


def counts() -> Dict[str, int]:
    """Per-site fault-hook hit counts (introspection for tests)."""
    with _LOCK:
        return dict(_COUNTS)


def _next_count(site: str) -> int:
    """Increment and return the 0-based hit index for ``site``."""
    with _LOCK:
        n = _COUNTS.get(site, 0)
        _COUNTS[site] = n + 1
        return n


def _fire_once(key: str) -> bool:
    with _LOCK:
        if key in _FIRED:
            return False
        _FIRED.add(key)
        return True


def _note(kind: str) -> None:
    # chaos decisions are visible in the telemetry registry so a CI chaos
    # leg can assert faults actually fired; one cached-bool check when off
    from distkeras_tpu import telemetry

    if telemetry.enabled():
        telemetry.metrics.counter(
            f"chaos_{kind}_total", help=f"chaos faults injected ({kind})"
        ).inc()


def fault(site: str) -> None:
    """Fire any armed fault for ``site``; no-op (beyond one counter bump)
    otherwise.  Sites: ``connect``, ``send``, ``recv``, ``rpc_reply``,
    ``epoch``, ``block``, ``replica``, ``http``, ``ckpt_commit``,
    ``window_rotate``."""
    cfg = spec()
    if cfg is None:
        return
    n = _next_count(site)
    if site == "connect":
        k = cfg.get("refuse_connect")
        if k is not None and n < k:
            _note("refuse_connect")
            raise ConnectionRefusedError(
                f"chaos: connect refused ({n + 1}/{int(k)})")
    elif site == "rpc_reply":
        k = cfg.get("drop_reply")
        if k is not None and n < k:
            _note("drop_reply")
            raise ConnectionError(f"chaos: reply dropped ({n + 1}/{int(k)})")
    elif site == "recv":
        k = cfg.get("drop_recv")
        if k is not None and n < k:
            _note("drop_recv")
            raise ConnectionError(f"chaos: recv dropped ({n + 1}/{int(k)})")
    elif site == "send":
        delay = cfg.get("delay_send_ms")
        if delay:
            _note("delay_send")
            time.sleep(delay / 1000.0)  # dklint: disable=DK112 — injected stall
    elif site == "epoch":
        k = cfg.get("kill_epoch")
        if k is not None and n == k and _fire_once("kill_epoch"):
            _note("kill_epoch")
            raise ChaosKilled(f"chaos: worker killed entering epoch {n}")
    elif site == "block":
        k = cfg.get("kill_block")
        if k is not None and n == k and _fire_once("kill_block"):
            _note("kill_block")
            raise ChaosKilled(f"chaos: worker killed at block {n}")
        k = cfg.get("stall_block")
        if k is not None and n == k and _fire_once("stall_block"):
            _note("stall_block")
            time.sleep(cfg.get("stall_secs") or 0.05)  # dklint: disable=DK112 — injected stall
    elif site == "replica":
        k = cfg.get("kill_replica")
        if k is not None and n == k and _fire_once("kill_replica"):
            _note("kill_replica")
            raise ChaosKilled(
                f"chaos: serving replica killed at busy iteration {n}")
    elif site == "http":
        k = cfg.get("stall_http")
        if k is not None and n < k:
            _note("stall_http")
            time.sleep(cfg.get("stall_secs") or 0.05)  # dklint: disable=DK112 — injected stall
    elif site == "window_rotate":
        k = cfg.get("kill_rotate")
        if k is not None and n == k and _fire_once("kill_rotate"):
            _note("kill_rotate")
            raise ChaosKilled(
                f"chaos: capture killed between shard rotation and manifest "
                f"publish (window rotation {n})")
    elif site == "ckpt_commit":
        delay = cfg.get("delay_commit_ms")
        if delay:
            _note("delay_commit")
            time.sleep(delay / 1000.0)  # dklint: disable=DK112 — injected stall
        k = cfg.get("kill_commit")
        if k is not None and n == k and _fire_once("kill_commit"):
            _note("kill_commit")
            raise ChaosKilled(
                f"chaos: killed between orbax commit and manifest publish "
                f"(publish {n})")


def tear_bytes(site: str, frame_len: int) -> Optional[int]:
    """When a ``tear_send`` fault is armed for this hit of ``site``, the
    number of leading frame bytes to put on the wire before dropping the
    connection (seeded split point, always a proper prefix); ``None``
    otherwise.  Does NOT consume the site counter — call before
    :func:`fault` for the same frame."""
    cfg = spec()
    if cfg is None:
        return None
    k = cfg.get("tear_send")
    if k is None:
        return None
    with _LOCK:
        n = _COUNTS.get(site, 0)
    if n >= k:
        return None
    _next_count(site)
    _note("tear_send")
    rng = random.Random((cfg.seed << 16) ^ n)
    return rng.randrange(1, max(2, frame_len))


def corrupt_ckpt(paths: Iterable[str]) -> Optional[str]:
    """Fire any armed post-publish checkpoint corruption (``torn_ckpt`` /
    ``flip_ckpt``) against one seeded file from ``paths``; consumes one hit
    of the ``ckpt_publish`` site per call.  Models damage that lands *after*
    the manifest committed (torn page-cache writeback, bit rot) — which is
    exactly what digest verification exists to catch — so the caller must
    invoke it after its commit record is durable.  Returns a description of
    the injected damage, ``None`` when nothing fired."""
    cfg = spec()
    if cfg is None:
        return None
    n = _next_count("ckpt_publish")
    # only non-empty regular files can be meaningfully damaged
    candidates = sorted(p for p in paths
                        if os.path.isfile(p) and os.path.getsize(p) > 0)
    if not candidates:
        return None
    k = cfg.get("torn_ckpt")
    if k is not None and n == k and _fire_once("torn_ckpt"):
        _note("torn_ckpt")
        rng = random.Random((cfg.seed << 16) ^ (0x70 + n))
        target = candidates[rng.randrange(len(candidates))]
        size = os.path.getsize(target)
        keep = rng.randrange(size)  # always a proper prefix
        with open(target, "rb+") as fh:
            fh.truncate(keep)
        return f"torn {target} at {keep}/{size} bytes"
    k = cfg.get("flip_ckpt")
    if k is not None and n == k and _fire_once("flip_ckpt"):
        _note("flip_ckpt")
        rng = random.Random((cfg.seed << 16) ^ (0xF0 + n))
        target = candidates[rng.randrange(len(candidates))]
        size = os.path.getsize(target)
        offset = rng.randrange(size)
        bit = 1 << rng.randrange(8)
        with open(target, "rb+") as fh:
            fh.seek(offset)
            byte = fh.read(1)[0]
            fh.seek(offset)
            fh.write(bytes([byte ^ bit]))
        return f"flipped bit {bit:#04x} at {target}:{offset}"
    return None


def wrap_blocks(blocks: Iterable) -> Iterator:
    """Wrap a streaming block iterator so each block crosses the ``block``
    fault site (kill/stall at a seeded block index) before it reaches the
    engine."""
    for item in blocks:
        fault("block")
        yield item
