"""Feature transformers — Spark-ML-style ``.transform(df)`` stages.

Reference parity: ``distkeras/transformers.py`` (``LabelIndexTransformer``,
``OneHotTransformer``, ``MinMaxTransformer``, ``ReshapeTransformer``,
``DenseTransformer``), each a per-row Python map over a Spark DataFrame.
Here each is a *vectorised* numpy transform over the columnar frame — same
API and semantics, no per-row Python.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from distkeras_tpu.frame import DataFrame

__all__ = [
    "Transformer",
    "LabelIndexTransformer",
    "OneHotTransformer",
    "MinMaxTransformer",
    "ReshapeTransformer",
    "DenseTransformer",
    "StandardScaleTransformer",
]


class Transformer:
    """Base: a pure DataFrame -> DataFrame stage."""

    def transform(self, dataframe: DataFrame) -> DataFrame:
        raise NotImplementedError

    def __call__(self, dataframe: DataFrame) -> DataFrame:
        return self.transform(dataframe)


class LabelIndexTransformer(Transformer):
    """Probability/one-hot vector -> class index (reference parity:
    ``LabelIndexTransformer(output_dim, input_col, output_col)``)."""

    def __init__(self, output_dim: int, input_col: str = "prediction",
                 output_col: str = "prediction_index"):
        self.output_dim = output_dim
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, dataframe: DataFrame) -> DataFrame:
        probs = dataframe.matrix(self.input_col)
        idx = np.argmax(probs.reshape(len(probs), -1), axis=-1).astype(np.int32)
        return dataframe.with_column(self.output_col, idx)


class OneHotTransformer(Transformer):
    """Class index -> one-hot vector (reference parity: ``OneHotTransformer``)."""

    def __init__(self, output_dim: int, input_col: str = "label",
                 output_col: str = "label_encoded"):
        self.output_dim = output_dim
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, dataframe: DataFrame) -> DataFrame:
        idx = np.asarray(dataframe.column(self.input_col), dtype=np.int64).reshape(-1)
        out = np.zeros((len(idx), self.output_dim), dtype=np.float32)
        out[np.arange(len(idx)), idx] = 1.0
        return dataframe.with_column(self.output_col, out)


class MinMaxTransformer(Transformer):
    """Rescale features to [o_min, o_max] (reference parity:
    ``MinMaxTransformer(n_min, n_max, o_min, o_max, input_col, output_col)``)."""

    def __init__(self, o_min: float = 0.0, o_max: float = 1.0,
                 n_min: float = 0.0, n_max: float = 255.0,
                 input_col: str = "features", output_col: str = "features_normalized"):
        self.o_min, self.o_max = float(o_min), float(o_max)
        self.n_min, self.n_max = float(n_min), float(n_max)
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, dataframe: DataFrame) -> DataFrame:
        x = dataframe.matrix(self.input_col)
        scale = (self.o_max - self.o_min) / (self.n_max - self.n_min)
        out = (x - self.n_min) * scale + self.o_min
        return dataframe.with_column(self.output_col, out.astype(np.float32))


class ReshapeTransformer(Transformer):
    """Flat vector -> tensor shape (reference parity: ``ReshapeTransformer``,
    used to reshape 784-vectors into 28x28x1 images for CNNs)."""

    def __init__(self, input_col: str, output_col: str, shape: Sequence[int]):
        self.input_col = input_col
        self.output_col = output_col
        self.shape = tuple(int(s) for s in shape)

    def transform(self, dataframe: DataFrame) -> DataFrame:
        x = dataframe.matrix(self.input_col)
        return dataframe.with_column(self.output_col, x.reshape((len(x),) + self.shape))


class DenseTransformer(Transformer):
    """Sparse -> dense vectors (reference parity: ``DenseTransformer``).

    The columnar frame stores everything dense already; this densifies object
    columns (lists / scipy sparse rows) into a stacked float matrix.
    """

    def __init__(self, input_col: str = "features", output_col: str = "features_dense"):
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, dataframe: DataFrame) -> DataFrame:
        col = dataframe.column(self.input_col)
        if col.dtype == object:
            rows = []
            for v in col:
                if hasattr(v, "toarray"):  # scipy sparse
                    rows.append(np.asarray(v.toarray()).reshape(-1))
                else:
                    rows.append(np.asarray(v, dtype=np.float32).reshape(-1))
            dense = np.stack(rows).astype(np.float32)
        else:
            dense = np.asarray(col, dtype=np.float32)
        return dataframe.with_column(self.output_col, dense)


class StandardScaleTransformer(Transformer):
    """Zero-mean/unit-variance scaling (extension beyond the reference set)."""

    def __init__(self, input_col: str = "features", output_col: str = "features_standardized"):
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, dataframe: DataFrame) -> DataFrame:
        x = dataframe.matrix(self.input_col)
        mu = x.mean(axis=0, keepdims=True)
        sd = x.std(axis=0, keepdims=True) + 1e-8
        return dataframe.with_column(self.output_col, ((x - mu) / sd).astype(np.float32))
