// Native data-path kernels for distkeras_tpu.
//
// The reference's per-row Python iterators (distkeras/workers.py minibatch
// loop) have no native analogue; here the host-side hot path is epoch
// batching — permutation-gather of the full feature matrix into the
// [workers, windows, window, batch, ...] layout (distkeras_tpu/data.py).
// numpy's fancy indexing is single-threaded; for CIFAR-scale epochs this
// multithreaded gather is the difference between the TPU waiting on the host
// and not.
//
// Built as a plain shared library (no pybind11 — loaded via ctypes):
//   g++ -O3 -march=native -shared -fPIC -o libdkdata.so dataloader.cpp -lpthread

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Parallel row gather: dst[i] = src[idx[i]] for rows of row_bytes bytes.
void gather_rows_impl(const uint8_t* src, const int64_t* idx, uint8_t* dst,
                      int64_t n_rows, int64_t row_bytes, int n_threads) {
  if (n_threads < 1) n_threads = 1;
  std::atomic<int64_t> next{0};
  const int64_t chunk = 256;
  auto work = [&] {
    for (;;) {
      int64_t start = next.fetch_add(chunk);
      if (start >= n_rows) return;
      int64_t end = start + chunk < n_rows ? start + chunk : n_rows;
      for (int64_t i = start; i < end; ++i) {
        std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes, row_bytes);
      }
    }
  };
  if (n_threads == 1) {
    work();
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(work);
  for (auto& t : threads) t.join();
}

}  // namespace

extern "C" {

// Gather rows by index. src/dst are raw buffers; row_bytes = bytes per row.
void dk_gather_rows(const void* src, const int64_t* idx, void* dst,
                    int64_t n_rows, int64_t row_bytes, int n_threads) {
  gather_rows_impl(static_cast<const uint8_t*>(src), idx,
                   static_cast<uint8_t*>(dst), n_rows, row_bytes, n_threads);
}

// Fisher-Yates shuffle of an index array with SplitMix64 (deterministic for a
// given seed — keeps the framework's reproducibility guarantee native-side).
void dk_shuffle_indices(int64_t* idx, int64_t n, uint64_t seed) {
  auto splitmix = [&seed]() {
    uint64_t z = (seed += 0x9E3779B97F4A7C15ull);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    return z ^ (z >> 31);
  };
  for (int64_t i = n - 1; i > 0; --i) {
    int64_t j = static_cast<int64_t>(splitmix() % static_cast<uint64_t>(i + 1));
    int64_t tmp = idx[i];
    idx[i] = idx[j];
    idx[j] = tmp;
  }
}

int dk_version() { return 1; }

}  // extern "C"
