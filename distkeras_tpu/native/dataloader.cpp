// Native data-path kernels for distkeras_tpu.
//
// The reference's per-row Python iterators (distkeras/workers.py minibatch
// loop) have no native analogue; here the host-side hot path is epoch
// batching — permutation-gather of the full feature matrix into the
// [workers, windows, window, batch, ...] layout (distkeras_tpu/data.py).
// numpy's fancy indexing is single-threaded; for CIFAR-scale epochs this
// multithreaded gather is the difference between the TPU waiting on the host
// and not.
//
// Built as a plain shared library (no pybind11 — loaded via ctypes):
//   g++ -O3 -march=native -shared -fPIC -o libdkdata.so dataloader.cpp -lpthread

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Shared chunked thread pool: calls row_op(i) for every destination row i,
// work-stealing in fixed chunks over n_threads threads.
template <typename RowOp>
void parallel_rows(int64_t n_rows, int64_t chunk, int n_threads, RowOp row_op) {
  if (n_threads < 1) n_threads = 1;
  std::atomic<int64_t> next{0};
  auto work = [&] {
    for (;;) {
      int64_t start = next.fetch_add(chunk);
      if (start >= n_rows) return;
      int64_t end = start + chunk < n_rows ? start + chunk : n_rows;
      for (int64_t i = start; i < end; ++i) row_op(i);
    }
  };
  if (n_threads == 1) {
    work();
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(work);
  for (auto& t : threads) t.join();
}

// Parallel row gather: dst[i] = src[idx[i]] for rows of row_bytes bytes.
void gather_rows_impl(const uint8_t* src, const int64_t* idx, uint8_t* dst,
                      int64_t n_rows, int64_t row_bytes, int n_threads) {
  parallel_rows(n_rows, 256, n_threads, [&](int64_t i) {
    std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes, row_bytes);
  });
}

// f32 -> bf16 with round-to-nearest-even, matching ml_dtypes/XLA (so the
// fused gather+cast below is bit-identical to gather-then-astype).
inline uint16_t f32_to_bf16(float f) {
  uint32_t u;
  std::memcpy(&u, &f, sizeof(u));
  if ((u & 0x7FFFFFFFu) > 0x7F800000u) {        // NaN: quiet, keep sign
    return static_cast<uint16_t>((u >> 16) | 0x0040u);
  }
  uint32_t rounding_bias = 0x7FFFu + ((u >> 16) & 1u);
  return static_cast<uint16_t>((u + rounding_bias) >> 16);
}

// Fused permutation-gather + f32->bf16 cast: dst[i] = bf16(src[idx[i]]).
// One pass instead of gather-f32 (write N) then astype (read N, write N/2) —
// the host half of the streaming path's compute-dtype transfer.
void gather_rows_bf16_impl(const float* src, const int64_t* idx, uint16_t* dst,
                           int64_t n_rows, int64_t row_elems, int n_threads) {
  parallel_rows(n_rows, 64, n_threads, [&](int64_t i) {
    const float* s = src + idx[i] * row_elems;
    uint16_t* d = dst + i * row_elems;
    for (int64_t j = 0; j < row_elems; ++j) d[j] = f32_to_bf16(s[j]);
  });
}

}  // namespace

extern "C" {

// Gather rows by index. src/dst are raw buffers; row_bytes = bytes per row.
void dk_gather_rows(const void* src, const int64_t* idx, void* dst,
                    int64_t n_rows, int64_t row_bytes, int n_threads) {
  gather_rows_impl(static_cast<const uint8_t*>(src), idx,
                   static_cast<uint8_t*>(dst), n_rows, row_bytes, n_threads);
}

// Fisher-Yates shuffle of an index array with SplitMix64 (deterministic for a
// given seed — keeps the framework's reproducibility guarantee native-side).
void dk_shuffle_indices(int64_t* idx, int64_t n, uint64_t seed) {
  auto splitmix = [&seed]() {
    uint64_t z = (seed += 0x9E3779B97F4A7C15ull);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    return z ^ (z >> 31);
  };
  for (int64_t i = n - 1; i > 0; --i) {
    int64_t j = static_cast<int64_t>(splitmix() % static_cast<uint64_t>(i + 1));
    int64_t tmp = idx[i];
    idx[i] = idx[j];
    idx[j] = tmp;
  }
}

// Fused gather + f32->bf16 cast; row_elems = floats per row.
void dk_gather_rows_bf16(const void* src, const int64_t* idx, void* dst,
                         int64_t n_rows, int64_t row_elems, int n_threads) {
  gather_rows_bf16_impl(static_cast<const float*>(src), idx,
                        static_cast<uint16_t*>(dst), n_rows, row_elems,
                        n_threads);
}

int dk_version() { return 2; }

}  // extern "C"
