"""Native (C++) data-path kernels, loaded via ctypes with a numpy fallback.

The library is compiled on first import (g++, one translation unit, ~1s) into
a per-user cache directory; if no toolchain is available every entry point
falls back to numpy transparently, so the package stays pure-Python-portable.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

import numpy as np

__all__ = ["gather_rows", "gather_rows_bf16", "shuffle_indices", "available"]

_SRC = os.path.join(os.path.dirname(__file__), "dataloader.cpp")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _cache_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    cache_dir = os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.join(os.path.expanduser("~"), ".cache")),
        "distkeras_tpu",
    )
    os.makedirs(cache_dir, exist_ok=True)
    return os.path.join(cache_dir, f"libdkdata_{digest}.so")


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.environ.get("DISTKERAS_TPU_NO_NATIVE"):
        return None
    path = _cache_path()
    if not os.path.exists(path):
        try:
            with tempfile.TemporaryDirectory() as td:
                tmp = os.path.join(td, "libdkdata.so")
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                     "-o", tmp, _SRC, "-lpthread"],
                    check=True, capture_output=True, timeout=120,
                )
                os.replace(tmp, path)
        except (OSError, subprocess.SubprocessError):
            return None
    try:
        lib = ctypes.CDLL(path)
        lib.dk_gather_rows.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
        ]
        lib.dk_gather_rows_bf16.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
        ]
        lib.dk_shuffle_indices.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_uint64,
        ]
        lib.dk_version.restype = ctypes.c_int
        assert lib.dk_version() == 2
        _lib = lib
    except (OSError, AssertionError):
        _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


def _dispatch_gather(fn, src, idx, out, row_size, n_threads):
    """Shared ctypes marshalling for the gather entry points."""
    if n_threads is None:
        n_threads = min(8, os.cpu_count() or 1)
    fn(
        src.ctypes.data_as(ctypes.c_void_p),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        out.ctypes.data_as(ctypes.c_void_p),
        len(idx), row_size, n_threads,
    )
    return out


def gather_rows(src: np.ndarray, idx: np.ndarray, n_threads: Optional[int] = None) -> np.ndarray:
    """dst[i] = src[idx[i]] — multithreaded native gather, numpy fallback."""
    lib = _load()
    src = np.ascontiguousarray(src)
    if lib is None:
        return src[idx]
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    out = np.empty((len(idx),) + src.shape[1:], dtype=src.dtype)
    row_bytes = int(np.prod(src.shape[1:], dtype=np.int64)) * src.dtype.itemsize
    return _dispatch_gather(lib.dk_gather_rows, src, idx, out, row_bytes, n_threads)


def gather_rows_bf16(src: np.ndarray, idx: np.ndarray,
                     n_threads: Optional[int] = None) -> np.ndarray:
    """Fused ``bf16(src[idx])`` for float32 sources — one pass over the data
    instead of gather (write N bytes) then astype (read N, write N/2).  The
    native round-to-nearest-even matches ml_dtypes bit-for-bit (tested);
    fallback composes the two numpy steps."""
    import ml_dtypes

    bf16 = np.dtype(ml_dtypes.bfloat16)
    src = np.ascontiguousarray(src)
    if src.dtype != np.float32:
        return gather_rows(src, idx, n_threads).astype(bf16)
    lib = _load()
    if lib is None:
        return src[idx].astype(bf16)
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    out = np.empty((len(idx),) + src.shape[1:], dtype=bf16)
    row_elems = int(np.prod(src.shape[1:], dtype=np.int64))
    return _dispatch_gather(lib.dk_gather_rows_bf16, src, idx, out, row_elems, n_threads)


def shuffle_indices(n: int, seed: int) -> np.ndarray:
    """Deterministic native Fisher-Yates permutation of arange(n)."""
    idx = np.arange(n, dtype=np.int64)
    lib = _load()
    if lib is None:
        np.random.default_rng(seed).shuffle(idx)
        return idx
    lib.dk_shuffle_indices(
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n, seed & (2**64 - 1)
    )
    return idx
