"""Worker definitions — reference-parity naming over the engine's rules.

In the reference a Worker is a pickled object shipped into a Spark executor
whose ``train(worker_id, iterator)`` runs the per-partition minibatch loop and
speaks the PS socket protocol (``distkeras/workers.py``).  On TPU the worker
loop is compiled into the SPMD program (:mod:`distkeras_tpu.parallel.engine`),
so a Worker here is the *specification* of that loop: which update rule runs
at commit boundaries and which local optimizer runs between them.  The class
names mirror the reference one-for-one so trainer ``allocate_worker``
implementations read identically.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from distkeras_tpu.algorithms import (
    Adag,
    Aeasgd,
    Downpour,
    DynSGD,
    Eamsgd,
    OneShotAverage,
    Sequential,
    UpdateRule,
)

__all__ = [
    "Worker",
    "SequentialWorker",
    "AveragingWorker",
    "DOWNPOURWorker",
    "AEASGDWorker",
    "EAMSGDWorker",
    "ADAGWorker",
    "DynSGDWorker",
    "AdaptiveDynSGDWorker",
]


@dataclasses.dataclass
class Worker:
    """Specification of the per-device training loop.

    ``optimizer`` — the local (worker-side) optimizer spec, the analogue of
    the reference's ``worker_optimizer`` handed to ``model.compile`` in
    ``Worker.prepare_model``.
    """

    optimizer: Any = "sgd"
    batch_size: int = 32
    features_col: str = "features"
    label_col: str = "label"
    rule: UpdateRule = dataclasses.field(default_factory=Sequential)


class SequentialWorker(Worker):
    """Plain local training, no parameter server (reference: SequentialWorker)."""

    def __init__(self, optimizer="sgd", batch_size=32, features_col="features", label_col="label"):
        super().__init__(optimizer, batch_size, features_col, label_col, Sequential())


class AveragingWorker(Worker):
    """Independent local training; weights averaged once at the end."""

    def __init__(self, optimizer="sgd", batch_size=32, features_col="features", label_col="label"):
        super().__init__(optimizer, batch_size, features_col, label_col, OneShotAverage())


class DOWNPOURWorker(Worker):
    def __init__(self, optimizer="sgd", batch_size=32, features_col="features",
                 label_col="label", communication_window=5):
        super().__init__(optimizer, batch_size, features_col, label_col,
                         Downpour(communication_window))


class AEASGDWorker(Worker):
    def __init__(self, optimizer="sgd", batch_size=32, features_col="features",
                 label_col="label", communication_window=32, rho=5.0, learning_rate=0.1):
        super().__init__(optimizer, batch_size, features_col, label_col,
                         Aeasgd(communication_window=communication_window, rho=rho,
                                learning_rate=learning_rate))


class EAMSGDWorker(Worker):
    def __init__(self, optimizer=None, batch_size=32, features_col="features",
                 label_col="label", communication_window=32, rho=5.0,
                 learning_rate=0.1, momentum=0.9):
        if optimizer is None:
            optimizer = ("sgd", {"learning_rate": learning_rate, "momentum": momentum,
                                 "nesterov": True})
        super().__init__(optimizer, batch_size, features_col, label_col,
                         Eamsgd(communication_window=communication_window, rho=rho,
                                learning_rate=learning_rate, momentum=momentum))


class ADAGWorker(Worker):
    def __init__(self, optimizer="sgd", batch_size=32, features_col="features",
                 label_col="label", communication_window=12):
        super().__init__(optimizer, batch_size, features_col, label_col,
                         Adag(communication_window))


class DynSGDWorker(Worker):
    def __init__(self, optimizer="sgd", batch_size=32, features_col="features",
                 label_col="label", communication_window=5):
        super().__init__(optimizer, batch_size, features_col, label_col,
                         DynSGD(communication_window))


class AdaptiveDynSGDWorker(Worker):
    def __init__(self, optimizer="sgd", batch_size=32, features_col="features",
                 label_col="label", communication_window=5,
                 initial_bound=float("inf")):
        from distkeras_tpu.algorithms.adaptive import AdaptiveDynSGD

        super().__init__(optimizer, batch_size, features_col, label_col,
                         AdaptiveDynSGD(communication_window,
                                        initial_bound=initial_bound))
