"""Job deployment — the reference's experimental "Punchcard" subsystem.

Reference parity: ``distkeras/job_deployment.py :: Job`` packages a training
script plus data pointer plus a shared secret and ships it to a remote
Punchcard daemon that runs queued jobs (SURVEY.md L7; explicitly experimental
and off the main path — same status here).

This implementation: :class:`PunchcardServer` is a small TCP daemon with a
FIFO queue and one runner thread; :class:`Job` is the client.  Transport uses
:mod:`distkeras_tpu.networking`'s restricted codec (no pickle).  Submitted
code executes with the daemon's privileges — the shared secret gates access,
so deploy only inside a trusted cluster, exactly like the reference.
"""

from __future__ import annotations

import glob
import hmac
import json
import os
import random
import socket
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from distkeras_tpu import chaos as _chaos
from distkeras_tpu import telemetry
from distkeras_tpu.fleet import FleetMembership
from distkeras_tpu.networking import connect, recv_data, send_data
from distkeras_tpu.sanitizer import lockwatch

__all__ = ["Job", "PunchcardServer"]

DEFAULT_PORT = 8000
# retained replies for retried submit/serve, keyed by client idempotency key
# (bounded FIFO — a retry storm must not grow daemon memory unboundedly)
_IDEMPOTENCY_CACHE = 256


def _collect_job_snapshot(tel_dir: str) -> Optional[dict]:
    """The last metrics snapshot from each ``metrics_*.jsonl`` a job wrote
    (one file per process), merged across its processes.  Returns ``None``
    when the job emitted no telemetry.  Dynamics-series lines (which carry
    no ``metrics`` key) are skipped — the snapshot line is the scrape
    surface; the series stay in the job's JSONL for offline analysis."""
    snaps = []
    for path in sorted(glob.glob(os.path.join(tel_dir, "metrics_*.jsonl"))):
        last = None
        try:
            with open(path, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and "metrics" in rec:
                        last = rec["metrics"]
        except OSError:
            continue
        if last:
            snaps.append(last)
    if not snaps:
        return None
    from distkeras_tpu.telemetry.metrics import merge_snapshots

    return merge_snapshots(snaps) if len(snaps) > 1 else snaps[0]


class PunchcardServer:
    """Queue-and-run daemon for packaged training jobs."""

    def __init__(self, port: int = DEFAULT_PORT, secret: str = "",
                 workdir: Optional[str] = None, handler_timeout: float = 30.0,
                 lease: float = 10.0, lease_misses: int = 2):
        self.port = port
        self.secret = secret
        self.workdir = workdir or tempfile.mkdtemp(prefix="punchcard_")
        #: per-connection deadline on handler sockets: a half-open client
        #: must time out instead of pinning a handler thread forever
        self.handler_timeout = handler_timeout
        # Under DISTKERAS_SANITIZE the cv is wrapped by the lock-order
        # watchdog (acquisition-order graph, off-lock wait/notify checks)
        # and the jobs dict rejects mutation off the cv — DK105's runtime
        # twin.  With the flag off both are the stock objects.
        self._cv = lockwatch.maybe_wrap(threading.Condition(), "punchcard.cv")
        self.jobs: Dict[str, dict] = lockwatch.guard_map({}, self._cv,
                                                         "punchcard.jobs")
        self._queue: list[str] = []
        self._running = False
        self._sock: Optional[socket.socket] = None
        self._threads: list[threading.Thread] = []
        # long-running `serve` jobs: job_id -> Popen (the FIFO runner only
        # handles run-to-completion scripts; a serving engine never exits)
        self._serving: Dict[str, subprocess.Popen] = {}
        # elastic-fleet membership (register/heartbeat/deregister/membership
        # verbs).  Same lock domain as queue + jobs: every access goes
        # through self._cv, so the lock-order graph stays a single node.
        self.fleet = FleetMembership(lease=lease, miss_tolerance=lease_misses)
        # idempotency-key -> reply replay cache for retried submit/serve
        self._idempotent: Dict[str, dict] = {}
        self._idempotent_order: list[str] = []
        self._evictions_exported = 0
        # serve_tier replica groups: tier_id -> {script, args, flags,
        # job_ids, respawns, max_respawns}.  Mutated under the cv; the
        # runner loop's idle wakeups double as the respawn supervisor.
        self._tiers: Dict[str, dict] = {}
        # online serve->train deployments: online_id -> {tier_id,
        # trainer_job_id, capture_dir, checkpoint_dir, placement}.  The
        # serving replicas live in self._tiers (so the respawn supervisor
        # covers them); this record ties them to their trainer job and the
        # capture/checkpoint directories the loop pivots on.
        self._online: Dict[str, dict] = {}

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", self.port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(16)
        with self._cv:
            self._running = True
        if telemetry.enabled():
            # Fleet correlation + live scrape: mint the daemon's run_id now
            # (spawned jobs inherit it through their env) and start the HTTP
            # exporter when one is configured, with the fleet-merged
            # /aggregate view mounted next to the per-process endpoints.
            telemetry.flightdeck.activate()
            telemetry.flightdeck.add_endpoint(
                "/aggregate",
                lambda: ("application/json", json.dumps(self._fleet_snapshot())),
            )
        for target in (self._accept_loop, self._runner_loop):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        for job_id in list(self._serving):
            self._stop_serving_job(job_id)
        with self._cv:
            self._running = False
            self._cv.notify_all()
        if self._sock is not None:
            try:  # self-connect to unblock accept() — the reference's cancel_accept trick
                socket.create_connection(("127.0.0.1", self.port), timeout=1).close()
            except OSError:
                pass
            self._sock.close()
        # the daemon often outlives any single fit and may be killed rather
        # than exit cleanly — write its trace/metrics now, not at interpreter
        # exit (no-op when telemetry is disabled)
        telemetry.flush()

    # -- server internals ---------------------------------------------------
    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            if not self._running:
                conn.close()
                return
            threading.Thread(target=self._handle, args=(conn,), daemon=True).start()

    def _authorized(self, msg: dict) -> bool:
        return hmac.compare_digest(str(msg.get("secret", "")), self.secret)

    def _remember(self, idem: Optional[str], reply: dict) -> None:
        """Retain ``reply`` under the client's idempotency key (caller holds
        the cv) so a retried submit/serve replays the original outcome
        instead of double-enqueuing."""
        if not idem:
            return
        if idem not in self._idempotent:
            self._idempotent_order.append(idem)
            while len(self._idempotent_order) > _IDEMPOTENCY_CACHE:
                self._idempotent.pop(self._idempotent_order.pop(0), None)
        self._idempotent[idem] = reply

    def _handle(self, conn: socket.socket) -> None:
        try:
            # per-connection deadline: recv_data on a half-open client must
            # raise instead of pinning this handler thread forever
            conn.settimeout(self.handler_timeout)
            msg = recv_data(conn)
            if not self._authorized(msg):
                send_data(conn, {"status": "denied"})
                return
            action = msg.get("action")
            idem = msg.get("idempotency")
            if action == "submit":
                with self._cv:
                    reply = self._idempotent.get(idem) if idem else None
                    if reply is None:
                        job_id = uuid.uuid4().hex
                        self.jobs[job_id] = {"status": "queued", "output": "",
                                             "returncode": None, "metrics": None,
                                             "script": msg["script"],
                                             "args": msg.get("args", [])}
                        self._queue.append(job_id)
                        self._cv.notify()
                        reply = {"status": "queued", "job_id": job_id}
                        self._remember(idem, reply)
                send_data(conn, reply)
            elif action == "serve":
                # Host a long-running serving engine as a job: launched
                # detached (Popen) because the FIFO runner blocks until a
                # script exits and a serving loop never does.  The script is
                # expected to build a ServingEngine, install the /generate
                # endpoint, and block; its flightdeck exporter port is
                # forced on so the engine is reachable, and discoverable
                # through the usual discovery-file -> status-verb path.
                # Idempotency here guards the sequential-retry case (a client
                # whose reply was lost re-sends the same key); the replay
                # check happens before any process is spawned.
                with self._cv:
                    cached = self._idempotent.get(idem) if idem else None
                if cached is not None:
                    send_data(conn, cached)
                    return
                flags = msg.get("flags")
                job_id = self._spawn_serve_job(
                    msg["script"], list(msg.get("args", [])),
                    flags if isinstance(flags, dict) else {})
                reply = {"status": "serving", "job_id": job_id}
                with self._cv:
                    self._remember(idem, reply)
                send_data(conn, reply)
            elif action == "serve_tier":
                # N identical serving replicas as one supervised group —
                # the unit the ServingTier router fronts.  Each replica is
                # an ordinary serve job (own exporter, own log, own
                # job_id); the daemon tracks the group so tier_status
                # answers in one round trip and the runner loop's idle
                # wakeups respawn crashed replicas (capped per tier).
                with self._cv:
                    cached = self._idempotent.get(idem) if idem else None
                if cached is not None:
                    send_data(conn, cached)
                    return
                replicas = max(1, int(msg.get("replicas") or 1))
                flags = msg.get("flags")
                flags = dict(flags) if isinstance(flags, dict) else {}
                tier_id = uuid.uuid4().hex
                job_ids = [
                    self._spawn_serve_job(
                        msg["script"], list(msg.get("args", [])), flags,
                        extra_env={"DISTKERAS_TIER_ID": tier_id,
                                   "DISTKERAS_REPLICA_INDEX": str(i)})
                    for i in range(replicas)
                ]
                reply = {"status": "serving", "tier_id": tier_id,
                         "job_ids": list(job_ids)}
                with self._cv:
                    self._tiers[tier_id] = {
                        "script": msg["script"],
                        "args": list(msg.get("args", [])),
                        "flags": flags,
                        "job_ids": job_ids,
                        "respawns": 0,
                        "max_respawns": int(msg.get("max_respawns", 3)),
                    }
                    self._remember(idem, reply)
                send_data(conn, reply)
            elif action == "tier_status":
                with self._cv:
                    tier = self._tiers.get(msg.get("tier_id", ""))
                    job_ids = list(tier["job_ids"]) if tier else []
                if tier is None:
                    send_data(conn, {"status": "unknown"})
                else:
                    reps = []
                    for jid in job_ids:
                        job = self.jobs.get(jid)
                        if job is None:
                            continue
                        self._refresh_serving(jid, job)
                        reps.append({"job_id": jid,
                                     "status": job["status"],
                                     "http": self._job_http_address(job)})
                    with self._cv:
                        respawns = tier["respawns"]
                        cap = tier["max_respawns"]
                    send_data(conn, {
                        "status": "ok", "tier_id": msg.get("tier_id"),
                        "replicas": reps,
                        "serving": sum(1 for r in reps
                                       if r["status"] == "serving"),
                        "respawns": respawns, "max_respawns": cap})
            elif action == "stop_tier":
                with self._cv:
                    tier = self._tiers.pop(msg.get("tier_id", ""), None)
                    job_ids = list(tier["job_ids"]) if tier else []
                if tier is None:
                    send_data(conn, {"status": "unknown"})
                else:
                    stopped = sum(1 for jid in job_ids
                                  if self._stop_serving_job(jid))
                    send_data(conn, {"status": "stopped",
                                     "tier_id": msg.get("tier_id"),
                                     "stopped": stopped})
            elif action == "online_loop":
                # Co-schedule the whole serve->train loop on this fleet:
                # ``replicas`` serving jobs as one supervised tier (their
                # script installs a TrafficLog-backed /generate) plus one
                # detached trainer job (its script runs a WindowScheduler
                # over the shared capture directory and publishes verified
                # checkpoint steps the replicas' watcher hot-swaps in).
                # Placement is decided from the live leases up front and
                # recorded on the deployment so online_status can show
                # where the work was put.
                with self._cv:
                    cached = self._idempotent.get(idem) if idem else None
                if cached is not None:
                    send_data(conn, cached)
                    return
                from distkeras_tpu.online.scheduler import plan_placement
                replicas = max(1, int(msg.get("replicas") or 1))
                flags = msg.get("flags")
                flags = dict(flags) if isinstance(flags, dict) else {}
                online_id = uuid.uuid4().hex
                capture_dir = (msg.get("capture_dir")
                               or os.path.join(self.workdir, "online",
                                               online_id, "capture"))
                ckpt_dir = (msg.get("checkpoint_dir")
                            or os.path.join(self.workdir, "online",
                                            online_id, "ckpt"))
                os.makedirs(capture_dir, exist_ok=True)
                os.makedirs(ckpt_dir, exist_ok=True)
                with self._cv:
                    self.fleet.sweep()
                    members = self.fleet.snapshot()["members"]
                placement = plan_placement(members, replicas)
                loop_env = {"DISTKERAS_ONLINE_ID": online_id,
                            "DISTKERAS_CAPTURE_DIR": capture_dir,
                            "DISTKERAS_CKPT_DIR": ckpt_dir}
                tier_id = uuid.uuid4().hex
                job_ids = [
                    self._spawn_serve_job(
                        msg["script"], list(msg.get("args", [])), flags,
                        extra_env={**loop_env,
                                   "DISTKERAS_TIER_ID": tier_id,
                                   "DISTKERAS_REPLICA_INDEX": str(i)})
                    for i in range(replicas)
                ]
                trainer_job = self._spawn_serve_job(
                    msg["trainer_script"],
                    list(msg.get("trainer_args", [])), flags,
                    extra_env={**loop_env, "DISTKERAS_ONLINE_ROLE": "trainer"})
                reply = {"status": "online", "online_id": online_id,
                         "tier_id": tier_id, "job_ids": list(job_ids),
                         "trainer_job_id": trainer_job,
                         "capture_dir": capture_dir,
                         "checkpoint_dir": ckpt_dir,
                         "placement": placement}
                with self._cv:
                    self._tiers[tier_id] = {
                        "script": msg["script"],
                        "args": list(msg.get("args", [])),
                        "flags": flags,
                        "job_ids": job_ids,
                        "respawns": 0,
                        "max_respawns": int(msg.get("max_respawns", 3)),
                    }
                    self._online[online_id] = {
                        "tier_id": tier_id,
                        "trainer_job_id": trainer_job,
                        "capture_dir": capture_dir,
                        "checkpoint_dir": ckpt_dir,
                        "placement": placement,
                    }
                    self._remember(idem, reply)
                send_data(conn, reply)
            elif action == "online_status":
                with self._cv:
                    ent = self._online.get(msg.get("online_id", ""))
                    ent = dict(ent) if ent else None
                    tier = self._tiers.get(ent["tier_id"]) if ent else None
                    job_ids = list(tier["job_ids"]) if tier else []
                if ent is None:
                    send_data(conn, {"status": "unknown"})
                else:
                    reps = []
                    for jid in job_ids:
                        job = self.jobs.get(jid)
                        if job is None:
                            continue
                        self._refresh_serving(jid, job)
                        reps.append({"job_id": jid,
                                     "status": job["status"],
                                     "http": self._job_http_address(job)})
                    tjid = ent["trainer_job_id"]
                    tjob = self.jobs.get(tjid)
                    if tjob is not None:
                        self._refresh_serving(tjid, tjob)
                    # window/step progress straight off the filesystem —
                    # counting manifests keeps the daemon free of the
                    # accelerator-heavy checkpoint module
                    from distkeras_tpu.online.capture import published_windows
                    windows = len(published_windows(ent["capture_dir"]))
                    steps = 0
                    if os.path.isdir(ent["checkpoint_dir"]):
                        names = set(os.listdir(ent["checkpoint_dir"]))
                        steps = sum(
                            1 for d in names
                            if d.startswith("step_")
                            and d.endswith(".manifest.json")
                            and d[len("step_"):-len(".manifest.json")].isdigit()
                            and d[:-len(".manifest.json")] in names)
                    send_data(conn, {
                        "status": "ok",
                        "online_id": msg.get("online_id"),
                        "tier_id": ent["tier_id"],
                        "replicas": reps,
                        "serving": sum(1 for r in reps
                                       if r["status"] == "serving"),
                        "trainer": {"job_id": tjid,
                                    "status": (tjob["status"]
                                               if tjob else "unknown")},
                        "windows_published": windows,
                        "steps_published": steps,
                        "capture_dir": ent["capture_dir"],
                        "checkpoint_dir": ent["checkpoint_dir"],
                        "placement": ent["placement"]})
            elif action == "stop_online":
                with self._cv:
                    ent = self._online.pop(msg.get("online_id", ""), None)
                    tier = (self._tiers.pop(ent["tier_id"], None)
                            if ent else None)
                    job_ids = list(tier["job_ids"]) if tier else []
                if ent is None:
                    send_data(conn, {"status": "unknown"})
                else:
                    stopped = sum(1 for jid in job_ids
                                  if self._stop_serving_job(jid))
                    if self._stop_serving_job(ent["trainer_job_id"]):
                        stopped += 1
                    send_data(conn, {"status": "stopped",
                                     "online_id": msg.get("online_id"),
                                     "stopped": stopped})
            elif action == "stop_serving":
                job_id = msg.get("job_id", "")
                if self._stop_serving_job(job_id):
                    send_data(conn, {"status": "stopped", "job_id": job_id})
                else:
                    send_data(conn, {"status": "unknown"})
            elif action == "register":
                with self._cv:
                    self.fleet.sweep()
                    wid = self.fleet.register(
                        msg.get("worker_id") or None,
                        int(msg.get("workers") or 1), msg.get("host"))
                    reply = {"status": "ok", "worker_id": wid,
                             "lease": self.fleet.lease,
                             "epoch": self.fleet.epoch}
                    self._export_fleet_metrics()
                send_data(conn, reply)
            elif action == "heartbeat":
                with self._cv:
                    self.fleet.sweep()
                    alive = self.fleet.heartbeat(str(msg.get("worker_id") or ""))
                    reply = ({"status": "ok", "epoch": self.fleet.epoch}
                             if alive else {"status": "unknown"})
                    self._export_fleet_metrics()
                send_data(conn, reply)
            elif action == "deregister":
                with self._cv:
                    known = self.fleet.deregister(str(msg.get("worker_id") or ""))
                    reply = {"status": "ok" if known else "unknown",
                             "epoch": self.fleet.epoch}
                    self._export_fleet_metrics()
                send_data(conn, reply)
            elif action == "membership":
                with self._cv:
                    self.fleet.sweep()
                    reply = {"status": "ok", **self.fleet.snapshot()}
                    self._export_fleet_metrics()
                send_data(conn, reply)
            elif action == "status":
                job = self.jobs.get(msg.get("job_id", ""))
                if job is None:
                    send_data(conn, {"status": "unknown"})
                else:
                    self._refresh_serving(msg.get("job_id", ""), job)
                    # telemetry_dir / http / last_heartbeat let an operator
                    # find (and scrape) a wedged job without grepping the
                    # daemon log; all None while telemetry is off.
                    send_data(conn, {"status": job["status"], "output": job["output"],
                                     "returncode": job["returncode"],
                                     "telemetry_dir": job.get("telemetry_dir"),
                                     "http": self._job_http_address(job),
                                     "last_heartbeat": self._job_heartbeat(job),
                                     "serve_flags": job.get("serve_flags")})
            elif action == "list":
                with self._cv:
                    serving_ids = set(self._serving)
                for jid, j in list(self.jobs.items()):
                    if jid in serving_ids:
                        self._refresh_serving(jid, j)
                send_data(conn, {"status": "ok",
                                 "jobs": {k: v["status"] for k, v in self.jobs.items()}})
            elif action == "metrics":
                # Control-plane scrape of this process's telemetry registry:
                # Prometheus text (for scrapers / humans) plus the structured
                # snapshot, both JSON-safe for the restricted codec — and the
                # merged whole-fleet view of every job that reported metrics.
                reply = {"status": "ok",
                         "enabled": telemetry.enabled(),
                         "prometheus": telemetry.metrics.to_prometheus(),
                         "snapshot": telemetry.metrics.snapshot(),
                         "fleet": self._fleet_snapshot()}
                job = self.jobs.get(msg.get("job_id") or "")
                if job is not None:
                    # live scrape of a still-running job's /vars through its
                    # flightdeck exporter, instead of waiting for job exit
                    reply["live"] = self._job_live_vars(job)
                send_data(conn, reply)
            elif action == "aggregate":
                send_data(conn, {"status": "ok", **self._fleet_snapshot()})
            elif action == "slo_status":
                send_data(conn, {"status": "ok", **self._fleet_slo()})
            elif action == "ledger_status":
                send_data(conn, {"status": "ok", **self._fleet_ledger()})
            else:
                send_data(conn, {"status": "bad_request"})
        except TimeoutError:
            # handler deadline hit (half-open or glacial client) — drop the
            # connection, count it, keep the thread pool healthy
            if telemetry.enabled():
                telemetry.metrics.counter(
                    "punchcard_handler_timeouts_total",
                    help="handler sockets dropped at the connection deadline",
                ).inc()
        except (ConnectionError, ValueError, OSError):
            pass
        except Exception:
            # a handler crash on a daemon thread would otherwise vanish with
            # the connection — leave the blackbox behind, then let it surface
            telemetry.flightdeck.on_crash("punchcard._handle crashed")
            raise
        finally:
            conn.close()

    def _export_fleet_metrics(self) -> None:
        """Fleet gauges into the telemetry registry (caller holds the cv —
        registry updates are cheap and never block).  They ride the same
        flightdeck ``/vars`` + ``aggregate``-verb path as every other
        daemon metric."""
        if not telemetry.enabled():
            return
        telemetry.metrics.gauge(
            "fleet_members", help="workers holding a live lease"
        ).set(len(self.fleet.members))
        telemetry.metrics.gauge(
            "fleet_workers", help="summed logical workers across members"
        ).set(self.fleet.workers_total())
        telemetry.metrics.gauge(
            "fleet_membership_epoch",
            help="monotonic membership epoch (bumps on join/leave/evict)",
        ).set(self.fleet.epoch)
        delta = self.fleet.evictions - self._evictions_exported
        if delta:
            telemetry.metrics.counter(
                "fleet_evictions_total",
                help="workers evicted on a missed lease",
            ).inc(delta)
            self._evictions_exported = self.fleet.evictions

    def _job_env(self, job_id: str, ensure_http: bool = False) -> tuple:
        """Telemetry environment for a spawned job: its own telemetry
        subdirectory (so the ``aggregate`` verb can collect snapshots
        without jobs clobbering each other), the fleet run_id (dktrace
        merge joins on it), and an ephemeral flightdeck exporter when the
        daemon itself is scrape-able — or unconditionally for ``serve``
        jobs (``ensure_http``), whose /generate endpoint lives on it.
        Returns ``(env, tel_dir)``, both ``None`` when telemetry is off;
        the caller records ``tel_dir`` on the job dict under the cv."""
        if not telemetry.enabled():
            return None, None
        tel_dir = os.path.join(self.workdir, "telemetry", job_id)
        os.makedirs(tel_dir, exist_ok=True)
        env = dict(os.environ, DISTKERAS_TELEMETRY="1",
                   DISTKERAS_TELEMETRY_DIR=tel_dir,
                   DISTKERAS_RUN_ID=telemetry.flightdeck.run_id())
        if ensure_http or telemetry.flightdeck.http_port() is not None:
            env["DISTKERAS_TELEMETRY_HTTP"] = "0"
        return env, tel_dir

    def _spawn_serve_job(self, script: str, args: list, flags: dict,
                         extra_env: Optional[Dict[str, str]] = None) -> str:
        """Spawn one detached serving process (shared by the ``serve`` and
        ``serve_tier`` verbs and the tier respawn supervisor): write the
        script, build the job env with the exporter forced on (the
        ``/generate`` endpoint lives on it), Popen with a log file, record
        the job and its process under the cv.  Returns the new job_id."""
        job_id = uuid.uuid4().hex
        script_path = os.path.join(self.workdir, f"{job_id}.py")
        with open(script_path, "w") as f:
            f.write(script)
        job = {"status": "serving", "output": "", "returncode": None,
               "metrics": None, "script": script, "args": list(args),
               "log_path": None, "serve_flags": dict(flags)}
        env, tel_dir = self._job_env(job_id, ensure_http=True)
        if tel_dir is not None:
            job["telemetry_dir"] = tel_dir
        if job["serve_flags"] or extra_env:
            if env is None:  # telemetry off: _job_env built no env
                env = dict(os.environ)
            if job["serve_flags"]:
                # engine knobs (prefill_buckets, spec_tokens, ...) ride to
                # the child as JSON; the script reads them back via
                # serving.serve_flags() so one script serves many configs
                env["DISTKERAS_SERVE_FLAGS"] = json.dumps(job["serve_flags"])
            if extra_env:
                env.update(extra_env)
        log_path = os.path.join(self.workdir, f"{job_id}.log")
        job["log_path"] = log_path
        with open(log_path, "w") as log:
            proc = subprocess.Popen(
                [sys.executable, script_path, *map(str, args)],
                stdout=log, stderr=subprocess.STDOUT,
                cwd=self.workdir, env=env,
            )
        with self._cv:
            self.jobs[job_id] = job
            self._serving[job_id] = proc
            n_serving = len(self._serving)
        if telemetry.enabled():
            telemetry.metrics.gauge(
                "punchcard_serving_jobs",
                help="serve-verb engines currently hosted",
            ).set(n_serving)
        return job_id

    def _find_dead_replica(self) -> Optional[tuple]:
        """One crashed tier replica due a respawn, as ``(tier_id, job_id)``
        — or ``None``.  Caller holds the cv; ``poll()`` is a non-blocking
        reap.  Tiers out of respawn credits are skipped: their dead
        replicas stay visible through ``tier_status`` as failed instead of
        flapping forever."""
        for tier_id, tier in self._tiers.items():
            if tier["respawns"] >= tier["max_respawns"]:
                continue
            for jid in tier["job_ids"]:
                proc = self._serving.get(jid)
                if proc is not None and proc.poll() is not None:
                    return tier_id, jid
                if proc is None:
                    # a tier_status poll's _refresh_serving may reap the
                    # corpse first — the folded status is still a death
                    # ("stopped" is an explicit stop, never respawned)
                    job = self.jobs.get(jid) or {}
                    if job.get("status") in ("failed", "finished"):
                        return tier_id, jid
        return None

    def _respawn_replica(self, tier_id: str, dead_id: str) -> None:
        """Replace one crashed tier replica: fold the dead process into its
        job record (off-lock log read), burn one respawn credit, spawn the
        replacement into the same slot.  Runs on the runner thread."""
        job = self.jobs.get(dead_id)
        if job is not None:
            self._refresh_serving(dead_id, job)
        with self._cv:
            tier = self._tiers.get(tier_id)
            if (tier is None or dead_id not in tier["job_ids"]
                    or tier["respawns"] >= tier["max_respawns"]):
                return  # tier stopped / already handled / out of credits
            tier["respawns"] += 1
            index = tier["job_ids"].index(dead_id)
            script = tier["script"]
            args = list(tier["args"])
            flags = dict(tier["flags"])
        new_id = self._spawn_serve_job(
            script, args, flags,
            extra_env={"DISTKERAS_TIER_ID": tier_id,
                       "DISTKERAS_REPLICA_INDEX": str(index)})
        with self._cv:
            tier = self._tiers.get(tier_id)
            live = (tier is not None and index < len(tier["job_ids"])
                    and tier["job_ids"][index] == dead_id)
            if live:
                tier["job_ids"][index] = new_id
        if not live:
            # the tier was stopped while the replacement was starting —
            # reap the orphan instead of leaking a headless engine
            self._stop_serving_job(new_id)
            return
        if telemetry.enabled():
            telemetry.metrics.counter(
                "punchcard_tier_respawns_total",
                help="tier serve replicas respawned after a crash",
            ).inc()

    def _refresh_serving(self, job_id: str, job: dict) -> None:
        """Fold a serve job's process state into its status: a serving
        engine that exited did not finish — it died (or was stopped).
        The log read happens off-lock; the job/ _serving mutations go under
        the cv (GuardedMap only polices the map itself — mutations of the
        inner job dicts are invisible to it, so the discipline must hold by
        construction here)."""
        with self._cv:
            proc = self._serving.get(job_id)
        if proc is None or proc.poll() is None:
            return
        output = self._read_log(job)
        with self._cv:
            job["returncode"] = proc.returncode
            job["status"] = "failed" if proc.returncode else "finished"
            job["output"] = output
            self._serving.pop(job_id, None)

    def _stop_serving_job(self, job_id: str) -> bool:
        """Terminate a serving job; ``False`` when no such job is live.
        The pop is atomic under the cv, the terminate/wait runs off-lock."""
        with self._cv:
            proc = self._serving.pop(job_id, None)
            n_serving = len(self._serving)
        if proc is None:
            return False
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
        job = self.jobs.get(job_id)
        output = self._read_log(job) if job is not None else ""
        if job is not None:
            with self._cv:
                job["status"] = "stopped"
                job["returncode"] = proc.returncode
                job["output"] = output
        if telemetry.enabled():
            telemetry.metrics.gauge(
                "punchcard_serving_jobs",
                help="serve-verb engines currently hosted",
            ).set(n_serving)
        return True

    @staticmethod
    def _read_log(job: dict) -> str:
        path = job.get("log_path")
        if not path:
            return job.get("output", "")
        try:
            with open(path, encoding="utf-8", errors="replace") as fh:
                return fh.read()
        except OSError:
            return job.get("output", "")

    def _runner_loop(self) -> None:
        while True:
            respawn = None
            with self._cv:
                while self._running and not self._queue:
                    self._cv.wait(timeout=0.5)
                    # the runner's idle wakeups double as the lease sweeper:
                    # an expired worker is evicted (and the membership epoch
                    # bumped) within ~0.5 s even with no verb traffic ...
                    if self.fleet.sweep():
                        self._export_fleet_metrics()
                    # ... and as the tier supervisor: a crashed serve_tier
                    # replica is detected here and respawned off-lock below
                    respawn = self._find_dead_replica()
                    if respawn is not None:
                        break
                if not self._running:
                    return
                if respawn is None:
                    job_id = self._queue.pop(0)
                    # job lookup + status flip under the cv (previously both
                    # raced the handler threads from outside the lock)
                    job = self.jobs[job_id]
                    job["status"] = "running"
                    script = job["script"]
                    args = list(job["args"])
            if respawn is not None:
                # the spawn itself (log open + Popen) must not hold the cv
                self._respawn_replica(*respawn)
                continue
            script_path = os.path.join(self.workdir, f"{job_id}.py")
            with open(script_path, "w") as f:
                f.write(script)
            env, tel_dir = self._job_env(job_id)
            if tel_dir is not None:
                with self._cv:
                    job["telemetry_dir"] = tel_dir
            try:
                # the job_run span is dktrace merge's clock-skew anchor: a
                # job's own trace starts at its process-local perf origin,
                # and realigning it into the fleet timeline needs the
                # daemon-side dispatch window
                with telemetry.trace.span("job_run", job_id=job_id):
                    proc = subprocess.run(
                        [sys.executable, script_path, *map(str, args)],
                        capture_output=True, text=True, timeout=3600, cwd=self.workdir,
                        env=env,
                    )
                with self._cv:
                    job["output"] = proc.stdout + proc.stderr
                    job["returncode"] = proc.returncode
                outcome = "finished" if proc.returncode == 0 else "failed"
            except subprocess.TimeoutExpired:
                outcome = "timeout"
            if tel_dir is not None:
                with telemetry.trace.span("job_collect", job_id=job_id):
                    snapshot = _collect_job_snapshot(tel_dir)
                with self._cv:
                    job["metrics"] = snapshot
            if telemetry.enabled():
                telemetry.metrics.counter(
                    "punchcard_jobs_finished_total" if outcome == "finished"
                    else "punchcard_jobs_failed_total",
                    help="jobs the runner completed, by outcome",
                ).inc()
                if outcome != "finished":
                    # daemon-side blackbox for the crashed/wedged job: the
                    # ring holds its dispatch/collect spans and the fleet
                    # counters at failure time
                    telemetry.flightdeck.on_crash(
                        f"punchcard job {job_id} {outcome}",
                        extra={"job_id": job_id,
                               "returncode": job["returncode"],
                               "telemetry_dir": tel_dir})
                # flush per job: fleet runs must not lose telemetry that
                # would otherwise only be written at interpreter exit
                telemetry.flush()
            # status last: clients poll it as the completion signal, so the
            # job's fleet snapshot must already be in place when it flips
            with self._cv:
                job["status"] = outcome

    def _job_http_address(self, job: dict) -> Optional[str]:
        """The job's live flightdeck address, from the discovery file its
        exporter drops into the job telemetry dir.  Cached into the job map
        once resolved; ``None`` while flightdeck is off or the job has not
        come up yet."""
        addr = job.get("http")
        if addr:
            return addr
        tel_dir = job.get("telemetry_dir")
        if not tel_dir:
            return None
        for path in sorted(glob.glob(os.path.join(tel_dir, "flightdeck_*.json"))):
            try:
                with open(path, encoding="utf-8") as fh:
                    addr = json.load(fh).get("address")
            except (OSError, ValueError):
                continue
            if addr:
                job["http"] = addr
                return addr
        return None

    def _job_heartbeat(self, job: dict) -> Optional[float]:
        """Unix timestamp of the job's last observable activity: the live
        ``/healthz`` answer when its exporter is up, else the newest mtime
        in its telemetry dir, else ``None`` — how an operator spots a wedged
        job from the ``status`` verb alone."""
        addr = self._job_http_address(job)
        if addr:
            try:
                import urllib.request

                with urllib.request.urlopen(f"http://{addr}/healthz",
                                            timeout=1.0) as resp:
                    body = json.loads(resp.read().decode("utf-8"))
                hb = body.get("last_event_unix") or body.get("unix")
                if hb is not None:
                    return float(hb)
            except (OSError, ValueError):
                pass
        tel_dir = job.get("telemetry_dir")
        if tel_dir and os.path.isdir(tel_dir):
            try:
                mtimes = [os.path.getmtime(os.path.join(tel_dir, name))
                          for name in os.listdir(tel_dir)]
            except OSError:
                mtimes = []
            if mtimes:
                return max(mtimes)
        return None

    def _job_live_json(self, job: dict, path: str) -> Optional[dict]:
        """GET one JSON endpoint off a still-running job's flightdeck
        exporter; ``None`` when the job has no live exporter (or the scrape
        fails — a dead job must not fail the fleet view)."""
        addr = self._job_http_address(job)
        if not addr:
            return None
        try:
            import urllib.request

            with urllib.request.urlopen(f"http://{addr}/{path}",
                                        timeout=1.0) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except (OSError, ValueError):
            return None

    def _job_live_vars(self, job: dict) -> Optional[dict]:
        """Scrape a still-running job's ``/vars`` (live metrics snapshot +
        dynamics summary); ``None`` when the job has no live exporter."""
        return self._job_live_json(job, "vars")

    def _fleet_snapshot(self) -> dict:
        """Merged metric snapshot across every job that reported metrics —
        whole-fleet health in one scrape (``aggregate`` verb)."""
        from distkeras_tpu.telemetry.metrics import (
            merge_snapshots,
            prometheus_from_snapshot,
        )

        with self._cv:
            snaps = [j["metrics"] for j in self.jobs.values() if j.get("metrics")]
        merged = merge_snapshots(snaps)
        return {"jobs": len(snaps), "snapshot": merged,
                "prometheus": prometheus_from_snapshot(merged)}

    def _fleet_slo(self) -> dict:
        """Fleet SLO + rollup view (``slo_status`` verb): every live job's
        ``/slo`` engines plus the daemon's own, and the jobs' rollup rings
        merged onto one time axis — what ``dkmon status/watch/check`` and
        the future autoscaler verb consume."""
        from distkeras_tpu.telemetry import slo as _slo
        from distkeras_tpu.telemetry.flightdeck.rollup import merge_series

        engines: Dict[str, dict] = {}
        firing: List[dict] = []
        series: List[dict] = []

        def _collect(owner: str, status_by_source: Dict[str, dict]) -> None:
            for src, st in (status_by_source or {}).items():
                engines[f"{owner}:{src}"] = st
                for row in st.get("objectives", ()):
                    if row.get("firing"):
                        firing.append({"owner": owner, "source": src, **row})

        with self._cv:
            jobs = list(self.jobs.items())
        for jid, job in jobs:
            body = self._job_live_json(job, "slo")
            if body:
                _collect(jid, body.get("engines"))
            ts = self._job_live_json(job, "timeseries")
            if ts and ts.get("samples"):
                series.append(ts)
        _collect("daemon",
                 {src: e.status() for src, e in _slo.engines().items()})
        align = max((float(p.get("interval") or 1.0) for p in series),
                    default=1.0)
        merged = (merge_series(series, align_s=align) if series
                  else {"interval": align, "capacity": 0, "samples": []})
        return {"engines": engines, "firing": firing,
                "firing_count": len(firing), "timeseries": merged}

    def _fleet_ledger(self) -> dict:
        """Fleet accounting view (``ledger_status`` verb): every live job's
        ``/ledger`` table plus the daemon's own process, merged tenant-wise
        (bucket-exact, see :func:`accounting.merge_ledgers`) — what
        ``dkmon top --daemon host:port`` renders."""
        from distkeras_tpu.telemetry import accounting

        with self._cv:
            jobs = list(self.jobs.items())
        tables = []
        scraped = 0
        for jid, job in jobs:
            body = self._job_live_json(job, "ledger")
            if body and body.get("enabled"):
                tables.append(body)
                scraped += 1
        own = accounting.ledger_payload()
        if own.get("enabled"):
            tables.append(own)
        merged = accounting.merge_ledgers(tables)
        merged["enabled"] = bool(tables)
        merged["jobs"] = scraped
        return merged


class Job:
    """Client: package a training script, submit it, poll for the result
    (reference parity: ``job_deployment.py :: Job``)."""

    def __init__(self, host: str, port: int = DEFAULT_PORT, secret: str = "",
                 script: str = "", args: Optional[list] = None,
                 rpc_timeout: float = 30.0, rpc_retries: int = 3,
                 rpc_backoff: float = 0.1):
        self.host = host
        self.port = port
        self.secret = secret
        self.script = script
        self.args = args or []
        self.job_id: Optional[str] = None
        self.tier_id: Optional[str] = None
        self.online_id: Optional[str] = None
        #: socket deadline per RPC attempt (connect + send + recv)
        self.rpc_timeout = rpc_timeout
        #: transport-failure retries per RPC (0 = single attempt)
        self.rpc_retries = rpc_retries
        #: base of the capped exponential retry backoff (x0.5–1.0 jitter)
        self.rpc_backoff = rpc_backoff

    def _rpc(self, message: dict) -> Any:
        """One control-plane round trip, retried on transport faults.

        Retries are safe for every verb: reads are idempotent by nature and
        the mutating verbs (``submit``/``serve``) carry an idempotency key
        the daemon replays, so a retry after a lost *reply* cannot
        double-enqueue.  Backoff is capped exponential with jitter so a
        fleet of recovering clients doesn't stampede the daemon."""
        last_exc: Optional[Exception] = None
        for attempt in range(self.rpc_retries + 1):
            if attempt and self.rpc_backoff > 0:
                delay = min(2.0, self.rpc_backoff * (2 ** (attempt - 1)))
                time.sleep(delay * (0.5 + 0.5 * random.random()))
            try:
                sock = connect(self.host, self.port, timeout=self.rpc_timeout)
                try:
                    sock.settimeout(self.rpc_timeout)
                    send_data(sock, {**message, "secret": self.secret})
                    if _chaos.enabled():
                        # lost-reply injection: the request reached the
                        # daemon, the reply never reaches us — the exact
                        # scenario idempotency keys exist for
                        _chaos.fault("rpc_reply")
                    return recv_data(sock)
                finally:
                    sock.close()
            except (ConnectionError, TimeoutError, ValueError, OSError) as e:
                last_exc = e
        assert last_exc is not None
        raise last_exc

    def submit(self) -> str:
        # one idempotency key per logical submit, constant across _rpc's
        # transport retries: the daemon replays the original reply instead
        # of enqueuing a second job
        reply = self._rpc({"action": "submit", "script": self.script,
                           "args": self.args,
                           "idempotency": uuid.uuid4().hex})
        if reply.get("status") != "queued":
            raise RuntimeError(f"submission rejected: {reply}")
        self.job_id = reply["job_id"]
        return self.job_id

    def status(self) -> dict:
        if self.job_id is None:
            raise RuntimeError("job not submitted")
        return self._rpc({"action": "status", "job_id": self.job_id})

    def serve(self, flags: Optional[dict] = None) -> str:
        """Host this client's script as a long-running serving job
        (``serve`` verb).  The script should build a
        :class:`distkeras_tpu.serving.ServingEngine`, install the
        ``/generate`` endpoint, and block; once up, ``status()['http']``
        is its flightdeck address (serve jobs always get an exporter).

        ``flags`` (a JSON-safe dict of engine knobs — ``prefill_buckets``,
        ``spec_tokens``, ``num_slots``, ...) is delivered to the job as the
        ``DISTKERAS_SERVE_FLAGS`` env var; the script reads it back with
        :func:`distkeras_tpu.serving.serve_flags`, so one serving script
        can be deployed under many engine configurations."""
        msg = {"action": "serve", "script": self.script, "args": self.args,
               "idempotency": uuid.uuid4().hex}
        if flags is not None:
            msg["flags"] = dict(flags)
        reply = self._rpc(msg)
        if reply.get("status") != "serving":
            raise RuntimeError(f"serve rejected: {reply}")
        self.job_id = reply["job_id"]
        return self.job_id

    def stop_serving(self, job_id: Optional[str] = None) -> dict:
        """Terminate a serving job (``stop_serving`` verb); defaults to
        this client's job."""
        jid = job_id or self.job_id
        if jid is None:
            raise RuntimeError("no serving job to stop")
        return self._rpc({"action": "stop_serving", "job_id": jid})

    def serving_address(self, timeout: float = 30.0,
                        poll: float = 0.2) -> str:
        """Block until the serving job's flightdeck exporter is
        discoverable and return its ``host:port``."""
        deadline = time.monotonic() + timeout
        polls = 0
        while time.monotonic() < deadline:
            st = self.status()
            polls += 1
            if st.get("status") not in ("serving",):
                raise RuntimeError(f"serving job is {st.get('status')}: "
                                   f"{st.get('output', '')[-2000:]}")
            addr = st.get("http")
            if addr:
                return addr
            time.sleep(poll)
        # polls may be 0 (timeout <= 0): the message must not read from the
        # loop-local status — previously an UnboundLocalError
        raise TimeoutError(
            f"serving job {self.job_id} published no address after {polls} "
            f"poll(s) in {timeout}s")

    def serve_tier(self, replicas: int, flags: Optional[dict] = None,
                   max_respawns: int = 3) -> str:
        """Host ``replicas`` copies of this client's script as one
        supervised serving tier (``serve_tier`` verb).  Each replica is an
        ordinary serve job; the daemon respawns crashed replicas (up to
        ``max_respawns`` across the tier) from its runner loop's idle
        wakeups.  Returns the tier id (also stored on ``self.tier_id``);
        front the replicas with :class:`distkeras_tpu.serving.ServingTier`
        over :class:`~distkeras_tpu.serving.HttpReplica` handles built from
        :meth:`tier_addresses`."""
        msg = {"action": "serve_tier", "script": self.script,
               "args": self.args, "replicas": int(replicas),
               "max_respawns": int(max_respawns),
               "idempotency": uuid.uuid4().hex}
        if flags is not None:
            msg["flags"] = dict(flags)
        reply = self._rpc(msg)
        if reply.get("status") != "serving":
            raise RuntimeError(f"serve_tier rejected: {reply}")
        self.tier_id = reply["tier_id"]
        return self.tier_id

    def tier_status(self, tier_id: Optional[str] = None) -> dict:
        """Per-replica status of a serving tier (``tier_status`` verb):
        ``{"status": "ok", "replicas": [{"job_id", "status", "http"}, ...],
        "serving": N, "respawns": n, "max_respawns": cap}``."""
        tid = tier_id or self.tier_id
        if tid is None:
            raise RuntimeError("no tier to query")
        return self._rpc({"action": "tier_status", "tier_id": tid})

    def stop_tier(self, tier_id: Optional[str] = None) -> dict:
        """Terminate every replica of a serving tier (``stop_tier`` verb);
        defaults to this client's tier."""
        tid = tier_id or self.tier_id
        if tid is None:
            raise RuntimeError("no tier to stop")
        return self._rpc({"action": "stop_tier", "tier_id": tid})

    def online_loop(self, replicas: int, trainer_script: str,
                    trainer_args: Optional[list] = None,
                    flags: Optional[dict] = None,
                    capture_dir: Optional[str] = None,
                    checkpoint_dir: Optional[str] = None,
                    max_respawns: int = 3) -> str:
        """Deploy the whole serve->train loop on the daemon's fleet
        (``online_loop`` verb): this client's script as ``replicas``
        supervised serving jobs plus ``trainer_script`` as the co-scheduled
        retraining job, wired together through a shared capture directory
        and checkpoint directory (daemon-chosen under its workdir unless
        given).  Every spawned process sees ``DISTKERAS_ONLINE_ID`` /
        ``DISTKERAS_CAPTURE_DIR`` / ``DISTKERAS_CKPT_DIR`` in its
        environment; the serve script should install its ``/generate``
        endpoint with a :class:`~distkeras_tpu.online.TrafficLog` on the
        capture dir and watch the checkpoint dir for hot-swaps, the trainer
        script should run a :class:`~distkeras_tpu.online.WindowScheduler`
        over the same pair.  Returns the online id (also stored on
        ``self.online_id``; the tier id lands on ``self.tier_id``)."""
        msg: dict = {"action": "online_loop", "script": self.script,
                     "args": self.args, "replicas": int(replicas),
                     "trainer_script": trainer_script,
                     "trainer_args": list(trainer_args or []),
                     "max_respawns": int(max_respawns),
                     "idempotency": uuid.uuid4().hex}
        if flags is not None:
            msg["flags"] = dict(flags)
        if capture_dir is not None:
            msg["capture_dir"] = capture_dir
        if checkpoint_dir is not None:
            msg["checkpoint_dir"] = checkpoint_dir
        reply = self._rpc(msg)
        if reply.get("status") != "online":
            raise RuntimeError(f"online_loop rejected: {reply}")
        self.online_id = reply["online_id"]
        self.tier_id = reply["tier_id"]
        return self.online_id

    def online_status(self, online_id: Optional[str] = None) -> dict:
        """Progress view of an online deployment (``online_status`` verb):
        serving replica statuses, trainer job status, and the loop's window
        and checkpoint-step counts read straight off the shared
        directories — ``{"status": "ok", "replicas": [...], "serving": N,
        "trainer": {"job_id", "status"}, "windows_published": n,
        "steps_published": m, "placement": {...}, ...}``."""
        oid = online_id or self.online_id
        if oid is None:
            raise RuntimeError("no online deployment to query")
        return self._rpc({"action": "online_status", "online_id": oid})

    def stop_online(self, online_id: Optional[str] = None) -> dict:
        """Tear down an online deployment — every serving replica plus the
        trainer job (``stop_online`` verb); defaults to this client's."""
        oid = online_id or self.online_id
        if oid is None:
            raise RuntimeError("no online deployment to stop")
        return self._rpc({"action": "stop_online", "online_id": oid})

    def tier_addresses(self, timeout: float = 30.0,
                       poll: float = 0.2) -> list:
        """Block until every tier replica has published its flightdeck
        address; returns ``["host:port", ...]`` ordered by replica slot."""
        deadline = time.monotonic() + timeout
        st: dict = {}
        while time.monotonic() < deadline:
            st = self.tier_status()
            reps = st.get("replicas", [])
            if reps and all(r.get("status") == "serving" and r.get("http")
                            for r in reps):
                return [r["http"] for r in reps]
            time.sleep(poll)
        raise TimeoutError(
            f"tier {self.tier_id} not fully addressable after {timeout}s: "
            f"{st}")

    def metrics(self, job_id: Optional[str] = None) -> dict:
        """Scrape the daemon's telemetry registry (``metrics`` verb):
        ``{"status": "ok", "enabled": ..., "prometheus": <text>,
        "snapshot": {...}, "fleet": {"jobs": N, "snapshot": <merged>,
        "prometheus": <text>}}`` — ``fleet`` is the whole-fleet merge of
        every finished job's metric snapshot.  With a ``job_id`` (defaults
        to this client's submitted job) the reply also carries ``live``:
        that job's ``/vars`` scraped through its flightdeck exporter while
        it is still running (``None`` when flightdeck is off)."""
        msg: dict = {"action": "metrics"}
        jid = job_id or self.job_id
        if jid:
            msg["job_id"] = jid
        return self._rpc(msg)

    def aggregate(self) -> dict:
        """Fleet-wide metric merge only (``aggregate`` verb): counters
        summed, gauges max'd (mean alongside), histograms merged on their
        bounded-bucket representation."""
        return self._rpc({"action": "aggregate"})

    def slo_status(self) -> dict:
        """Fleet SLO view (``slo_status`` verb): ``{"engines": {"<owner>:
        <source>": <status>}, "firing": [...], "firing_count": N,
        "timeseries": <merged rollup>}`` — every live job's ``/slo``
        engines plus the daemon's own, and the jobs' rollup rings merged
        onto one time axis.  ``dkmon status --daemon host:port`` renders
        this; ``dkmon check`` gates on ``firing_count``."""
        return self._rpc({"action": "slo_status"})

    def ledger_status(self) -> dict:
        """Fleet per-tenant accounting view (``ledger_status`` verb): every
        live job's ``/ledger`` table plus the daemon's own, merged
        tenant-wise with shares recomputed over the merged totals.  ``dkmon
        top --daemon host:port`` renders this."""
        return self._rpc({"action": "ledger_status"})

    def wait(self, timeout: float = 300.0, poll: float = 0.2) -> dict:
        # monotonic, not wall-clock: an NTP step mid-poll must not shrink or
        # stretch the deadline (dklint DK106)
        deadline = time.monotonic() + timeout
        st: Optional[dict] = None
        polls = 0
        while time.monotonic() < deadline:
            st = self.status()
            polls += 1
            if st["status"] in ("finished", "failed", "timeout"):
                return st
            time.sleep(poll)
        # with timeout <= 0 the loop never runs; st stays None (previously
        # this raise hit an UnboundLocalError)
        last = st["status"] if st is not None else "unpolled"
        raise TimeoutError(
            f"job {self.job_id} still {last} after {polls} poll(s) in "
            f"{timeout}s")
