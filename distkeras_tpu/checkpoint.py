"""Checkpoint / resume — mid-training persistence of the center variable.

The reference has nothing in-tree (SURVEY.md §5.4: users call
``model.save()`` on the returned Keras model; a dead parameter server loses
the run).  Here the full training state — center params, per-worker local
replicas, optimizer state, rule state (clocks/anchors), epoch counter —
checkpoints through Orbax, so an interrupted distributed run resumes exactly
(bitwise, given the same data order seed).

Saves are asynchronous (``ocp.AsyncCheckpointer``): the host thread returns
as soon as the state is snapshotted, so per-epoch checkpointing stays off
the training path; ``CheckpointManager.wait()`` (called by trainers at the
end of the epoch loop, and implicitly before any restore) flushes the queue.

**Verified publication.**  A step is *published* — visible to restores,
watchers, GC, and the serving tier — only once a ``step_N.manifest.json``
commit record sits next to its directory: per-file sha256 + sizes + step +
run id, written tmp + fsync + ``os.replace`` (+ parent-dir fsync) after the
orbax commit landed.  :func:`verify_checkpoint` checks a published step
against its manifest (``fast`` = existence + sizes, ``full`` = digests);
every restore path verifies before load, renames a failing step aside
(``step_N.corrupt`` + ``checkpoint_quarantined_total``), and falls back to
the newest step that does verify — so a torn write or a flipped bit can
cost at most one checkpoint interval, never the run or the serving fleet.
Orbax directories without a manifest are *unverified* (a crash between the
orbax write and the manifest commit, another process's in-flight save, or a
pre-manifest checkpoint — adopt those explicitly via
:func:`write_manifest`): never restored, never GC'd, never quarantined.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Any, Optional

import jax
import numpy as np

from distkeras_tpu import chaos as _chaos
from distkeras_tpu import telemetry

__all__ = [
    "save_checkpoint", "restore_checkpoint", "restore_center",
    "model_state_worker_mean", "latest_step",
    "checkpoint_num_workers", "CheckpointManager", "CheckpointWatcher",
    "save_data_state", "restore_data_state",
    "manifest_path", "write_manifest", "verify_checkpoint", "verify_failure",
    "quarantine_step", "committed_steps",
]

_CHECKPOINTER = None
_PYTREE_CHECKPOINTER = None


def _checkpointer():
    """Singleton async checkpointer on the current (non-deprecated) Orbax
    API: ``AsyncCheckpointer(StandardCheckpointHandler)`` with explicit
    ``args.StandardSave/StandardRestore`` (the round-1 ``PyTreeCheckpointer``
    is deprecated upstream)."""
    global _CHECKPOINTER
    if _CHECKPOINTER is None:
        import orbax.checkpoint as ocp

        _CHECKPOINTER = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return _CHECKPOINTER


def _pytree_checkpointer():
    """Singleton synchronous PyTree checkpointer for the partial
    (PLACEHOLDER) restores — built once, like :func:`_checkpointer`, instead
    of leaking a fresh instance per elastic resume."""
    global _PYTREE_CHECKPOINTER
    if _PYTREE_CHECKPOINTER is None:
        import orbax.checkpoint as ocp

        _PYTREE_CHECKPOINTER = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
    return _PYTREE_CHECKPOINTER


# ------------------------------------------------------ verified publication

#: (directory, step) pairs whose orbax save has been enqueued but whose
#: manifest has not been published yet.  In-process bookkeeping only — it
#: mirrors exactly the window a real crash would leave on disk (orbax dir
#: without a manifest), so losing it to a crash loses nothing.
_PENDING: list = []
_PENDING_LOCK = threading.Lock()

#: (manifest path) -> (manifest stat, per-file stats) recorded when a step
#: passed a FULL digest verify — skips re-hashing multi-GB state when one
#: resume sequence (worker-count probe, center restore, model-state reduce)
#: re-resolves the same step several times.  A memo hit still stats every
#: file: any size/mtime change since the digests were proven (a republish,
#: or damage landing after the verify) drops the memo and re-hashes.
_VERIFIED: dict = {}


def manifest_path(directory: str, step: int) -> str:
    """The ``step_<n>.manifest.json`` commit record published after the
    orbax save lands.  A plain file, so :func:`committed_steps`'s digit
    parse never mistakes it for a step directory."""
    return os.path.join(os.path.abspath(directory),
                        f"step_{step}.manifest.json")


def _fsync_dir(path: str) -> None:
    """Make a directory entry durable (the rename itself, not just the
    renamed bytes).  Best-effort: not every filesystem lets you open or
    fsync a directory."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_write_json(path: str, obj) -> None:
    """tmp + fsync + ``os.replace`` + parent-dir fsync: a reader sees the
    old file or the new file, never a torn one — and the new one survives
    power loss once this returns."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(obj, fh, sort_keys=True)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))


def _step_files(step_dir: str) -> list:
    """Every regular file under a step directory, as sorted relative paths
    — the manifest's (and verify's) stable enumeration order."""
    out = []
    for root, dirs, files in os.walk(step_dir):
        dirs.sort()
        for name in sorted(files):
            out.append(os.path.relpath(os.path.join(root, name), step_dir))
    return out


def _sha256_file(path: str):
    h = hashlib.sha256()
    size = 0
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
            size += len(chunk)
    return h.hexdigest(), size


def write_manifest(directory: str, step: int) -> str:
    """Hash a committed ``step_<n>`` directory and publish its commit
    record.  Called automatically as async saves land; call it directly
    only to *adopt* a checkpoint written by an external (pre-manifest)
    writer into the verified set."""
    directory = os.path.abspath(directory)
    step_dir = os.path.join(directory, f"step_{step}")
    files = {}
    with telemetry.trace.span("checkpoint_publish", phase="ckpt",
                              step=int(step)):
        for rel in _step_files(step_dir):
            digest, size = _sha256_file(os.path.join(step_dir, rel))
            files[rel] = {"sha256": digest, "bytes": size}
        from distkeras_tpu.telemetry.flightdeck import correlate

        path = manifest_path(directory, step)
        _atomic_write_json(path, {
            "version": 1,
            "step": int(step),
            "run_id": correlate.run_id(),
            "files": files,
        })
    return path


def _publish(directory: str, step: int) -> None:
    """Publish one landed save: chaos ``ckpt_commit`` site (kill/delay in
    the committed-but-unpublished window), manifest write, then the
    post-publish corruption site (torn/flipped bytes the manifest must
    catch on the next verify)."""
    if _chaos.enabled():
        _chaos.fault("ckpt_commit")
    write_manifest(directory, step)
    if telemetry.enabled():
        telemetry.metrics.counter(
            "checkpoints_published_total",
            help="checkpoint manifests committed (verified-publication record)",
        ).inc()
    if _chaos.enabled():
        step_dir = os.path.join(directory, f"step_{step}")
        _chaos.corrupt_ckpt(
            os.path.join(step_dir, rel) for rel in _step_files(step_dir))


def _publish_pending(purge_missing: bool = False) -> None:
    """Publish manifests for every pending save whose final ``step_<n>``
    directory exists — orbax renames the directory into place only at
    commit, so the listing alone is commit evidence.  ``purge_missing``
    (set after a clean flush) drops entries whose save provably failed."""
    with _PENDING_LOCK:
        entries = list(_PENDING)
    for entry in entries:
        directory, step = entry
        if os.path.isdir(os.path.join(directory, f"step_{step}")):
            with _PENDING_LOCK:
                if entry not in _PENDING:
                    continue  # another thread claimed it
                _PENDING.remove(entry)
            # a raise here (chaos kill_commit, ENOSPC) leaves the step
            # unpublished for good — exactly the on-disk state a real
            # crash in this window leaves behind
            _publish(directory, step)
        elif purge_missing:
            with _PENDING_LOCK:
                if entry in _PENDING:
                    _PENDING.remove(entry)


def wait_until_finished() -> None:
    """Block until every in-flight async save has committed, then publish
    the manifests that make those commits visible."""
    try:
        if _CHECKPOINTER is not None:
            with telemetry.trace.span("checkpoint_flush", phase="ckpt"):
                _CHECKPOINTER.wait_until_finished()
    finally:
        # even when the flush re-raises a failed async save, the saves
        # that DID land still publish (train_with_recovery resumes from
        # them); only a clean flush proves a missing dir means a dead
        # save rather than one still in flight
        _publish_pending()
    _publish_pending(purge_missing=True)


def save_checkpoint(directory: str, state: Any, step: int,
                    force: bool = False) -> str:
    """Write training state under ``directory/step_N`` (async); returns the
    path.  Call :func:`wait_until_finished` before reading it back.

    ``force=True`` overwrites an existing ``step_N`` — the mid-epoch
    (datapipe) save path, where the same step id is re-saved as the block
    cursor advances and finally superseded by the epoch-boundary save.  A
    forced save flushes the async queue first so it cannot race an
    in-flight write to the same path."""
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    path = os.path.join(directory, f"step_{step}")
    entry = (directory, int(step))
    if not force and os.path.isdir(path) \
            and not os.path.exists(manifest_path(directory, step)):
        # an orbax dir with no manifest is an orphan from a crash between
        # the orbax commit and the manifest publish: nothing will ever
        # restore it, so the re-save of its step overwrites it
        force = True
    # "checkpoint_enqueue" covers only the synchronous part of an async
    # save: the host snapshot plus handing the write to Orbax's thread.
    with telemetry.trace.span("checkpoint_enqueue", phase="ckpt", step=int(step)):
        host_state = jax.tree.map(np.asarray, state)
        if force:
            # the step is being superseded: retract its pending record and
            # its published manifest FIRST, so the stale manifest can never
            # describe (and a reader never verify against) the replacement
            # bytes orbax is about to write
            with _PENDING_LOCK:
                if entry in _PENDING:
                    _PENDING.remove(entry)
            try:
                os.remove(manifest_path(directory, step))
            except FileNotFoundError:
                pass
            wait_until_finished()
        _checkpointer().save(
            path, args=ocp.args.StandardSave(host_state), force=force)
    # orbax's save() waited for every *previous* save internally, so those
    # are committed now — publish their manifests before registering this
    # one (whose manifest lands at the next flush / save)
    with _PENDING_LOCK:
        _PENDING.append(entry)
    _publish_pending()
    if telemetry.enabled():
        telemetry.metrics.counter(
            "checkpoints_saved_total", help="async checkpoint saves enqueued"
        ).inc()
    return path


def data_state_path(directory: str, step: int) -> str:
    """The ``step_<n>_data.json`` sidecar carrying a step's
    :class:`~distkeras_tpu.datapipe.DataState`.  A plain file (no ``step_<n>``
    *directory* name), so :func:`committed_steps`'s digit parse never
    mistakes it for a checkpoint step."""
    return os.path.join(os.path.abspath(directory), f"step_{step}_data.json")


def save_data_state(directory: str, data_state, step: int) -> str:
    """Write the data checkpoint sidecar for ``step`` — synchronous (a few
    hundred bytes), atomic, and durable (tmp + fsync + rename + dir fsync),
    so a crash can never leave a half-written cursor next to a committed
    model step, and power loss cannot un-write one that was reported
    saved."""
    path = data_state_path(directory, step)
    _atomic_write_json(path, data_state.to_json())
    return path


def restore_data_state(directory: str, step: Optional[int] = None):
    """The :class:`~distkeras_tpu.datapipe.DataState` saved with ``step``
    (default: latest), or None — model-only checkpoints (pre-datapipe runs,
    external writers) resume with the legacy epoch-boundary RNG
    fast-forward instead."""
    from distkeras_tpu.datapipe.state import DataState

    if step is None:
        step = latest_step(directory)
        if step is None:
            return None
    path = data_state_path(directory, step)
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as fh:
        return DataState.from_json(json.load(fh))


def committed_steps(directory: str) -> list:
    """*Published* steps: a ``step_<n>.manifest.json`` commit record next
    to a final ``step_<n>`` directory — readable cross-process with no
    flush.  Orbax dirs without a manifest (in-flight async saves, crashes
    between the orbax write and the manifest commit) and quarantined
    ``step_<n>.corrupt`` renames do not count."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return []
    names = set(os.listdir(directory))
    suffix = ".manifest.json"
    out = []
    for d in names:
        if d.startswith("step_") and d.endswith(suffix):
            num = d[len("step_"):-len(suffix)]
            if num.isdigit() and f"step_{num}" in names:
                out.append(int(num))
    return sorted(out)


def _orbax_step_dirs(directory: str) -> list:
    """Steps with a final orbax dir, manifested or not — the pre-manifest
    commit evidence.  Restores never trust this alone; it exists for the
    recovery paths that must *see* an unpublished step (to avoid colliding
    with or deleting it) without ever loading it."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return []
    return sorted(
        int(d.split("_", 1)[1])
        for d in os.listdir(directory)
        if d.startswith("step_") and d.split("_", 1)[1].isdigit()
    )


def latest_step(directory: str) -> Optional[int]:
    wait_until_finished()  # a step only counts once its async save committed
    steps = committed_steps(directory)
    return steps[-1] if steps else None


def verify_failure(directory: str, step: int,
                   mode: str = "fast") -> Optional[str]:
    """Why ``step`` fails verification against its manifest, or ``None``
    when it passes.  ``fast`` checks every manifested file exists at its
    recorded size (catches torn writes); ``full`` additionally re-hashes
    every file (catches bit flips — sizes intact, digests not).
    ``off`` always passes."""
    if mode not in ("off", "fast", "full"):
        raise ValueError(f"verify mode must be off|fast|full, got {mode!r}")
    if mode == "off":
        return None
    directory = os.path.abspath(directory)
    mpath = manifest_path(directory, step)
    try:
        with open(mpath, "r", encoding="utf-8") as fh:
            manifest = json.load(fh)
        files = manifest["files"]
    except FileNotFoundError:
        return (f"step {step} has no manifest (in-flight save, crashed "
                "publish, or pre-manifest checkpoint)")
    except (ValueError, KeyError, OSError) as e:
        return f"step {step} manifest unreadable: {e}"
    step_dir = os.path.join(directory, f"step_{step}")
    hash_files = mode == "full"
    if hash_files:
        memo = _VERIFIED.get(mpath)
        if memo is not None:
            try:
                st = os.stat(mpath)
                if memo[0] == (st.st_mtime_ns, st.st_size):
                    hash_files = False  # digests proven; stats re-checked below
                else:
                    _VERIFIED.pop(mpath, None)
                    memo = None
            except OSError:
                memo = None
    file_stats = []
    for rel in sorted(files):
        full = os.path.join(step_dir, rel)
        want = files[rel]
        try:
            st = os.stat(full)
        except OSError:
            return f"step {step}: {rel} missing"
        if st.st_size != int(want["bytes"]):
            return (f"step {step}: {rel} is {st.st_size} bytes, "
                    f"manifest says {want['bytes']}")
        if mode == "full" and not hash_files:
            # memo hit: the digests were proven earlier — but only for the
            # bytes as they were THEN; any stat drift since re-hashes
            if (rel, st.st_size, st.st_mtime_ns) not in memo[1]:
                _VERIFIED.pop(mpath, None)
                return verify_failure(directory, step, mode)
        if hash_files:
            digest, _size = _sha256_file(full)
            if digest != want["sha256"]:
                return f"step {step}: {rel} sha256 mismatch"
            file_stats.append((rel, st.st_size, st.st_mtime_ns))
    if hash_files:
        try:
            st = os.stat(mpath)
            _VERIFIED[mpath] = ((st.st_mtime_ns, st.st_size),
                                frozenset(file_stats))
        except OSError:
            pass
    return None


def verify_checkpoint(directory: str, step: int,
                      mode: str = "fast") -> bool:
    """Whether ``step`` passes manifest verification (see
    :func:`verify_failure` for the mode semantics and the reason text)."""
    return verify_failure(directory, step, mode) is None


def quarantine_step(directory: str, step: int, reason: str = "") -> str:
    """Move a corrupt step out of the restorable set: ``step_N`` →
    ``step_N.corrupt`` (suffix-numbered if that name is taken), with its
    manifest and data sidecar renamed alongside for forensics.  The digit
    parse in :func:`committed_steps` never matches the renamed artifacts,
    so quarantine is also un-publication.  Writer-side only — serving
    replicas reject and keep polling instead (they don't own the dir)."""
    directory = os.path.abspath(directory)
    src = os.path.join(directory, f"step_{step}")
    dst = src + ".corrupt"
    n = 0
    while os.path.exists(dst) or os.path.exists(dst + ".manifest.json"):
        n += 1
        dst = f"{src}.corrupt.{n}"
    if os.path.isdir(src):
        os.replace(src, dst)
    mpath = manifest_path(directory, step)
    _VERIFIED.pop(mpath, None)
    try:
        os.replace(mpath, dst + ".manifest.json")
    except FileNotFoundError:
        pass
    try:
        os.replace(data_state_path(directory, step), dst + "_data.json")
    except FileNotFoundError:
        pass
    _fsync_dir(directory)
    if telemetry.enabled():
        telemetry.metrics.counter(
            "checkpoint_quarantined_total",
            help="corrupt checkpoint steps renamed aside (step_N.corrupt)",
        ).inc()
        # the reason lands in the trace (spans carry attrs; there is no
        # instant-event API) so a postmortem can see WHAT failed, not
        # just that something did
        with telemetry.trace.span("checkpoint_quarantine", phase="ckpt",
                                  step=int(step), reason=reason[:200]):
            pass
    return dst


def _resolve_verified(directory: str, step: Optional[int],
                      mode: str = "full") -> int:
    """The step a restore may actually load: verify first; quarantine a
    corrupt step and fall back to the newest one that verifies.  An
    explicitly requested step without a manifest raises instead of
    falling back — it may be another process's in-flight save (never
    rename it) or a legacy checkpoint (adopt via :func:`write_manifest`)."""
    wait_until_finished()
    directory = os.path.abspath(directory)
    if step is not None:
        reason = verify_failure(directory, step, mode)
        if reason is None:
            return int(step)
        if not os.path.exists(manifest_path(directory, step)):
            raise FileNotFoundError(
                f"cannot restore unverified step under {directory}: {reason}")
        quarantine_step(directory, step, reason)
    while True:
        steps = committed_steps(directory)
        if not steps:
            raise FileNotFoundError(
                f"no verified checkpoints under {directory}")
        newest = steps[-1]
        reason = verify_failure(directory, newest, mode)
        if reason is None:
            return newest
        quarantine_step(directory, newest, reason)


class CheckpointWatcher:
    """Newest-step watcher over a checkpoint directory — the train→serve
    bridge.  ``poll()`` returns the newest *verified* step the first time
    it is seen, ``None`` otherwise.

    Built on :func:`committed_steps` (manifest listing = commit record),
    NOT :func:`latest_step`: the latter flushes *this* process's async save
    queue, which is meaningless — and wrong to wait on — when the trainer
    writing the checkpoints is a different process.  An orbax directory
    whose manifest has not been published yet (an in-flight async save, or
    a crash between the orbax write and the manifest commit) is invisible
    here by construction, and a published step must additionally pass a
    ``fast`` size verify before it is surfaced — a corrupt newest step is
    skipped (older new steps still surface), never returned and never
    touched (quarantine is the writer's job).  With ``start_after``
    omitted, the watcher baselines at the newest step already on disk at
    construction, so only steps committed *afterwards* fire (a serving
    replica that just loaded step N must not be told to hot-swap to step
    N).  Pass ``start_after=-1`` to see every committed step including
    pre-existing ones."""

    def __init__(self, directory: str,
                 start_after: Optional[int] = None):
        self.directory = directory
        if start_after is None:
            steps = committed_steps(directory)
            start_after = steps[-1] if steps else -1
        self.last_step = int(start_after)

    def poll(self) -> Optional[int]:
        """The newest verified step if it is newer than anything reported
        before, else ``None``.  Intermediate steps are skipped on purpose:
        a serving fleet swaps to the freshest params, not through history."""
        for step in reversed(committed_steps(self.directory)):
            if step <= self.last_step:
                return None
            if verify_failure(self.directory, step, "fast") is None:
                self.last_step = step
                return step
            # corrupt (or mid-rewrite): leave last_step alone so a later
            # poll re-checks; fast mode is stat-only, so re-checks are cheap
        return None


def restore_checkpoint(directory: str, step: Optional[int] = None,
                       like: Any = None, verify: str = "full") -> Any:
    """Load training state; ``like`` (a template pytree, e.g. a freshly built
    TrainState) restores exact structure/dtypes and device placement.

    Verifies before load (default ``full`` — a bit flip preserves sizes, so
    only digests prove the bytes): a corrupt step is quarantined and the
    newest verified one loads instead; ``verify="off"`` restores blind
    (external checkpoints without manifests)."""
    import orbax.checkpoint as ocp

    path = _step_path(directory, step, verify)
    template = jax.tree.map(np.asarray, like) if like is not None else None
    restored = _checkpointer().restore(
        path, args=ocp.args.StandardRestore(template)
    )
    if like is not None:
        # re-place on the same shardings as the template
        return jax.tree.map(
            lambda tpl, val: jax.device_put(val, tpl.sharding)
            if hasattr(tpl, "sharding")
            else val,
            like,
            restored,
        )
    return restored


def _step_path(directory: str, step: Optional[int],
               verify: str = "full") -> str:
    """Resolve the directory a restore will read — verified (quarantine +
    newest-verified fallback, see :func:`_resolve_verified`) unless the
    caller opted out with ``verify="off"``."""
    if verify == "off":
        wait_until_finished()
        if step is None:
            step = latest_step(directory)
            if step is None:
                raise FileNotFoundError(f"no checkpoints under {directory}")
    else:
        step = _resolve_verified(directory, step, verify)
    return os.path.join(os.path.abspath(directory), f"step_{step}")


def _metadata_tree(path: str) -> dict:
    meta = _checkpointer().metadata(path)
    tree = getattr(meta, "item_metadata", meta)
    tree = getattr(tree, "tree", tree)
    if not isinstance(tree, dict):
        # the getattr chain above tracks Orbax's metadata API (validated
        # against orbax-checkpoint 0.11.x); a release that reshapes it again
        # should fail here by name, not with a KeyError downstream
        raise RuntimeError(
            "could not read the checkpoint metadata tree as a dict (got "
            f"{type(tree).__name__}) — the installed orbax-checkpoint "
            "version exposes an unexpected metadata layout; "
            "distkeras_tpu.checkpoint expects the 0.11.x "
            "item_metadata/.tree API"
        )
    return tree


def restore_center(
    directory: str, step: Optional[int] = None,
    include_model_state: bool = True,
) -> dict:
    """Partial restore for elastic resume: only the center variable, its
    rule state, the model state, and the epoch counter leave disk; the
    per-worker subtrees (local replicas, optimizer state, rule locals,
    rngs) — ~3N x the model size at N workers — restore as Orbax
    placeholders, i.e. are never read.

    ``include_model_state=False`` additionally placeholders the per-worker
    ``[N, ...]`` model-state stack — pair with
    :func:`model_state_worker_mean`, which reduces that stack leaf by leaf
    instead of materialising all of it at once."""
    import orbax.checkpoint as ocp

    path = _step_path(directory, step)
    tree = _metadata_tree(path)
    keep = ("center_params", "center_rule", "epoch")
    if include_model_state:
        keep = keep + ("model_state",)

    def template_for(key, sub):
        if key in keep:
            return jax.tree.map(
                lambda m: jax.ShapeDtypeStruct(tuple(m.shape), m.dtype), sub
            )
        return jax.tree.map(lambda m: ocp.PLACEHOLDER, sub)

    template = {k: template_for(k, v) for k, v in tree.items()}
    # PLACEHOLDER is a PyTree-handler feature (the Standard handler rejects
    # it); both handlers share the on-disk format, so reading a
    # StandardSave checkpoint through PyTreeRestore is exact.
    restored = _pytree_checkpointer().restore(
        path, args=ocp.args.PyTreeRestore(item=template)
    )
    return {k: restored[k] for k in keep}


def worker_mean(x: np.ndarray) -> np.ndarray:
    """Mean over the leading (workers) axis with resume-grade dtype care:
    accumulate in float64 (bf16 leaves don't round twice), round integer
    leaves to nearest instead of truncating."""
    x = np.asarray(x)
    m = x.astype(np.float64).mean(axis=0)
    if np.issubdtype(x.dtype, np.integer):
        m = np.rint(m)
    return m.astype(x.dtype)


def model_state_worker_mean(
    directory: str, step: Optional[int] = None,
    host_bytes_budget: int = 256 * 1024**2,
):
    """Collapse the checkpointed per-worker ``[N_old, ...]`` model-state
    stack to its worker mean WITHOUT materialising the whole stack on host.

    Elastic resume at a new worker count needs only the mean (the same
    semantic ``sync_model_state`` applies at every commit), but a naive
    restore reads all ``N_old x`` model-state bytes into one host tree —
    for large stateful models exactly the host spike the sharded training
    path avoids.  Instead leaves restore in groups whose combined stack
    size stays under ``host_bytes_budget`` (every other array in the
    checkpoint is an Orbax PLACEHOLDER, i.e. never read) and reduce
    immediately, bounding peak host memory without paying one serial
    restore round-trip per leaf on deeply-stateful models (asserted by the
    restore-spy test in tests/test_elastic.py)."""
    import orbax.checkpoint as ocp
    from jax import tree_util as jtu

    path = _step_path(directory, step)
    tree = _metadata_tree(path)
    sub = tree.get("model_state", {})
    meta_leaves, treedef = jtu.tree_flatten(sub)
    others_placeholder = {
        k: jax.tree.map(lambda m: ocp.PLACEHOLDER, v)
        for k, v in tree.items() if k != "model_state"
    }
    # greedy grouping: combined bytes per restore <= budget (single
    # over-budget leaves still restore alone — that bound is irreducible)
    groups, cur, cur_bytes = [], [], 0
    for i, m in enumerate(meta_leaves):
        nbytes = int(np.prod(m.shape, dtype=np.int64)) * np.dtype(m.dtype).itemsize
        if cur and cur_bytes + nbytes > host_bytes_budget:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        groups.append(cur)
    out = [None] * len(meta_leaves)
    for group in groups:
        live = set(group)
        sub_tpl = jtu.tree_unflatten(treedef, [
            jax.ShapeDtypeStruct(tuple(m.shape), m.dtype) if j in live
            else ocp.PLACEHOLDER
            for j, m in enumerate(meta_leaves)
        ])
        restored = _pytree_checkpointer().restore(
            path,
            args=ocp.args.PyTreeRestore(
                item=dict(others_placeholder, model_state=sub_tpl)
            ),
        )
        flat = jtu.tree_flatten(restored["model_state"])[0]
        for i in group:
            out[i] = worker_mean(flat[i])
    return jtu.tree_unflatten(treedef, out)


def checkpoint_num_workers(directory: str, step: Optional[int] = None) -> int:
    """Worker count a checkpoint was written at: the leading dim of its
    per-worker ``rng`` leaf, read from array METADATA only (no tensor data
    leaves disk) — the cheap probe behind elastic resume."""
    tree = _metadata_tree(_step_path(directory, step))
    return int(tree["rng"].shape[0])


class CheckpointManager:
    """Every-N-epochs checkpointing hook used by trainers (``checkpoint_dir``
    + ``checkpoint_every`` kwargs)."""

    def __init__(self, directory: str, every: int = 1, keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.every = max(1, int(every))
        self.keep = keep
        self._saved: set[int] = set()
        # steps whose latest save is a mid-epoch (partial) one: their
        # epoch-boundary save must overwrite (force=True), and their stale
        # cursor sidecar must go when the boundary save supersedes it
        self._partial: set[int] = set()
        os.makedirs(self.directory, exist_ok=True)

    def _is_partial(self, step: int) -> bool:
        """Whether ``step``'s latest save is a mid-epoch one — from this
        manager's memory, or from the on-disk cursor sidecar (sidecar writes
        are synchronous, so a resumed process sees a killed run's partial
        step even while its async model save is still uncommitted)."""
        if step in self._partial:
            return True
        ds = restore_data_state(self.directory, step)
        return ds is not None and int(ds.block_cursor) > 0

    def maybe_save(self, state: Any, epoch: int,
                   data_state=None) -> Optional[str]:
        if (epoch + 1) % self.every:
            return None
        step = epoch + 1
        path = save_checkpoint(self.directory, state, step,
                               force=self._is_partial(step))
        if data_state is not None:
            save_data_state(self.directory, data_state, step)
        else:
            # boundary save without a DataState supersedes a mid-epoch one:
            # drop any stale cursor so resume doesn't skip blocks
            try:
                os.remove(data_state_path(self.directory, step))
            except FileNotFoundError:
                pass
        self._partial.discard(step)
        self._saved.add(step)
        self._gc()
        return path

    def save_partial(self, state: Any, epoch: int, data_state) -> str:
        """Mid-epoch save: model state plus the :class:`DataState` cursor
        marking how far into ``epoch``'s block sequence the run got.  Saved
        under the step the epoch-boundary save will later claim
        (``epoch + 1``) and re-saved in place (``force=True``) as the cursor
        advances — resume always sees one coherent (state, cursor) pair."""
        step = epoch + 1
        path = save_checkpoint(self.directory, state, step, force=True)
        save_data_state(self.directory, data_state, step)
        self._partial.add(step)
        self._saved.add(step)
        self._gc()
        return path

    def restore_data_state(self, step: Optional[int] = None):
        return restore_data_state(self.directory, step)

    def wait(self) -> None:
        """Flush in-flight async saves (end of the trainer epoch loop)."""
        wait_until_finished()
        # everything initiated is now committed: apply the keep policy
        # exactly (collects the predecessor whose deletion _gc deferred
        # while its successor was in flight)
        self._gc()

    def _gc(self) -> None:
        # Only PUBLISHED steps (manifest + final step_ dir on disk) are gc
        # candidates.  Counting the in-flight newest save toward ``keep``
        # would, at keep=1, delete the only restorable checkpoint while the
        # new one is still writing — a crash in that window leaves zero
        # restorable checkpoints.  An in-flight (or crashed-publish) step
        # has no manifest yet, so excluding it both protects it and defers
        # deleting its predecessor until it lands; quarantined
        # ``step_N.corrupt`` renames fail the digit parse entirely and are
        # kept for forensics.  The manifest goes FIRST (un-publication),
        # so no reader can resolve a step whose bytes are mid-deletion.
        import shutil

        committed = committed_steps(self.directory)
        for s in committed[: -self.keep] if self.keep else []:
            self._saved.discard(s)
            self._partial.discard(s)
            try:
                os.remove(manifest_path(self.directory, s))
            except FileNotFoundError:
                pass
            shutil.rmtree(os.path.join(self.directory, f"step_{s}"), ignore_errors=True)
            try:
                os.remove(data_state_path(self.directory, s))
            except FileNotFoundError:
                pass

    def latest(self) -> Optional[int]:
        self.wait()  # flush + exact keep policy before reading the record
        return latest_step(self.directory)

    def latest_verified(self, mode: str = "full") -> Optional[int]:
        """The newest step whose bytes provably match their manifest —
        what resume pins: corrupt steps found on the way are quarantined
        (with their fate counted), so a crash that tore the newest
        checkpoint costs one checkpoint interval, not the run.  ``None``
        when nothing verifiable exists."""
        self.wait()
        try:
            return _resolve_verified(self.directory, None, mode)
        except FileNotFoundError:
            return None

    def saved_worker_count(self, step: Optional[int] = None) -> int:
        return checkpoint_num_workers(self.directory, step)

    def restore_center(
        self, step: Optional[int] = None, include_model_state: bool = True,
    ) -> dict:
        return restore_center(self.directory, step, include_model_state)

    def model_state_worker_mean(
        self, step: Optional[int] = None,
        host_bytes_budget: int = 256 * 1024**2,
    ):
        return model_state_worker_mean(self.directory, step, host_bytes_budget)

    def restore(self, like: Any = None, step: Optional[int] = None,
                verify: str = "full") -> Any:
        return restore_checkpoint(self.directory, step, like, verify)
