"""Checkpoint / resume — mid-training persistence of the center variable.

The reference has nothing in-tree (SURVEY.md §5.4: users call
``model.save()`` on the returned Keras model; a dead parameter server loses
the run).  Here the full training state — center params, per-worker local
replicas, optimizer state, rule state (clocks/anchors), epoch counter —
checkpoints through Orbax, so an interrupted distributed run resumes exactly
(bitwise, given the same data order seed).

Saves are asynchronous (``ocp.AsyncCheckpointer``): the host thread returns
as soon as the state is snapshotted, so per-epoch checkpointing stays off
the training path; ``CheckpointManager.wait()`` (called by trainers at the
end of the epoch loop, and implicitly before any restore) flushes the queue.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import numpy as np

from distkeras_tpu import telemetry

__all__ = [
    "save_checkpoint", "restore_checkpoint", "restore_center",
    "model_state_worker_mean", "latest_step",
    "checkpoint_num_workers", "CheckpointManager", "CheckpointWatcher",
    "save_data_state", "restore_data_state",
]

_CHECKPOINTER = None
_PYTREE_CHECKPOINTER = None


def _checkpointer():
    """Singleton async checkpointer on the current (non-deprecated) Orbax
    API: ``AsyncCheckpointer(StandardCheckpointHandler)`` with explicit
    ``args.StandardSave/StandardRestore`` (the round-1 ``PyTreeCheckpointer``
    is deprecated upstream)."""
    global _CHECKPOINTER
    if _CHECKPOINTER is None:
        import orbax.checkpoint as ocp

        _CHECKPOINTER = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return _CHECKPOINTER


def _pytree_checkpointer():
    """Singleton synchronous PyTree checkpointer for the partial
    (PLACEHOLDER) restores — built once, like :func:`_checkpointer`, instead
    of leaking a fresh instance per elastic resume."""
    global _PYTREE_CHECKPOINTER
    if _PYTREE_CHECKPOINTER is None:
        import orbax.checkpoint as ocp

        _PYTREE_CHECKPOINTER = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
    return _PYTREE_CHECKPOINTER


def wait_until_finished() -> None:
    """Block until every in-flight async save has committed."""
    if _CHECKPOINTER is not None:
        with telemetry.trace.span("checkpoint_flush", phase="ckpt"):
            _CHECKPOINTER.wait_until_finished()


def save_checkpoint(directory: str, state: Any, step: int,
                    force: bool = False) -> str:
    """Write training state under ``directory/step_N`` (async); returns the
    path.  Call :func:`wait_until_finished` before reading it back.

    ``force=True`` overwrites an existing ``step_N`` — the mid-epoch
    (datapipe) save path, where the same step id is re-saved as the block
    cursor advances and finally superseded by the epoch-boundary save.  A
    forced save flushes the async queue first so it cannot race an
    in-flight write to the same path."""
    import orbax.checkpoint as ocp

    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    # "checkpoint_enqueue" covers only the synchronous part of an async
    # save: the host snapshot plus handing the write to Orbax's thread.
    with telemetry.trace.span("checkpoint_enqueue", phase="ckpt", step=int(step)):
        host_state = jax.tree.map(np.asarray, state)
        if force:
            _checkpointer().wait_until_finished()
        _checkpointer().save(
            path, args=ocp.args.StandardSave(host_state), force=force)
    if telemetry.enabled():
        telemetry.metrics.counter(
            "checkpoints_saved_total", help="async checkpoint saves enqueued"
        ).inc()
    return path


def data_state_path(directory: str, step: int) -> str:
    """The ``step_<n>_data.json`` sidecar carrying a step's
    :class:`~distkeras_tpu.datapipe.DataState`.  A plain file (no ``step_<n>``
    *directory* name), so :func:`committed_steps`'s digit parse never
    mistakes it for a checkpoint step."""
    return os.path.join(os.path.abspath(directory), f"step_{step}_data.json")


def save_data_state(directory: str, data_state, step: int) -> str:
    """Write the data checkpoint sidecar for ``step`` — synchronous (a few
    hundred bytes) and atomic (tmp + rename), so a crash can never leave a
    half-written cursor next to a committed model step."""
    path = data_state_path(directory, step)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(data_state.to_json(), fh)
    os.replace(tmp, path)
    return path


def restore_data_state(directory: str, step: Optional[int] = None):
    """The :class:`~distkeras_tpu.datapipe.DataState` saved with ``step``
    (default: latest), or None — model-only checkpoints (pre-datapipe runs,
    external writers) resume with the legacy epoch-boundary RNG
    fast-forward instead."""
    from distkeras_tpu.datapipe.state import DataState

    if step is None:
        step = latest_step(directory)
        if step is None:
            return None
    path = data_state_path(directory, step)
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as fh:
        return DataState.from_json(json.load(fh))


def committed_steps(directory: str) -> list:
    """Steps whose final ``step_<n>`` directory exists — async saves only
    get their final name at commit, so the listing alone is a commit
    record (no flush needed)."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return []
    return sorted(
        int(d.split("_", 1)[1])
        for d in os.listdir(directory)
        if d.startswith("step_") and d.split("_", 1)[1].isdigit()
    )


def latest_step(directory: str) -> Optional[int]:
    wait_until_finished()  # a step only counts once its async save committed
    steps = committed_steps(directory)
    return steps[-1] if steps else None


class CheckpointWatcher:
    """Newest-step watcher over a checkpoint directory — the train→serve
    bridge.  ``poll()`` returns the newest committed step the first time it
    is seen, ``None`` otherwise.

    Built on :func:`committed_steps` (directory listing = commit record),
    NOT :func:`latest_step`: the latter flushes *this* process's async save
    queue, which is meaningless — and wrong to wait on — when the trainer
    writing the checkpoints is a different process.  With ``start_after``
    omitted, the watcher baselines at the newest step already on disk at
    construction, so only steps committed *afterwards* fire (a serving
    replica that just loaded step N must not be told to hot-swap to step
    N).  Pass ``start_after=-1`` to see every committed step including
    pre-existing ones."""

    def __init__(self, directory: str,
                 start_after: Optional[int] = None):
        self.directory = directory
        if start_after is None:
            steps = committed_steps(directory)
            start_after = steps[-1] if steps else -1
        self.last_step = int(start_after)

    def poll(self) -> Optional[int]:
        """The newest committed step if it is newer than anything reported
        before, else ``None``.  Intermediate steps are skipped on purpose:
        a serving fleet swaps to the freshest params, not through history."""
        steps = committed_steps(self.directory)
        if steps and steps[-1] > self.last_step:
            self.last_step = steps[-1]
            return self.last_step
        return None


def restore_checkpoint(directory: str, step: Optional[int] = None, like: Any = None) -> Any:
    """Load training state; ``like`` (a template pytree, e.g. a freshly built
    TrainState) restores exact structure/dtypes and device placement."""
    import orbax.checkpoint as ocp

    path = _step_path(directory, step)
    template = jax.tree.map(np.asarray, like) if like is not None else None
    restored = _checkpointer().restore(
        path, args=ocp.args.StandardRestore(template)
    )
    if like is not None:
        # re-place on the same shardings as the template
        return jax.tree.map(
            lambda tpl, val: jax.device_put(val, tpl.sharding)
            if hasattr(tpl, "sharding")
            else val,
            like,
            restored,
        )
    return restored


def _step_path(directory: str, step: Optional[int]) -> str:
    wait_until_finished()
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    return os.path.join(os.path.abspath(directory), f"step_{step}")


def _metadata_tree(path: str) -> dict:
    meta = _checkpointer().metadata(path)
    tree = getattr(meta, "item_metadata", meta)
    tree = getattr(tree, "tree", tree)
    if not isinstance(tree, dict):
        # the getattr chain above tracks Orbax's metadata API (validated
        # against orbax-checkpoint 0.11.x); a release that reshapes it again
        # should fail here by name, not with a KeyError downstream
        raise RuntimeError(
            "could not read the checkpoint metadata tree as a dict (got "
            f"{type(tree).__name__}) — the installed orbax-checkpoint "
            "version exposes an unexpected metadata layout; "
            "distkeras_tpu.checkpoint expects the 0.11.x "
            "item_metadata/.tree API"
        )
    return tree


def restore_center(
    directory: str, step: Optional[int] = None,
    include_model_state: bool = True,
) -> dict:
    """Partial restore for elastic resume: only the center variable, its
    rule state, the model state, and the epoch counter leave disk; the
    per-worker subtrees (local replicas, optimizer state, rule locals,
    rngs) — ~3N x the model size at N workers — restore as Orbax
    placeholders, i.e. are never read.

    ``include_model_state=False`` additionally placeholders the per-worker
    ``[N, ...]`` model-state stack — pair with
    :func:`model_state_worker_mean`, which reduces that stack leaf by leaf
    instead of materialising all of it at once."""
    import orbax.checkpoint as ocp

    path = _step_path(directory, step)
    tree = _metadata_tree(path)
    keep = ("center_params", "center_rule", "epoch")
    if include_model_state:
        keep = keep + ("model_state",)

    def template_for(key, sub):
        if key in keep:
            return jax.tree.map(
                lambda m: jax.ShapeDtypeStruct(tuple(m.shape), m.dtype), sub
            )
        return jax.tree.map(lambda m: ocp.PLACEHOLDER, sub)

    template = {k: template_for(k, v) for k, v in tree.items()}
    # PLACEHOLDER is a PyTree-handler feature (the Standard handler rejects
    # it); both handlers share the on-disk format, so reading a
    # StandardSave checkpoint through PyTreeRestore is exact.
    restored = _pytree_checkpointer().restore(
        path, args=ocp.args.PyTreeRestore(item=template)
    )
    return {k: restored[k] for k in keep}


def worker_mean(x: np.ndarray) -> np.ndarray:
    """Mean over the leading (workers) axis with resume-grade dtype care:
    accumulate in float64 (bf16 leaves don't round twice), round integer
    leaves to nearest instead of truncating."""
    x = np.asarray(x)
    m = x.astype(np.float64).mean(axis=0)
    if np.issubdtype(x.dtype, np.integer):
        m = np.rint(m)
    return m.astype(x.dtype)


def model_state_worker_mean(
    directory: str, step: Optional[int] = None,
    host_bytes_budget: int = 256 * 1024**2,
):
    """Collapse the checkpointed per-worker ``[N_old, ...]`` model-state
    stack to its worker mean WITHOUT materialising the whole stack on host.

    Elastic resume at a new worker count needs only the mean (the same
    semantic ``sync_model_state`` applies at every commit), but a naive
    restore reads all ``N_old x`` model-state bytes into one host tree —
    for large stateful models exactly the host spike the sharded training
    path avoids.  Instead leaves restore in groups whose combined stack
    size stays under ``host_bytes_budget`` (every other array in the
    checkpoint is an Orbax PLACEHOLDER, i.e. never read) and reduce
    immediately, bounding peak host memory without paying one serial
    restore round-trip per leaf on deeply-stateful models (asserted by the
    restore-spy test in tests/test_elastic.py)."""
    import orbax.checkpoint as ocp
    from jax import tree_util as jtu

    path = _step_path(directory, step)
    tree = _metadata_tree(path)
    sub = tree.get("model_state", {})
    meta_leaves, treedef = jtu.tree_flatten(sub)
    others_placeholder = {
        k: jax.tree.map(lambda m: ocp.PLACEHOLDER, v)
        for k, v in tree.items() if k != "model_state"
    }
    # greedy grouping: combined bytes per restore <= budget (single
    # over-budget leaves still restore alone — that bound is irreducible)
    groups, cur, cur_bytes = [], [], 0
    for i, m in enumerate(meta_leaves):
        nbytes = int(np.prod(m.shape, dtype=np.int64)) * np.dtype(m.dtype).itemsize
        if cur and cur_bytes + nbytes > host_bytes_budget:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        groups.append(cur)
    out = [None] * len(meta_leaves)
    for group in groups:
        live = set(group)
        sub_tpl = jtu.tree_unflatten(treedef, [
            jax.ShapeDtypeStruct(tuple(m.shape), m.dtype) if j in live
            else ocp.PLACEHOLDER
            for j, m in enumerate(meta_leaves)
        ])
        restored = _pytree_checkpointer().restore(
            path,
            args=ocp.args.PyTreeRestore(
                item=dict(others_placeholder, model_state=sub_tpl)
            ),
        )
        flat = jtu.tree_flatten(restored["model_state"])[0]
        for i in group:
            out[i] = worker_mean(flat[i])
    return jtu.tree_unflatten(treedef, out)


def checkpoint_num_workers(directory: str, step: Optional[int] = None) -> int:
    """Worker count a checkpoint was written at: the leading dim of its
    per-worker ``rng`` leaf, read from array METADATA only (no tensor data
    leaves disk) — the cheap probe behind elastic resume."""
    tree = _metadata_tree(_step_path(directory, step))
    return int(tree["rng"].shape[0])


class CheckpointManager:
    """Every-N-epochs checkpointing hook used by trainers (``checkpoint_dir``
    + ``checkpoint_every`` kwargs)."""

    def __init__(self, directory: str, every: int = 1, keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.every = max(1, int(every))
        self.keep = keep
        self._saved: set[int] = set()
        # steps whose latest save is a mid-epoch (partial) one: their
        # epoch-boundary save must overwrite (force=True), and their stale
        # cursor sidecar must go when the boundary save supersedes it
        self._partial: set[int] = set()
        os.makedirs(self.directory, exist_ok=True)

    def _is_partial(self, step: int) -> bool:
        """Whether ``step``'s latest save is a mid-epoch one — from this
        manager's memory, or from the on-disk cursor sidecar (sidecar writes
        are synchronous, so a resumed process sees a killed run's partial
        step even while its async model save is still uncommitted)."""
        if step in self._partial:
            return True
        ds = restore_data_state(self.directory, step)
        return ds is not None and int(ds.block_cursor) > 0

    def maybe_save(self, state: Any, epoch: int,
                   data_state=None) -> Optional[str]:
        if (epoch + 1) % self.every:
            return None
        step = epoch + 1
        path = save_checkpoint(self.directory, state, step,
                               force=self._is_partial(step))
        if data_state is not None:
            save_data_state(self.directory, data_state, step)
        else:
            # boundary save without a DataState supersedes a mid-epoch one:
            # drop any stale cursor so resume doesn't skip blocks
            try:
                os.remove(data_state_path(self.directory, step))
            except FileNotFoundError:
                pass
        self._partial.discard(step)
        self._saved.add(step)
        self._gc()
        return path

    def save_partial(self, state: Any, epoch: int, data_state) -> str:
        """Mid-epoch save: model state plus the :class:`DataState` cursor
        marking how far into ``epoch``'s block sequence the run got.  Saved
        under the step the epoch-boundary save will later claim
        (``epoch + 1``) and re-saved in place (``force=True``) as the cursor
        advances — resume always sees one coherent (state, cursor) pair."""
        step = epoch + 1
        path = save_checkpoint(self.directory, state, step, force=True)
        save_data_state(self.directory, data_state, step)
        self._partial.add(step)
        self._saved.add(step)
        self._gc()
        return path

    def restore_data_state(self, step: Optional[int] = None):
        return restore_data_state(self.directory, step)

    def wait(self) -> None:
        """Flush in-flight async saves (end of the trainer epoch loop)."""
        wait_until_finished()
        # everything initiated is now committed: apply the keep policy
        # exactly (collects the predecessor whose deletion _gc deferred
        # while its successor was in flight)
        self._gc()

    def _gc(self) -> None:
        # Only COMMITTED steps (final step_ dirs on disk) are gc
        # candidates.  Counting the in-flight newest save toward ``keep``
        # would, at keep=1, delete the only committed checkpoint while the
        # new one is still writing — a crash in that window leaves zero
        # restorable checkpoints.  The in-flight step has no final dir yet,
        # so excluding it both protects it and defers deleting its
        # predecessor until it lands (at most one extra step on disk).
        import shutil

        committed = committed_steps(self.directory)
        for s in committed[: -self.keep] if self.keep else []:
            self._saved.discard(s)
            self._partial.discard(s)
            shutil.rmtree(os.path.join(self.directory, f"step_{s}"), ignore_errors=True)
            try:
                os.remove(data_state_path(self.directory, s))
            except FileNotFoundError:
                pass

    def latest(self) -> Optional[int]:
        self.wait()  # flush + exact keep policy before reading the record
        return latest_step(self.directory)

    def saved_worker_count(self, step: Optional[int] = None) -> int:
        return checkpoint_num_workers(self.directory, step)

    def restore_center(
        self, step: Optional[int] = None, include_model_state: bool = True,
    ) -> dict:
        return restore_center(self.directory, step, include_model_state)

    def model_state_worker_mean(
        self, step: Optional[int] = None,
        host_bytes_budget: int = 256 * 1024**2,
    ):
        return model_state_worker_mean(self.directory, step, host_bytes_budget)

    def restore(self, like: Any = None, step: Optional[int] = None) -> Any:
        return restore_checkpoint(self.directory, step, like)
