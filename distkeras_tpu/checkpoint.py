"""Checkpoint / resume — mid-training persistence of the center variable.

The reference has nothing in-tree (SURVEY.md §5.4: users call
``model.save()`` on the returned Keras model; a dead parameter server loses
the run).  Here the full training state — center params, per-worker local
replicas, optimizer state, rule state (clocks/anchors), epoch counter —
checkpoints through Orbax, so an interrupted distributed run resumes exactly
(bitwise, given the same data order seed).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np

__all__ = ["save_checkpoint", "restore_checkpoint", "latest_step", "CheckpointManager"]


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_checkpoint(directory: str, state: Any, step: int) -> str:
    """Write training state under ``directory/step_N``; returns the path."""
    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    _checkpointer().save(path, jax.tree.map(np.asarray, state))
    return path


def latest_step(directory: str) -> Optional[int]:
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return None
    steps = [
        int(d.split("_", 1)[1])
        for d in os.listdir(directory)
        if d.startswith("step_") and d.split("_", 1)[1].isdigit()
    ]
    return max(steps) if steps else None


def restore_checkpoint(directory: str, step: Optional[int] = None, like: Any = None) -> Any:
    """Load training state; ``like`` (a template pytree, e.g. a freshly built
    TrainState) restores exact structure/dtypes and device placement."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    restored = _checkpointer().restore(path, item=jax.tree.map(np.asarray, like) if like is not None else None)
    if like is not None:
        # re-place on the same shardings as the template
        return jax.tree.map(
            lambda tpl, val: jax.device_put(val, tpl.sharding)
            if hasattr(tpl, "sharding")
            else val,
            like,
            restored,
        )
    return restored


class CheckpointManager:
    """Every-N-epochs checkpointing hook used by trainers (``checkpoint_dir``
    + ``checkpoint_every`` kwargs)."""

    def __init__(self, directory: str, every: int = 1, keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.every = max(1, int(every))
        self.keep = keep
        os.makedirs(self.directory, exist_ok=True)

    def maybe_save(self, state: Any, epoch: int) -> Optional[str]:
        if (epoch + 1) % self.every:
            return None
        path = save_checkpoint(self.directory, state, epoch + 1)
        self._gc()
        return path

    def _gc(self) -> None:
        steps = sorted(
            int(d.split("_", 1)[1])
            for d in os.listdir(self.directory)
            if d.startswith("step_") and d.split("_", 1)[1].isdigit()
        )
        import shutil

        for s in steps[: -self.keep] if self.keep else []:
            shutil.rmtree(os.path.join(self.directory, f"step_{s}"), ignore_errors=True)

    def latest(self) -> Optional[int]:
        return latest_step(self.directory)

    def restore(self, like: Any = None, step: Optional[int] = None) -> Any:
        return restore_checkpoint(self.directory, step, like)
