"""Checkpoint / resume — mid-training persistence of the center variable.

The reference has nothing in-tree (SURVEY.md §5.4: users call
``model.save()`` on the returned Keras model; a dead parameter server loses
the run).  Here the full training state — center params, per-worker local
replicas, optimizer state, rule state (clocks/anchors), epoch counter —
checkpoints through Orbax, so an interrupted distributed run resumes exactly
(bitwise, given the same data order seed).

Saves are asynchronous (``ocp.AsyncCheckpointer``): the host thread returns
as soon as the state is snapshotted, so per-epoch checkpointing stays off
the training path; ``CheckpointManager.wait()`` (called by trainers at the
end of the epoch loop, and implicitly before any restore) flushes the queue.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np

from distkeras_tpu import telemetry

__all__ = [
    "save_checkpoint", "restore_checkpoint", "restore_center",
    "model_state_worker_mean", "latest_step",
    "checkpoint_num_workers", "CheckpointManager",
]

_CHECKPOINTER = None
_PYTREE_CHECKPOINTER = None


def _checkpointer():
    """Singleton async checkpointer on the current (non-deprecated) Orbax
    API: ``AsyncCheckpointer(StandardCheckpointHandler)`` with explicit
    ``args.StandardSave/StandardRestore`` (the round-1 ``PyTreeCheckpointer``
    is deprecated upstream)."""
    global _CHECKPOINTER
    if _CHECKPOINTER is None:
        import orbax.checkpoint as ocp

        _CHECKPOINTER = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return _CHECKPOINTER


def _pytree_checkpointer():
    """Singleton synchronous PyTree checkpointer for the partial
    (PLACEHOLDER) restores — built once, like :func:`_checkpointer`, instead
    of leaking a fresh instance per elastic resume."""
    global _PYTREE_CHECKPOINTER
    if _PYTREE_CHECKPOINTER is None:
        import orbax.checkpoint as ocp

        _PYTREE_CHECKPOINTER = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
    return _PYTREE_CHECKPOINTER


def wait_until_finished() -> None:
    """Block until every in-flight async save has committed."""
    if _CHECKPOINTER is not None:
        with telemetry.trace.span("checkpoint_flush", phase="ckpt"):
            _CHECKPOINTER.wait_until_finished()


def save_checkpoint(directory: str, state: Any, step: int) -> str:
    """Write training state under ``directory/step_N`` (async); returns the
    path.  Call :func:`wait_until_finished` before reading it back."""
    import orbax.checkpoint as ocp

    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    # "checkpoint_enqueue" covers only the synchronous part of an async
    # save: the host snapshot plus handing the write to Orbax's thread.
    with telemetry.trace.span("checkpoint_enqueue", phase="ckpt", step=int(step)):
        host_state = jax.tree.map(np.asarray, state)
        _checkpointer().save(path, args=ocp.args.StandardSave(host_state))
    if telemetry.enabled():
        telemetry.metrics.counter(
            "checkpoints_saved_total", help="async checkpoint saves enqueued"
        ).inc()
    return path


def committed_steps(directory: str) -> list:
    """Steps whose final ``step_<n>`` directory exists — async saves only
    get their final name at commit, so the listing alone is a commit
    record (no flush needed)."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return []
    return sorted(
        int(d.split("_", 1)[1])
        for d in os.listdir(directory)
        if d.startswith("step_") and d.split("_", 1)[1].isdigit()
    )


def latest_step(directory: str) -> Optional[int]:
    wait_until_finished()  # a step only counts once its async save committed
    steps = committed_steps(directory)
    return steps[-1] if steps else None


def restore_checkpoint(directory: str, step: Optional[int] = None, like: Any = None) -> Any:
    """Load training state; ``like`` (a template pytree, e.g. a freshly built
    TrainState) restores exact structure/dtypes and device placement."""
    import orbax.checkpoint as ocp

    path = _step_path(directory, step)
    template = jax.tree.map(np.asarray, like) if like is not None else None
    restored = _checkpointer().restore(
        path, args=ocp.args.StandardRestore(template)
    )
    if like is not None:
        # re-place on the same shardings as the template
        return jax.tree.map(
            lambda tpl, val: jax.device_put(val, tpl.sharding)
            if hasattr(tpl, "sharding")
            else val,
            like,
            restored,
        )
    return restored


def _step_path(directory: str, step: Optional[int]) -> str:
    wait_until_finished()
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    return os.path.join(os.path.abspath(directory), f"step_{step}")


def _metadata_tree(path: str) -> dict:
    meta = _checkpointer().metadata(path)
    tree = getattr(meta, "item_metadata", meta)
    tree = getattr(tree, "tree", tree)
    if not isinstance(tree, dict):
        # the getattr chain above tracks Orbax's metadata API (validated
        # against orbax-checkpoint 0.11.x); a release that reshapes it again
        # should fail here by name, not with a KeyError downstream
        raise RuntimeError(
            "could not read the checkpoint metadata tree as a dict (got "
            f"{type(tree).__name__}) — the installed orbax-checkpoint "
            "version exposes an unexpected metadata layout; "
            "distkeras_tpu.checkpoint expects the 0.11.x "
            "item_metadata/.tree API"
        )
    return tree


def restore_center(
    directory: str, step: Optional[int] = None,
    include_model_state: bool = True,
) -> dict:
    """Partial restore for elastic resume: only the center variable, its
    rule state, the model state, and the epoch counter leave disk; the
    per-worker subtrees (local replicas, optimizer state, rule locals,
    rngs) — ~3N x the model size at N workers — restore as Orbax
    placeholders, i.e. are never read.

    ``include_model_state=False`` additionally placeholders the per-worker
    ``[N, ...]`` model-state stack — pair with
    :func:`model_state_worker_mean`, which reduces that stack leaf by leaf
    instead of materialising all of it at once."""
    import orbax.checkpoint as ocp

    path = _step_path(directory, step)
    tree = _metadata_tree(path)
    keep = ("center_params", "center_rule", "epoch")
    if include_model_state:
        keep = keep + ("model_state",)

    def template_for(key, sub):
        if key in keep:
            return jax.tree.map(
                lambda m: jax.ShapeDtypeStruct(tuple(m.shape), m.dtype), sub
            )
        return jax.tree.map(lambda m: ocp.PLACEHOLDER, sub)

    template = {k: template_for(k, v) for k, v in tree.items()}
    # PLACEHOLDER is a PyTree-handler feature (the Standard handler rejects
    # it); both handlers share the on-disk format, so reading a
    # StandardSave checkpoint through PyTreeRestore is exact.
    restored = _pytree_checkpointer().restore(
        path, args=ocp.args.PyTreeRestore(item=template)
    )
    return {k: restored[k] for k in keep}


def worker_mean(x: np.ndarray) -> np.ndarray:
    """Mean over the leading (workers) axis with resume-grade dtype care:
    accumulate in float64 (bf16 leaves don't round twice), round integer
    leaves to nearest instead of truncating."""
    x = np.asarray(x)
    m = x.astype(np.float64).mean(axis=0)
    if np.issubdtype(x.dtype, np.integer):
        m = np.rint(m)
    return m.astype(x.dtype)


def model_state_worker_mean(
    directory: str, step: Optional[int] = None,
    host_bytes_budget: int = 256 * 1024**2,
):
    """Collapse the checkpointed per-worker ``[N_old, ...]`` model-state
    stack to its worker mean WITHOUT materialising the whole stack on host.

    Elastic resume at a new worker count needs only the mean (the same
    semantic ``sync_model_state`` applies at every commit), but a naive
    restore reads all ``N_old x`` model-state bytes into one host tree —
    for large stateful models exactly the host spike the sharded training
    path avoids.  Instead leaves restore in groups whose combined stack
    size stays under ``host_bytes_budget`` (every other array in the
    checkpoint is an Orbax PLACEHOLDER, i.e. never read) and reduce
    immediately, bounding peak host memory without paying one serial
    restore round-trip per leaf on deeply-stateful models (asserted by the
    restore-spy test in tests/test_elastic.py)."""
    import orbax.checkpoint as ocp
    from jax import tree_util as jtu

    path = _step_path(directory, step)
    tree = _metadata_tree(path)
    sub = tree.get("model_state", {})
    meta_leaves, treedef = jtu.tree_flatten(sub)
    others_placeholder = {
        k: jax.tree.map(lambda m: ocp.PLACEHOLDER, v)
        for k, v in tree.items() if k != "model_state"
    }
    # greedy grouping: combined bytes per restore <= budget (single
    # over-budget leaves still restore alone — that bound is irreducible)
    groups, cur, cur_bytes = [], [], 0
    for i, m in enumerate(meta_leaves):
        nbytes = int(np.prod(m.shape, dtype=np.int64)) * np.dtype(m.dtype).itemsize
        if cur and cur_bytes + nbytes > host_bytes_budget:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        groups.append(cur)
    out = [None] * len(meta_leaves)
    for group in groups:
        live = set(group)
        sub_tpl = jtu.tree_unflatten(treedef, [
            jax.ShapeDtypeStruct(tuple(m.shape), m.dtype) if j in live
            else ocp.PLACEHOLDER
            for j, m in enumerate(meta_leaves)
        ])
        restored = _pytree_checkpointer().restore(
            path,
            args=ocp.args.PyTreeRestore(
                item=dict(others_placeholder, model_state=sub_tpl)
            ),
        )
        flat = jtu.tree_flatten(restored["model_state"])[0]
        for i in group:
            out[i] = worker_mean(flat[i])
    return jtu.tree_unflatten(treedef, out)


def checkpoint_num_workers(directory: str, step: Optional[int] = None) -> int:
    """Worker count a checkpoint was written at: the leading dim of its
    per-worker ``rng`` leaf, read from array METADATA only (no tensor data
    leaves disk) — the cheap probe behind elastic resume."""
    tree = _metadata_tree(_step_path(directory, step))
    return int(tree["rng"].shape[0])


class CheckpointManager:
    """Every-N-epochs checkpointing hook used by trainers (``checkpoint_dir``
    + ``checkpoint_every`` kwargs)."""

    def __init__(self, directory: str, every: int = 1, keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.every = max(1, int(every))
        self.keep = keep
        self._saved: set[int] = set()
        os.makedirs(self.directory, exist_ok=True)

    def maybe_save(self, state: Any, epoch: int) -> Optional[str]:
        if (epoch + 1) % self.every:
            return None
        path = save_checkpoint(self.directory, state, epoch + 1)
        self._saved.add(epoch + 1)
        self._gc()
        return path

    def wait(self) -> None:
        """Flush in-flight async saves (end of the trainer epoch loop)."""
        wait_until_finished()
        # everything initiated is now committed: apply the keep policy
        # exactly (collects the predecessor whose deletion _gc deferred
        # while its successor was in flight)
        self._gc()

    def _gc(self) -> None:
        # Only COMMITTED steps (final step_ dirs on disk) are gc
        # candidates.  Counting the in-flight newest save toward ``keep``
        # would, at keep=1, delete the only committed checkpoint while the
        # new one is still writing — a crash in that window leaves zero
        # restorable checkpoints.  The in-flight step has no final dir yet,
        # so excluding it both protects it and defers deleting its
        # predecessor until it lands (at most one extra step on disk).
        import shutil

        committed = committed_steps(self.directory)
        for s in committed[: -self.keep] if self.keep else []:
            self._saved.discard(s)
            shutil.rmtree(os.path.join(self.directory, f"step_{s}"), ignore_errors=True)

    def latest(self) -> Optional[int]:
        self.wait()  # flush + exact keep policy before reading the record
        return latest_step(self.directory)

    def saved_worker_count(self, step: Optional[int] = None) -> int:
        return checkpoint_num_workers(self.directory, step)

    def restore_center(
        self, step: Optional[int] = None, include_model_state: bool = True,
    ) -> dict:
        return restore_center(self.directory, step, include_model_state)

    def model_state_worker_mean(
        self, step: Optional[int] = None,
        host_bytes_budget: int = 256 * 1024**2,
    ):
        return model_state_worker_mean(self.directory, step, host_bytes_budget)

    def restore(self, like: Any = None, step: Optional[int] = None) -> Any:
        return restore_checkpoint(self.directory, step, like)
