"""Evaluators — reference parity for ``distkeras/evaluators.py``.

``AccuracyEvaluator.evaluate(df)`` compares a prediction column against a
label column and returns scalar accuracy; the reference does this as a Spark
row filter + count, here it is one vectorised numpy comparison.
"""

from __future__ import annotations

import numpy as np

from distkeras_tpu.frame import DataFrame

__all__ = ["Evaluator", "AccuracyEvaluator", "LossEvaluator"]


class Evaluator:
    def evaluate(self, dataframe: DataFrame) -> float:
        raise NotImplementedError


class AccuracyEvaluator(Evaluator):
    """Fraction of rows where prediction matches label (reference parity:
    ``AccuracyEvaluator(prediction_col, label_col)``).

    Either column may hold class indices or probability / one-hot vectors;
    vectors are argmaxed first (the reference requires a prior
    ``LabelIndexTransformer`` pass — we accept both forms).
    """

    def __init__(self, prediction_col: str = "prediction", label_col: str = "label"):
        self.prediction_col = prediction_col
        self.label_col = label_col

    @staticmethod
    def _to_index(col: np.ndarray) -> np.ndarray:
        if col.dtype == object:
            col = np.stack([np.asarray(v) for v in col])
        col = np.asarray(col)
        if col.ndim > 1 and col.shape[-1] > 1:
            return np.argmax(col.reshape(len(col), -1), axis=-1)
        return col.reshape(-1).astype(np.int64)

    def evaluate(self, dataframe: DataFrame) -> float:
        preds = self._to_index(dataframe.column(self.prediction_col))
        labels = self._to_index(dataframe.column(self.label_col))
        if len(preds) == 0:
            return 0.0
        return float(np.mean(preds == labels))


class LossEvaluator(Evaluator):
    """Mean loss over a DataFrame (extension beyond the reference set)."""

    def __init__(self, loss="categorical_crossentropy", prediction_col: str = "prediction",
                 label_col: str = "label", from_logits: bool = False):
        from distkeras_tpu.ops import get_loss

        self.loss_fn = get_loss(loss, from_logits=from_logits)
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, dataframe: DataFrame) -> float:
        import jax.numpy as jnp

        preds = jnp.asarray(dataframe.matrix(self.prediction_col))
        labels = jnp.asarray(dataframe.matrix(self.label_col))
        return float(self.loss_fn(preds, labels))
