"""Evaluators — reference parity for ``distkeras/evaluators.py``.

``AccuracyEvaluator.evaluate(df)`` compares a prediction column against a
label column and returns scalar accuracy; the reference does this as a Spark
row filter + count, here it is one vectorised numpy comparison.
"""

from __future__ import annotations

import numpy as np

from distkeras_tpu.frame import DataFrame

__all__ = ["Evaluator", "AccuracyEvaluator", "LossEvaluator", "PerplexityEvaluator"]


class Evaluator:
    def evaluate(self, dataframe: DataFrame) -> float:
        raise NotImplementedError


class AccuracyEvaluator(Evaluator):
    """Fraction of rows where prediction matches label (reference parity:
    ``AccuracyEvaluator(prediction_col, label_col)``).

    Either column may hold class indices or probability / one-hot vectors;
    vectors are argmaxed first (the reference requires a prior
    ``LabelIndexTransformer`` pass — we accept both forms).
    """

    def __init__(self, prediction_col: str = "prediction", label_col: str = "label"):
        self.prediction_col = prediction_col
        self.label_col = label_col

    @staticmethod
    def _to_index(col: np.ndarray) -> np.ndarray:
        if col.dtype == object:
            col = np.stack([np.asarray(v) for v in col])
        col = np.asarray(col)
        if col.ndim > 1 and col.shape[-1] > 1:
            return np.argmax(col.reshape(len(col), -1), axis=-1)
        return col.reshape(-1).astype(np.int64)

    def evaluate(self, dataframe: DataFrame) -> float:
        preds = self._to_index(dataframe.column(self.prediction_col))
        labels = self._to_index(dataframe.column(self.label_col))
        if len(preds) == 0:
            return 0.0
        return float(np.mean(preds == labels))


class LossEvaluator(Evaluator):
    """Mean loss over a DataFrame (extension beyond the reference set)."""

    def __init__(self, loss="categorical_crossentropy", prediction_col: str = "prediction",
                 label_col: str = "label", from_logits: bool = False):
        from distkeras_tpu.ops import get_loss

        self.loss_fn = get_loss(loss, from_logits=from_logits)
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, dataframe: DataFrame) -> float:
        import jax.numpy as jnp

        preds = jnp.asarray(dataframe.matrix(self.prediction_col))
        labels = jnp.asarray(dataframe.matrix(self.label_col))
        return float(self.loss_fn(preds, labels))


class PerplexityEvaluator(Evaluator):
    """Per-token perplexity for language models (extension beyond the
    reference set): ``exp(mean NLL of the true next tokens)``.

    Expects a prediction column of per-token distributions ``[seq, vocab]``
    (what ``ModelPredictor`` emits for a ``TransformerLM``/``StagedLM`` —
    softmax probabilities) and an integer label column ``[seq]``.
    """

    def __init__(self, prediction_col: str = "prediction",
                 label_col: str = "label", from_logits: bool = False,
                 eps: float = 1e-9):
        self.prediction_col = prediction_col
        self.label_col = label_col
        self.from_logits = from_logits
        self.eps = eps

    def evaluate(self, dataframe: DataFrame) -> float:
        preds = dataframe.matrix(self.prediction_col, dtype=np.float64)
        labels = dataframe.matrix(self.label_col, dtype=np.int64)
        if preds.ndim != 3:
            raise ValueError(
                f"perplexity needs per-token distributions [N, seq, vocab]; "
                f"got prediction shape {preds.shape}"
            )
        if self.from_logits:
            z = preds - preds.max(-1, keepdims=True)
            ez = np.exp(z)
            preds = ez / ez.sum(-1, keepdims=True)
        elif preds.min() < 0.0 or preds.max() > 1.0 + 1e-6:
            raise ValueError(
                "prediction column holds values outside [0, 1] — pass "
                "from_logits=True for raw logits (clipping them would report "
                "a deceptively low perplexity)"
            )
        picked = np.take_along_axis(preds, labels[..., None], axis=-1)[..., 0]
        nll = -np.log(np.clip(picked, self.eps, 1.0))
        return float(np.exp(nll.mean()))
