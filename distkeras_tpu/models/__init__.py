"""Model layer: functional adapters (Keras-3 / flax) and the in-tree zoo."""

from distkeras_tpu.models.adapter import (
    FlaxModel,
    FunctionalModel,
    ModelAdapter,
    TrainedModel,
    as_adapter,
)
from distkeras_tpu.models.moe import (
    MoEEncoderBlock,
    MoEFeedForward,
    MoETransformerClassifier,
    expert_partition,
)
from distkeras_tpu.models.hf import HuggingFaceModel
from distkeras_tpu.models.hf_staged import PretrainedStagedLM, gpt2_to_staged
from distkeras_tpu.models.generate import greedy_generate
from distkeras_tpu.models.staged import StagedLM, StagedTransformer
from distkeras_tpu.models.transformer import (
    TransformerClassifier,
    TransformerEncoderBlock,
    TransformerLM,
)
from distkeras_tpu.models.zoo import CIFARCNN, MLP, MNISTCNN, ResNet20, TextCNN

__all__ = [
    "ModelAdapter",
    "FlaxModel",
    "FunctionalModel",
    "TrainedModel",
    "as_adapter",
    "MLP",
    "MNISTCNN",
    "CIFARCNN",
    "ResNet20",
    "TextCNN",
    "TransformerClassifier",
    "TransformerEncoderBlock",
    "TransformerLM",
    "StagedTransformer",
    "StagedLM",
    "greedy_generate",
    "MoEFeedForward",
    "MoEEncoderBlock",
    "MoETransformerClassifier",
    "expert_partition",
    "HuggingFaceModel",
    "PretrainedStagedLM",
    "gpt2_to_staged",
]
