"""Transformer models with optional sequence parallelism.

Beyond the reference's model scale (SURVEY.md §5.7): a Transformer encoder
classifier whose sequence axis can be sharded over a mesh axis.  When
``seq_axis`` is set (running inside ``shard_map`` with that axis), attention
runs as ring attention (:mod:`distkeras_tpu.parallel.ring`) and the classifier
head pools *per-token logits* so every parameter-consuming op sees sharded
activations — which makes the cross-shard gradient sync a plain ``psum`` over
the sequence axis (done by the engine), with no replicated-activation
double-counting.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from distkeras_tpu.parallel.ring import attention, ring_attention

__all__ = ["TransformerClassifier", "TransformerEncoderBlock"]


class _SelfAttention(nn.Module):
    dim: int
    heads: int
    seq_axis: Optional[str] = None
    causal: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        head_dim = self.dim // self.heads
        qkv = nn.DenseGeneral((3, self.heads, head_dim), name="qkv")(x)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        if self.seq_axis is not None:
            out = ring_attention(q, k, v, self.seq_axis, causal=self.causal)
        else:
            out = attention(q, k, v, causal=self.causal)
        return nn.DenseGeneral(self.dim, axis=(-2, -1), name="proj")(out)


class TransformerEncoderBlock(nn.Module):
    dim: int
    heads: int
    mlp_ratio: int = 4
    seq_axis: Optional[str] = None
    causal: bool = False
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, training: bool = False):
        h = nn.LayerNorm()(x)
        h = _SelfAttention(self.dim, self.heads, self.seq_axis, self.causal)(h, training)
        if self.dropout > 0:
            h = nn.Dropout(self.dropout, deterministic=not training)(h)
        x = x + h
        h = nn.LayerNorm()(x)
        h = nn.Dense(self.dim * self.mlp_ratio)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.dim)(h)
        if self.dropout > 0:
            h = nn.Dropout(self.dropout, deterministic=not training)(h)
        return x + h


class TransformerClassifier(nn.Module):
    """Token classifier over [batch, seq(block)] int32 inputs.

    With ``seq_axis`` set, the input is this device's sequence *block*;
    positional embeddings are offset by the block index and the head output
    is psum-pooled over the axis (replicated logits out).
    """

    vocab_size: int
    num_classes: int = 2
    dim: int = 128
    heads: int = 4
    num_layers: int = 2
    max_len: int = 2048
    seq_axis: Optional[str] = None
    causal: bool = False
    dropout: float = 0.0

    @nn.compact
    def __call__(self, tokens, training: bool = False):
        tokens = tokens.astype(jnp.int32)
        block_len = tokens.shape[1]
        if self.seq_axis is not None:
            offset = lax.axis_index(self.seq_axis) * block_len
            seq_total = block_len * lax.axis_size(self.seq_axis)
        else:
            offset = 0
            seq_total = block_len
        positions = offset + jnp.arange(block_len)
        x = nn.Embed(self.vocab_size, self.dim, name="tok_embed")(tokens)
        x = x + nn.Embed(self.max_len, self.dim, name="pos_embed")(positions)[None]
        for i in range(self.num_layers):
            x = TransformerEncoderBlock(
                self.dim, self.heads, seq_axis=self.seq_axis, causal=self.causal,
                dropout=self.dropout, name=f"block_{i}",
            )(x, training)
        x = nn.LayerNorm()(x)
        token_logits = nn.Dense(self.num_classes, name="head")(x)  # [b, blk, C]
        logits = token_logits.sum(axis=1) / seq_total
        if self.seq_axis is not None:
            logits = lax.psum(logits, self.seq_axis)
        return logits
