"""Transformer models with optional sequence parallelism.

Beyond the reference's model scale (SURVEY.md §5.7): a Transformer encoder
classifier whose sequence axis can be sharded over a mesh axis.  When
``seq_axis`` is set (running inside ``shard_map`` with that axis), attention
runs as ring attention (:mod:`distkeras_tpu.parallel.ring`) and the classifier
head pools *per-token logits* so every parameter-consuming op sees sharded
activations — which makes the cross-shard gradient sync a plain ``psum`` over
the sequence axis (done by the engine), with no replicated-activation
double-counting.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from distkeras_tpu.parallel.ring import attention, ring_attention

__all__ = ["TransformerClassifier", "TransformerEncoderBlock", "TransformerLM"]


class _SelfAttention(nn.Module):
    dim: int
    heads: int
    seq_axis: Optional[str] = None
    causal: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        head_dim = self.dim // self.heads
        qkv = nn.DenseGeneral((3, self.heads, head_dim), name="qkv")(x)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        if self.seq_axis is not None:
            out = ring_attention(q, k, v, self.seq_axis, causal=self.causal)
        else:
            out = attention(q, k, v, causal=self.causal)
        return nn.DenseGeneral(self.dim, axis=(-2, -1), name="proj")(out)


class TransformerEncoderBlock(nn.Module):
    dim: int
    heads: int
    mlp_ratio: int = 4
    seq_axis: Optional[str] = None
    causal: bool = False
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, training: bool = False):
        h = nn.LayerNorm()(x)
        h = _SelfAttention(self.dim, self.heads, self.seq_axis, self.causal)(h, training)
        if self.dropout > 0:
            h = nn.Dropout(self.dropout, deterministic=not training)(h)
        x = x + h
        h = nn.LayerNorm()(x)
        h = nn.Dense(self.dim * self.mlp_ratio)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.dim)(h)
        if self.dropout > 0:
            h = nn.Dropout(self.dropout, deterministic=not training)(h)
        return x + h


def _encode_tokens(tokens, *, vocab_size, dim, heads, num_layers, max_len,
                   seq_axis, causal, dropout, training):
    """Shared classifier/LM trunk: token + (block-offset) positional
    embeddings, encoder-block stack, final LayerNorm.  Must be called from
    inside an ``@nn.compact`` ``__call__`` — the modules it instantiates
    attach to the caller's scope (flat param names)."""
    tokens = tokens.astype(jnp.int32)
    block_len = tokens.shape[1]
    offset = lax.axis_index(seq_axis) * block_len if seq_axis is not None else 0
    positions = offset + jnp.arange(block_len)
    x = nn.Embed(vocab_size, dim, name="tok_embed")(tokens)
    x = x + nn.Embed(max_len, dim, name="pos_embed")(positions)[None]
    for i in range(num_layers):
        x = TransformerEncoderBlock(
            dim, heads, seq_axis=seq_axis, causal=causal,
            dropout=dropout, name=f"block_{i}",
        )(x, training)
    return nn.LayerNorm()(x)


class TransformerLM(nn.Module):
    """Causal language model over ``[batch, seq(block)]`` int32 tokens,
    emitting per-token next-token logits ``[batch, seq(block), vocab]``.

    Long-context first-class: with ``seq_axis`` set (inside ``shard_map``
    over that axis), attention runs as *causal ring attention* — each
    device holds one sequence block, K/V blocks rotate around the ring —
    and the per-token logits (and their integer labels, sharded by the
    engine) stay block-local, so memory per device is O(seq/shards).
    Train with ``loss="token_crossentropy"`` /
    ``metrics=("token_accuracy",)``.
    """

    vocab_size: int
    dim: int = 128
    heads: int = 4
    num_layers: int = 2
    max_len: int = 2048
    seq_axis: Optional[str] = None
    dropout: float = 0.0

    #: engines shard the label array like the token array (per-token labels)
    per_token_labels = True

    @nn.compact
    def __call__(self, tokens, training: bool = False):
        x = _encode_tokens(
            tokens, vocab_size=self.vocab_size, dim=self.dim, heads=self.heads,
            num_layers=self.num_layers, max_len=self.max_len,
            seq_axis=self.seq_axis, causal=True, dropout=self.dropout,
            training=training,
        )
        return nn.Dense(self.vocab_size, name="lm_head")(x)


class TransformerClassifier(nn.Module):
    """Token classifier over [batch, seq(block)] int32 inputs.

    With ``seq_axis`` set, the input is this device's sequence *block*;
    positional embeddings are offset by the block index and the head output
    is psum-pooled over the axis (replicated logits out).
    """

    vocab_size: int
    num_classes: int = 2
    dim: int = 128
    heads: int = 4
    num_layers: int = 2
    max_len: int = 2048
    seq_axis: Optional[str] = None
    causal: bool = False
    dropout: float = 0.0

    @nn.compact
    def __call__(self, tokens, training: bool = False):
        block_len = tokens.shape[1]
        seq_total = (
            block_len * lax.axis_size(self.seq_axis)
            if self.seq_axis is not None else block_len
        )
        x = _encode_tokens(
            tokens, vocab_size=self.vocab_size, dim=self.dim, heads=self.heads,
            num_layers=self.num_layers, max_len=self.max_len,
            seq_axis=self.seq_axis, causal=self.causal, dropout=self.dropout,
            training=training,
        )
        token_logits = nn.Dense(self.num_classes, name="head")(x)  # [b, blk, C]
        logits = token_logits.sum(axis=1) / seq_total
        if self.seq_axis is not None:
            logits = lax.psum(logits, self.seq_axis)
        return logits
