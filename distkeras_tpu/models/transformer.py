"""Transformer models with optional sequence parallelism.

Beyond the reference's model scale (SURVEY.md §5.7): a Transformer encoder
classifier whose sequence axis can be sharded over a mesh axis.  When
``seq_axis`` is set (running inside ``shard_map`` with that axis), attention
runs as ring attention (:mod:`distkeras_tpu.parallel.ring`) and the classifier
head pools *per-token logits* so every parameter-consuming op sees sharded
activations — which makes the cross-shard gradient sync a plain ``psum`` over
the sequence axis (done by the engine), with no replicated-activation
double-counting.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax

from distkeras_tpu.utils.compat import axis_size
import jax.numpy as jnp
from jax import lax

from distkeras_tpu.parallel.ring import attention, ring_attention

__all__ = ["TransformerClassifier", "TransformerEncoderBlock", "TransformerLM",
           "packed_positions"]


def packed_positions(segment_ids):
    """Per-segment positions ``[batch, width]`` from packed segment IDs
    (:func:`distkeras_tpu.datapipe.pack_sequences` convention: monotone
    per-row, 0 = pad): each token's index minus the index of its segment's
    first token, so every segment sees the positions ``0..len-1`` a
    standalone sequence would — computed on device with a cummax over
    segment starts (no host round-trip, no python loop)."""
    segment_ids = jnp.asarray(segment_ids)
    idx = jnp.arange(segment_ids.shape[1], dtype=jnp.int32)
    prev = jnp.concatenate(
        [jnp.full_like(segment_ids[:, :1], -1), segment_ids[:, :-1]], axis=1
    )
    is_start = segment_ids != prev
    start = lax.cummax(jnp.where(is_start, idx[None], 0), axis=1)
    return idx[None] - start


class _SelfAttention(nn.Module):
    dim: int
    heads: int
    seq_axis: Optional[str] = None
    causal: bool = False
    max_len: Optional[int] = None  # KV-cache capacity for decode mode

    @nn.compact
    def __call__(self, x, training: bool = False, decode: bool = False,
                 segment_ids=None):
        head_dim = self.dim // self.heads
        qkv = nn.DenseGeneral((3, self.heads, head_dim), name="qkv")(x)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        if decode:
            if segment_ids is not None:
                raise ValueError(
                    "segment_ids (sequence packing) is a training-path "
                    "feature; decode serves one sequence per row"
                )
            out = self._decode_attention(q, k, v)
        elif self.seq_axis is not None:
            if segment_ids is not None:
                raise ValueError(
                    "segment_ids is incompatible with seq_axis: ring "
                    "attention has no segment-mask block structure — pack "
                    "with seq_axis=None"
                )
            out = ring_attention(q, k, v, self.seq_axis, causal=self.causal)
        else:
            out = attention(q, k, v, causal=self.causal,
                            segment_ids=segment_ids)
        return nn.DenseGeneral(self.dim, axis=(-2, -1), name="proj")(out)

    def _decode_attention(self, q, k, v):
        """Chunked KV-cache attention for autoregressive decode: append this
        chunk's K/V at the cache cursor, attend the chunk's queries over the
        whole (padded) cache with position masking.  One code path serves
        prefill (chunk = prompt) and generation (chunk = 1 token); padded
        cache rows mask to exp(-inf) = 0 exactly, so the math matches the
        full-context recompute path (tests/test_generate.py).  Cache
        variables materialise on first use — run the prefill chunk with
        ``mutable=["cache"]`` and no separate cache-init call is needed."""
        if not self.causal or self.seq_axis is not None or self.max_len is None:
            raise ValueError(
                "KV-cache decode needs causal=True, seq_axis=None and "
                "max_len set (generation runs on the single-device twin)"
            )
        b, chunk, h, hd = q.shape
        cap = self.max_len
        ck = self.variable("cache", "cached_key", jnp.zeros, (b, cap, h, hd), k.dtype)
        cv = self.variable("cache", "cached_value", jnp.zeros, (b, cap, h, hd), v.dtype)
        idx = self.variable("cache", "cache_index",
                            lambda: jnp.zeros((), jnp.int32))
        i = idx.value
        ck.value = lax.dynamic_update_slice(ck.value, k, (0, i, 0, 0))
        cv.value = lax.dynamic_update_slice(cv.value, v, (0, i, 0, 0))
        idx.value = i + chunk
        # same layout/scale as ring.local_attention's reference math
        qt = jnp.moveaxis(q, 1, 2)                 # [b, h, chunk, hd]
        kt = jnp.moveaxis(ck.value, 1, 2)          # [b, h, cap, hd]
        vt = jnp.moveaxis(cv.value, 1, 2)
        scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
        s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
        q_pos = (i + jnp.arange(chunk))[:, None]   # [chunk, 1]
        key_pos = jnp.arange(cap)[None, :]         # [1, cap]
        s = jnp.where(key_pos <= q_pos, s, -jnp.inf)
        out = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), vt)
        # Decoding past max_len would silently clamp the cache write and
        # attend over corrupted rows; the cursor is traced so we cannot
        # raise — poison the output with NaN instead, which no plausible
        # generation survives unnoticed.  (greedy_generate validates
        # prompt+steps <= max_len statically and never hits this.)
        out = jnp.where(i + chunk > cap, jnp.nan, out)
        return jnp.moveaxis(out, 1, 2)


class TransformerEncoderBlock(nn.Module):
    dim: int
    heads: int
    mlp_ratio: int = 4
    seq_axis: Optional[str] = None
    causal: bool = False
    dropout: float = 0.0
    max_len: Optional[int] = None  # KV-cache capacity (decode mode only)
    ln_eps: float = 1e-6  # GPT-2 checkpoints use 1e-5 (models/hf_staged.py)

    @nn.compact
    def __call__(self, x, training: bool = False, decode: bool = False,
                 segment_ids=None):
        h = nn.LayerNorm(epsilon=self.ln_eps)(x)
        h = _SelfAttention(self.dim, self.heads, self.seq_axis, self.causal,
                           self.max_len)(h, training, decode,
                                         segment_ids=segment_ids)
        if self.dropout > 0:
            h = nn.Dropout(self.dropout, deterministic=not training)(h)
        x = x + h
        h = nn.LayerNorm(epsilon=self.ln_eps)(x)
        h = nn.Dense(self.dim * self.mlp_ratio)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.dim)(h)
        if self.dropout > 0:
            h = nn.Dropout(self.dropout, deterministic=not training)(h)
        return x + h


def _encode_tokens(tokens, *, vocab_size, dim, heads, num_layers, max_len,
                   seq_axis, causal, dropout, training, decode=False,
                   pos_offset=None, positions=None, segment_ids=None):
    """Shared classifier/LM trunk: token + (block-offset) positional
    embeddings, encoder-block stack, final LayerNorm.  Must be called from
    inside an ``@nn.compact`` ``__call__`` — the modules it instantiates
    attach to the caller's scope (flat param names).

    ``positions`` (``[batch, width]``, sequence packing) overrides the
    arange-derived positions with per-segment ones; ``segment_ids`` threads
    down to every block's attention mask."""
    tokens = tokens.astype(jnp.int32)
    block_len = tokens.shape[1]
    x = nn.Embed(vocab_size, dim, name="tok_embed")(tokens)
    pos_embed = nn.Embed(max_len, dim, name="pos_embed")
    if positions is not None:
        x = x + pos_embed(positions)
    else:
        if pos_offset is not None:
            offset = pos_offset
        else:
            offset = lax.axis_index(seq_axis) * block_len if seq_axis is not None else 0
        x = x + pos_embed(offset + jnp.arange(block_len))[None]
    for i in range(num_layers):
        x = TransformerEncoderBlock(
            dim, heads, seq_axis=seq_axis, causal=causal,
            dropout=dropout, max_len=max_len, name=f"block_{i}",
        )(x, training, decode, segment_ids=segment_ids)
    return nn.LayerNorm()(x)


class TransformerLM(nn.Module):
    """Causal language model over ``[batch, seq(block)]`` int32 tokens,
    emitting per-token next-token logits ``[batch, seq(block), vocab]``.

    Long-context first-class: with ``seq_axis`` set (inside ``shard_map``
    over that axis), attention runs as *causal ring attention* — each
    device holds one sequence block, K/V blocks rotate around the ring —
    and the per-token logits (and their integer labels, sharded by the
    engine) stay block-local, so memory per device is O(seq/shards).
    Train with ``loss="token_crossentropy"`` /
    ``metrics=("token_accuracy",)``.

    ``packed=True`` consumes sequence-packed input
    (:func:`distkeras_tpu.datapipe.pack_sequences`): ``[batch, width, 2]``
    int32 with token and segment-ID channels
    (:meth:`PackedBatch.model_inputs`).  Positions restart per segment and
    attention is masked intra-segment, so each packed segment's logits
    equal the logits the sequence would get alone in a row
    (tests/test_datapipe.py pins this).  Train packed models with
    ``loss="masked_token_crossentropy"`` — the packer marks pads and
    segment tails with ``-1`` labels.
    """

    vocab_size: int
    dim: int = 128
    heads: int = 4
    num_layers: int = 2
    max_len: int = 2048
    seq_axis: Optional[str] = None
    dropout: float = 0.0
    packed: bool = False

    #: engines shard the label array like the token array (per-token labels)
    per_token_labels = True

    @nn.compact
    def __call__(self, tokens, training: bool = False, decode: bool = False):
        pos_offset = None
        positions = None
        segment_ids = None
        if self.packed:
            if decode:
                raise ValueError(
                    "packed=True is a training-path layout; decode with a "
                    "packed=False twin (same params)"
                )
            if self.seq_axis is not None:
                raise ValueError(
                    "packed=True is incompatible with seq_axis (ring "
                    "attention has no segment-mask block structure)"
                )
            tokens, segment_ids = tokens[..., 0], tokens[..., 1]
            segment_ids = segment_ids.astype(jnp.int32)
            positions = packed_positions(segment_ids)
        if decode:
            # decode chunks carry no absolute positions; a top-level cache
            # cursor supplies them (prefill advances it by the prompt length,
            # each generation step by 1)
            pi = self.variable("cache", "pos_index",
                               lambda: jnp.zeros((), jnp.int32))
            pos_offset = pi.value
            pi.value = pos_offset + tokens.shape[1]
        x = _encode_tokens(
            tokens, vocab_size=self.vocab_size, dim=self.dim, heads=self.heads,
            num_layers=self.num_layers, max_len=self.max_len,
            seq_axis=self.seq_axis, causal=True, dropout=self.dropout,
            training=training, decode=decode, pos_offset=pos_offset,
            positions=positions, segment_ids=segment_ids,
        )
        return nn.Dense(self.vocab_size, name="lm_head")(x)

    def decode_spec(self, params):
        """Slice ``params`` into the layout the serving engine consumes
        (:mod:`distkeras_tpu.serving.engine`): embedding tables, per-block
        subtrees, final LayerNorm, LM head, plus static config.  Kept next
        to the model so the serving layer cannot drift from the param tree
        this module actually builds."""
        if self.seq_axis is not None:
            raise ValueError(
                "serving decodes on the single-device twin — build the "
                "engine from a seq_axis=None model with the same params"
            )
        return {
            "config": {
                "dim": self.dim, "heads": self.heads,
                # explicit head geometry: the engine's tensor-parallel build
                # shards the qkv kernels over heads, so the global count must
                # come from config, not from (shard-local) kernel shapes
                "head_dim": self.dim // self.heads,
                "num_layers": self.num_layers, "max_len": self.max_len,
                "vocab_size": self.vocab_size,
                # blocks and the final LayerNorm both use the flax default
                "ln_eps": 1e-6,
            },
            "embed": {
                "tok": params["tok_embed"]["embedding"],
                "pos": params["pos_embed"]["embedding"],
            },
            "blocks": [params[f"block_{i}"] for i in range(self.num_layers)],
            "final_ln": params["LayerNorm_0"],
            "head": params["lm_head"],
        }


class TransformerClassifier(nn.Module):
    """Token classifier over [batch, seq(block)] int32 inputs.

    With ``seq_axis`` set, the input is this device's sequence *block*;
    positional embeddings are offset by the block index and the head output
    is psum-pooled over the axis (replicated logits out).
    """

    vocab_size: int
    num_classes: int = 2
    dim: int = 128
    heads: int = 4
    num_layers: int = 2
    max_len: int = 2048
    seq_axis: Optional[str] = None
    causal: bool = False
    dropout: float = 0.0

    @nn.compact
    def __call__(self, tokens, training: bool = False):
        block_len = tokens.shape[1]
        seq_total = (
            block_len * axis_size(self.seq_axis)
            if self.seq_axis is not None else block_len
        )
        x = _encode_tokens(
            tokens, vocab_size=self.vocab_size, dim=self.dim, heads=self.heads,
            num_layers=self.num_layers, max_len=self.max_len,
            seq_axis=self.seq_axis, causal=self.causal, dropout=self.dropout,
            training=training,
        )
        token_logits = nn.Dense(self.num_classes, name="head")(x)  # [b, blk, C]
        logits = token_logits.sum(axis=1) / seq_total
        if self.seq_axis is not None:
            logits = lax.psum(logits, self.seq_axis)
        return logits
