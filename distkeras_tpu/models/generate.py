"""KV-cached greedy decoding for the LM family.

The reference has no generation at all (its predictors are classifier-shaped
— ``distkeras/predictors.py :: ModelPredictor`` appends one prediction column
per row); this is beyond-reference capability rounding out the causal-LM
story.  Decoding is serving-shaped, built the TPU way:

  * ONE jitted program per (prompt-shape, steps): the prefill chunk runs the
    whole prompt through the model once (MXU-friendly — a real matmul, not
    token-at-a-time), then a ``lax.scan`` carries the KV cache through the
    single-token generation steps.  No per-token Python, no retracing.
  * the KV cache is a pytree of static-shape ``[batch, max_len, heads, dim]``
    buffers written at a cursor (``lax.dynamic_update_slice``) — attention
    per step is O(context), not O(context²) like full-context recompute.
  * padded cache positions mask to ``exp(-inf) = 0`` exactly, so cached
    decode emits the SAME tokens as the recompute path
    (tests/test_generate.py asserts identity).

Supports the in-tree causal models: ``TransformerLM`` (through ``FlaxModel``
or a ``TrainedModel``) and ``StagedLM`` — sequentially on one device by
default, or through its pipeline mesh with ``pipelined=True``
(:func:`greedy_generate_staged_pipelined`): per-device residency is ONE
stage's blocks + ONE stage's KV cache, so a model whose block stack does not
fit one chip decodes from ``num_stages`` chips (VERDICT r4 weak #5 / item 7).
HuggingFace adapters ship their own ``generate`` — use that for HF
checkpoints.
"""

from __future__ import annotations

import inspect
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.utils.compat import shard_map
from jax import lax

__all__ = ["greedy_generate", "greedy_generate_staged_pipelined"]

# Compiled decode programs keyed by (id(target), steps), bounded LRU.
# jax.jit caches per function object, so a per-call closure would recompile
# every generate call; the value keeps a strong reference to the target so a
# live entry's id cannot alias (the identity check covers ids recycled after
# eviction), and the LRU bound keeps a long-lived process from pinning every
# model it ever generated from.
from collections import OrderedDict

_DECODE_PROGRAMS: OrderedDict = OrderedDict()
_DECODE_PROGRAMS_MAX = 32


def _decode_program(target, steps: int, build):
    key = (id(target), steps)
    hit = _DECODE_PROGRAMS.get(key)
    if hit is None or hit[0] is not target:
        _DECODE_PROGRAMS[key] = hit = (target, jax.jit(build()))
    _DECODE_PROGRAMS.move_to_end(key)
    while len(_DECODE_PROGRAMS) > _DECODE_PROGRAMS_MAX:
        _DECODE_PROGRAMS.popitem(last=False)
    return hit[1]


def _resolve(model) -> tuple:
    """(kind, target, params) from a TrainedModel / adapter+params pair."""
    from distkeras_tpu.models.adapter import FlaxModel, TrainedModel

    if isinstance(model, TrainedModel):
        adapter, params = model.adapter, model.params
    else:
        raise TypeError(
            "greedy_generate expects the TrainedModel a trainer returned "
            f"(got {type(model).__name__}); for raw params use "
            "greedy_generate_module / greedy_generate_staged"
        )
    if hasattr(adapter, "decode_step"):  # StagedLM
        return "staged", adapter, params
    module = getattr(adapter, "module", None)
    # decode capability, not just LM shape: a classifier also has max_len
    # but its __call__ takes no decode kwarg — reject it here by name, not
    # with a flax TypeError three frames deep
    if (
        isinstance(adapter, FlaxModel)
        and module is not None
        and hasattr(module, "max_len")
        and "decode" in inspect.signature(type(module).__call__).parameters
    ):
        return "flax", module, params
    raise TypeError(
        f"model {type(adapter).__name__}"
        f"({type(module).__name__ if module is not None else ''}) has no "
        "KV-cache decode path (supported: TransformerLM, StagedLM)"
    )


def greedy_generate(model, prompt, steps: int, *, pipelined: bool = False) -> np.ndarray:
    """Greedily extend ``prompt`` ``[batch, prompt_len]`` by ``steps`` tokens
    with a carried KV cache; returns ``[batch, prompt_len + steps]`` int32
    (prompt included) — the batched analogue of the predictor shape.

    ``pipelined=True`` (StagedLM only) decodes through the pipeline mesh —
    one stage of blocks + cache per device — instead of the single-device
    sequential executor."""
    kind, target, params = _resolve(model)
    if kind == "staged":
        if pipelined:
            return greedy_generate_staged_pipelined(target, params, prompt, steps)
        return greedy_generate_staged(target, params, prompt, steps)
    if pipelined:
        raise TypeError(
            f"pipelined decode needs a StagedLM (got {type(target).__name__})"
        )
    return greedy_generate_module(target, params, prompt, steps)


def _check(prompt, steps, max_len):
    prompt = jnp.asarray(prompt, jnp.int32)
    if prompt.ndim != 2:
        raise ValueError(f"prompt must be [batch, len], got {prompt.shape}")
    if steps < 0:
        raise ValueError(f"steps must be >= 0, got {steps}")
    if prompt.shape[1] + steps > max_len:
        raise ValueError(
            f"prompt ({prompt.shape[1]}) + steps ({steps}) exceeds the "
            f"model's max_len ({max_len}) — the KV cache is sized to it"
        )
    return prompt


def greedy_generate_module(module, params, prompt, steps: int) -> np.ndarray:
    """KV-cached greedy decode on a flax causal LM with ``decode`` support
    (``TransformerLM``): prefill + scanned single-token steps, one program."""
    prompt = _check(prompt, steps, module.max_len)
    if steps == 0:
        return np.asarray(prompt)

    def build():
        def run(params, prompt):
            logits, var = module.apply(
                {"params": params}, prompt, decode=True, mutable=["cache"]
            )
            tok = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)

            def body(carry, _):
                cache, tok = carry
                logits, var = module.apply(
                    {"params": params, "cache": cache}, tok[:, None],
                    decode=True, mutable=["cache"],
                )
                nxt = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
                return (var["cache"], nxt), nxt

            (_, _), rest = lax.scan(
                body, (var["cache"], tok), None, length=steps - 1
            )
            return jnp.moveaxis(jnp.concatenate([tok[None], rest], axis=0), 0, 1)

        return run

    run = _decode_program(module, steps, build)
    return np.concatenate([np.asarray(prompt), np.asarray(run(params, prompt))], axis=1)


def greedy_generate_staged(staged, params, prompt, steps: int) -> np.ndarray:
    """KV-cached greedy decode on a ``StagedLM`` via its sequential executor
    (:meth:`StagedLM.decode_step`)."""
    prompt = _check(prompt, steps, staged.max_len)
    if steps == 0:
        return np.asarray(prompt)
    cache = staged.init_cache(prompt.shape[0])

    def build():
        def run(params, cache, prompt):
            logits, cache = staged.decode_step(params, cache, prompt, 0)
            tok = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)

            def body(carry, pos):
                cache, tok = carry
                logits, cache = staged.decode_step(params, cache, tok[:, None], pos)
                nxt = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
                return (cache, nxt), nxt

            positions = prompt.shape[1] + jnp.arange(steps - 1, dtype=jnp.int32)
            (_, _), rest = lax.scan(body, (cache, tok), positions)
            return jnp.moveaxis(jnp.concatenate([tok[None], rest], axis=0), 0, 1)

        return run

    run = _decode_program(staged, steps, build)
    return np.concatenate(
        [np.asarray(prompt), np.asarray(run(params, cache, prompt))], axis=1
    )


def greedy_generate_staged_pipelined(
    staged, params, prompt, steps: int, devices=None
) -> np.ndarray:
    """KV-cached greedy decode of a ``StagedLM`` THROUGH its pipeline mesh.

    The sequential executor (:func:`greedy_generate_staged`) needs every
    block's params AND every block's KV cache resident on one device — a
    model trained across ``num_stages`` devices *because it doesn't fit one*
    couldn't generate (VERDICT r4 weak #5).  Here the ``stages`` mesh axis
    shards both: per-device residency is one stage's blocks + one stage's
    cache; embed/head ride in replicated (a model TRAINED with stage-sharded
    embed/head — ``PipelineEngine(fsdp=True)`` — decodes from its
    host-gathered center, ``gather_center``, so decode sees full leaves).

    Schedule (the SPMD pipelining idiom of ``parallel/pipeline.py``): each
    decode chunk rides a ``num_stages``-iteration ring — every device applies
    its local stage every iteration, adopt-gates the result to the device
    whose turn it is (``lax.axis_index == s``), and ``ppermute``s the
    activation to its neighbour over ICI.  Token latency is the same
    ``num_stages`` sequential stage-applies the one-device executor pays, so
    tokens are IDENTICAL (tests/test_generate_pp.py asserts it); off-turn
    applies are redundant compute, the price of static SPMD control flow.
    """
    from jax.sharding import Mesh, PartitionSpec as P

    from distkeras_tpu.parallel.pipeline import PP_AXIS
    from distkeras_tpu.utils.pytree import tree_where

    prompt = _check(prompt, steps, staged.max_len)
    n_stages = staged.num_stages
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < n_stages:
        raise ValueError(
            f"{n_stages} pipeline stages need >= {n_stages} devices, got "
            f"{len(devices)}"
        )
    mesh = Mesh(np.array(devices[:n_stages]), (PP_AXIS,))
    if steps == 0:
        return np.asarray(prompt)

    # [n_blocks, ...] flat cache -> [S, per_stage, ...] so the leading dim
    # shards over the stages axis like the block params do
    cache = jax.tree.map(
        lambda x: x.reshape((n_stages, staged.blocks_per_stage) + x.shape[1:]),
        staged.init_cache(prompt.shape[0]),
    )
    ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def build():
        def stage_apply(blocks, cache, h):
            # one stage = blocks_per_stage cached blocks, leaves [per_stage, ...]
            def body(x, bc):
                p, c = bc
                y, upd = staged._block.apply(
                    {"params": p, "cache": c}, x, decode=True, mutable=["cache"]
                )
                return y, upd["cache"]

            return lax.scan(body, h, (blocks, cache))

        def ring_chunk(blocks, cache, h, idx):
            # h: [b, chunk, d], the embed output (replicated).  Iteration s:
            # device s's apply is the real one — adopt its output + cache
            # there, pass the activation to s+1.  Stage S-1's output lands on
            # device 0; a masked psum replicates it for the head.
            def body(carry, s):
                h, cache = carry
                y, new_cache = stage_apply(blocks, cache, h)
                adopt = idx == s
                cache = tree_where(adopt, new_cache, cache)
                h = jnp.where(adopt, y, h)
                h = lax.ppermute(h, PP_AXIS, ring)
                return (h, cache), None

            (h, cache), _ = lax.scan(
                body, (h, cache), jnp.arange(n_stages, dtype=jnp.int32)
            )
            h = lax.psum(jnp.where(idx == 0, h, jnp.zeros_like(h)), PP_AXIS)
            return h, cache

        def run(params, cache, prompt):
            idx = lax.axis_index(PP_AXIS)
            blocks = jax.tree.map(lambda x: x[0], params["blocks"])
            cache = jax.tree.map(lambda x: x[0], cache)
            h = staged.embed(params["embed"], prompt)
            h, cache = ring_chunk(blocks, cache, h, idx)
            tok = jnp.argmax(
                staged.head(params["head"], h)[:, -1], -1
            ).astype(jnp.int32)

            def body(carry, pos):
                cache, tok = carry
                h = staged.embed(params["embed"], tok[:, None], offset=pos)
                h, cache = ring_chunk(blocks, cache, h, idx)
                nxt = jnp.argmax(
                    staged.head(params["head"], h)[:, -1], -1
                ).astype(jnp.int32)
                return (cache, nxt), nxt

            positions = prompt.shape[1] + jnp.arange(steps - 1, dtype=jnp.int32)
            (_, _), rest = lax.scan(body, (cache, tok), positions)
            return jnp.moveaxis(jnp.concatenate([tok[None], rest], axis=0), 0, 1)

        mapped = shard_map(
            run,
            mesh=mesh,
            in_specs=(
                {"embed": P(), "blocks": P(PP_AXIS), "head": P()},
                P(PP_AXIS),
                P(),
            ),
            out_specs=P(),
            check_vma=False,
        )
        return mapped

    # key carries the mesh's device ids: a later call with different
    # devices must not reuse a program compiled for the first mesh
    dev_key = tuple(d.id for d in mesh.devices.flat)
    run = _decode_program(staged, ("pp", steps, dev_key), build)
    return np.concatenate(
        [np.asarray(prompt), np.asarray(run(params, cache, prompt))], axis=1
    )
