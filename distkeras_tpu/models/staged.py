"""Pipeline-staged transformer: the model half of pipeline parallelism.

The reference has no pipeline parallelism (its only strategy is socket
parameter-server data parallelism — SURVEY.md §2 parallelism census); this is
a beyond-reference strategy in the same spirit as the GSPMD tensor-parallel
engine.  The TPU-idiomatic formulation (scaling-book pipelining chapter): a
stack of **homogeneous** transformer blocks is split into ``num_stages``
stages of ``blocks_per_stage`` blocks each, block parameters are *stacked*
along a leading ``[num_stages]`` axis so they shard cleanly over a ``stages``
mesh axis, and microbatches stream through the stages via ``ppermute``
neighbour exchanges (see :mod:`distkeras_tpu.parallel.pipeline`).

The embedding and the classifier head are deliberately *not* staged: they
stay replicated and are computed by every stage device (masked into the
pipeline on stage 0 / the last stage).  When they are NOT small next to the
block stack — vocab-scale LM embeddings and heads — ``PipelineEngine(...,
fsdp=True)`` stores them (and their optimizer state) sharded 1/num_stages
per device and all-gathers at use (:mod:`distkeras_tpu.parallel.pipeline`),
trajectory-identical to the replicated layout.

``StagedTransformer`` is a plain :class:`ModelAdapter` whose ``apply`` runs
the stages **sequentially** — the single-device reference semantics used for
initialisation, prediction, and the equivalence tests.  The pipelined
schedule is a different *executor* of the same parameters, not a different
model.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from distkeras_tpu.models.adapter import ModelAdapter
from distkeras_tpu.models.transformer import TransformerEncoderBlock

__all__ = ["StagedTransformer", "StagedLM", "stack_block_params"]


def stack_block_params(per_block, num_stages, blocks_per_stage, xp=jnp):
    """Fold a list of per-block param trees into the staged
    ``[num_stages, blocks_per_stage, ...]`` leaf layout — THE contract
    :class:`~distkeras_tpu.parallel.pipeline.PipelineEngine`'s stage
    sharding relies on, kept in one place so init and checkpoint
    conversion (``models/hf_staged.py``) cannot drift.  ``xp=np`` keeps
    converted checkpoints as host leaves (no eager device transfer)."""
    stacked = jax.tree.map(lambda *xs: xp.stack(xs), *per_block)
    return jax.tree.map(
        lambda x: x.reshape((num_stages, blocks_per_stage) + x.shape[1:]),
        stacked,
    )


class _Embed(nn.Module):
    vocab_size: int
    dim: int
    max_len: int

    @nn.compact
    def __call__(self, tokens, offset=0, positions=None):
        tokens = tokens.astype(jnp.int32)
        x = nn.Embed(self.vocab_size, self.dim, name="tok_embed")(tokens)
        pos_embed = nn.Embed(self.max_len, self.dim, name="pos_embed")
        if positions is not None:
            # sequence packing: batched [b, width] per-segment positions
            return x + pos_embed(positions)
        return x + pos_embed(offset + jnp.arange(tokens.shape[1]))[None]


class _Head(nn.Module):
    num_classes: int
    ln_eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(epsilon=self.ln_eps)(x)
        token_logits = nn.Dense(self.num_classes, name="out")(x)
        return token_logits.sum(axis=1) / x.shape[1]


class _LMHead(nn.Module):
    vocab_size: int
    ln_eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(epsilon=self.ln_eps)(x)
        return nn.Dense(self.vocab_size, name="out")(x)  # [b, seq, vocab]


@dataclasses.dataclass
class StagedTransformer(ModelAdapter):
    """Token classifier over ``[batch, seq]`` int32 inputs with its encoder
    blocks stacked ``[num_stages, blocks_per_stage, ...]`` for pipelining.

    Parameter layout (the contract :class:`~distkeras_tpu.parallel.pipeline.
    PipelineEngine` relies on)::

        {"embed": <replicated>, "blocks": <leaves [S, per_stage, ...]>,
         "head": <replicated>}
    """

    vocab_size: int
    num_classes: int = 2
    dim: int = 128
    heads: int = 4
    num_stages: int = 2
    blocks_per_stage: int = 1
    max_len: int = 2048
    ln_eps: float = 1e-6  # 1e-5 for GPT-2 checkpoints (models/hf_staged.py)
    #: set to the seq mesh axis name for pipeline x sequence parallelism:
    #: blocks run ring attention over it and the engine shards tokens/labels
    #: along it (PipelineEngine(seq_shards=k)); decode needs a seq_axis=None
    #: twin — `dataclasses.replace(model, seq_axis=None)`, same params
    seq_axis: Optional[str] = None
    outputs_logits: bool = True

    def __post_init__(self):
        self._embed = _Embed(self.vocab_size, self.dim, self.max_len)
        self._block = self._make_block()
        self._head = self._make_head()

    def _make_block(self):
        return TransformerEncoderBlock(self.dim, self.heads,
                                       seq_axis=self.seq_axis,
                                       ln_eps=self.ln_eps)

    def _make_head(self):
        return _Head(self.num_classes, ln_eps=self.ln_eps)

    # ------------------------------------------------------------------ init
    def init(self, rng: jax.Array, sample_input) -> Tuple[Any, Any]:
        sample = jnp.asarray(sample_input)
        r_embed, r_blocks, r_head = jax.random.split(rng, 3)
        embed_p = self._embed.init(r_embed, sample)["params"]
        h = self._embed.apply({"params": embed_p}, sample)
        n_blocks = self.num_stages * self.blocks_per_stage
        # homogeneous blocks: init each with its own key, stack the pytrees,
        # then fold the flat [n_blocks] axis into [S, per_stage]
        block_ps = [
            self._block.init(jax.random.fold_in(r_blocks, i), h)["params"]
            for i in range(n_blocks)
        ]
        stacked = stack_block_params(
            block_ps, self.num_stages, self.blocks_per_stage
        )
        head_p = self._head.init(r_head, h)["params"]
        return {"embed": embed_p, "blocks": stacked, "head": head_p}, {}

    # ------------------------------------------------- stage pieces (public
    # to the pipeline engine; all pure functions of explicit params)
    def embed(self, embed_params, tokens, offset=0, positions=None):
        return self._embed.apply({"params": embed_params}, tokens, offset,
                                 positions)

    def stage(self, stage_params, h, segment_ids=None):
        """Apply one stage: scan ``blocks_per_stage`` blocks whose param
        leaves carry a leading ``[blocks_per_stage]`` axis.  ``segment_ids``
        (sequence packing) threads to every block's attention mask."""

        def body(x, p):
            return self._block.apply(
                {"params": p}, x, segment_ids=segment_ids), None

        h, _ = lax.scan(body, h, stage_params)
        return h

    def head(self, head_params, h):
        return self._head.apply({"params": head_params}, h)

    # ----------------------------------------------------------- sequential
    def apply(self, params, state, inputs, training=False, rng=None):
        h = self.embed(params["embed"], inputs)

        def body(x, p):
            return self.stage(p, x), None

        h, _ = lax.scan(body, h, params["blocks"])
        return self.head(params["head"], h), state


@dataclasses.dataclass
class StagedLM(StagedTransformer):
    """Pipeline-staged causal language model: the GPipe-for-LM shape.

    Same staged layout as :class:`StagedTransformer` (embed replicated,
    homogeneous block stages stacked ``[S, per_stage, ...]``, head
    replicated) with causal blocks and a per-token vocab head — trained
    with ``loss="token_crossentropy"``; the engines shard the integer
    label array like the tokens (``per_token_labels``).  Output width is
    ``vocab_size``; the inherited ``num_classes`` field does not apply.

    ``packed=True`` consumes sequence-packed ``[batch, width, 2]`` input
    (token + segment-ID channels, :meth:`PackedBatch.model_inputs`) through
    the *sequential* executor: per-segment positions, intra-segment
    attention masks, train with ``loss="masked_token_crossentropy"``.
    The pipeline schedule (``pipeline_stages>1``) does not thread segment
    IDs — train packed StagedLMs on the windowed/GSPMD engines.
    """

    per_token_labels: bool = True
    packed: bool = False

    def __post_init__(self):
        if self.num_classes != type(self).num_classes:
            raise ValueError(
                "StagedLM outputs vocab_size-wide logits; num_classes does "
                "not apply — did you mean StagedTransformer?"
            )
        super().__post_init__()

    def _make_block(self):
        # max_len sizes the per-block KV cache for decode (training ignores
        # it); with seq_axis set, attention is CAUSAL RING attention and
        # decode requires the seq_axis=None twin (see StagedTransformer)
        return TransformerEncoderBlock(self.dim, self.heads, causal=True,
                                       max_len=self.max_len,
                                       seq_axis=self.seq_axis,
                                       ln_eps=self.ln_eps)

    def _make_head(self):
        return _LMHead(self.vocab_size, ln_eps=self.ln_eps)

    # ------------------------------------------------------------------ init
    def init(self, rng: jax.Array, sample_input) -> Tuple[Any, Any]:
        if self.packed:
            # init on the token channel: the packed and unpacked executors
            # share one param tree (the parity test swaps params between them)
            sample_input = jnp.asarray(sample_input)[..., 0]
        return super().init(rng, sample_input)

    # ----------------------------------------------------------- sequential
    def apply(self, params, state, inputs, training=False, rng=None):
        if not self.packed:
            return super().apply(params, state, inputs, training, rng)
        if self.seq_axis is not None:
            raise ValueError(
                "packed=True is incompatible with seq_axis (ring attention "
                "has no segment-mask block structure)"
            )
        from distkeras_tpu.models.transformer import packed_positions

        tokens = inputs[..., 0]
        segment_ids = inputs[..., 1].astype(jnp.int32)
        h = self.embed(params["embed"], tokens,
                       positions=packed_positions(segment_ids))

        def body(x, p):
            return self.stage(p, x, segment_ids=segment_ids), None

        h, _ = lax.scan(body, h, params["blocks"])
        return self.head(params["head"], h), state

    # ------------------------------------------------------- KV-cache decode
    def init_cache(self, batch_size: int, dtype=jnp.float32):
        """Zeroed per-block KV caches, stacked ``[n_blocks, ...]`` to scan
        with the flat block stack in :meth:`decode_step`."""
        dummy = jnp.zeros((batch_size, 1, self.dim), dtype)
        shapes = jax.eval_shape(
            lambda: self._block.init(jax.random.PRNGKey(0), dummy, decode=True)
        )["cache"]
        n_blocks = self.num_stages * self.blocks_per_stage
        return jax.tree.map(
            lambda s: jnp.zeros((n_blocks,) + s.shape, s.dtype), shapes
        )

    def decode_step(self, params, cache, tokens, pos_offset):
        """Run one decode chunk (prompt at prefill, 1 token per generation
        step) through the *sequential* stage stack with per-block KV caches:
        returns ``(logits [b, chunk, vocab], new_cache)``.  Same math as the
        full-context ``apply`` on the prefix (tests/test_generate.py); like
        prediction, generation runs on the plain sequential executor — the
        pipeline is a training-time schedule."""
        h = self.embed(params["embed"], tokens, offset=pos_offset)
        flat_blocks = jax.tree.map(
            lambda x: x.reshape((-1,) + x.shape[2:]), params["blocks"]
        )

        def body(x, block):
            p, c = block
            y, upd = self._block.apply(
                {"params": p, "cache": c}, x, decode=True, mutable=["cache"]
            )
            return y, upd["cache"]

        h, new_cache = lax.scan(body, h, (flat_blocks, cache))
        return self.head(params["head"], h), new_cache

    def decode_spec(self, params):
        """Slice staged params into the serving engine's layout
        (:mod:`distkeras_tpu.serving.engine`): the ``[S, per_stage, ...]``
        block stack unfolds into a flat per-block list (same order as
        :meth:`decode_step`'s scan); embed/head are already replicated.
        Like prediction, serving runs the sequential executor — the
        pipeline is a training-time schedule."""
        if self.seq_axis is not None:
            raise ValueError(
                "serving decodes on the single-device twin — build the "
                "engine from a seq_axis=None replica "
                "(dataclasses.replace(model, seq_axis=None), same params)"
            )
        flat = jax.tree.map(
            lambda x: x.reshape((-1,) + x.shape[2:]), params["blocks"]
        )
        n_blocks = self.num_stages * self.blocks_per_stage
        return {
            "config": {
                "dim": self.dim, "heads": self.heads,
                # explicit head geometry for the engine's tensor-parallel
                # build (global count, independent of kernel sharding)
                "head_dim": self.dim // self.heads,
                "num_layers": n_blocks, "max_len": self.max_len,
                "vocab_size": self.vocab_size, "ln_eps": self.ln_eps,
            },
            "embed": {
                "tok": params["embed"]["tok_embed"]["embedding"],
                "pos": params["embed"]["pos_embed"]["embedding"],
            },
            "blocks": [
                jax.tree.map(lambda x, i=i: x[i], flat) for i in range(n_blocks)
            ],
            "final_ln": params["head"]["LayerNorm_0"],
            "head": params["head"]["out"],
        }
