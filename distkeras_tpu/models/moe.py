"""Mixture-of-experts models + expert parallelism placement.

Beyond the reference's model scale (its zoo tops out at ResNet-20 /
TextCNN — SURVEY.md §2): a Switch-style sparse MoE transformer whose expert
FFNs are *stacked* along a leading ``[num_experts]`` axis, formulated the
GShard/Switch way — static-shape one-hot dispatch/combine einsums with a
per-expert token capacity — so XLA can partition the expert axis over the
device mesh (expert parallelism) with no data-dependent shapes.

Expert parallelism rides the GSPMD engine: :func:`expert_partition` is a
``spec_fn`` for :class:`~distkeras_tpu.parallel.gspmd.GSPMDEngine` that
places the leading expert axis of every ``[num_experts, ...]`` leaf on the
``model`` mesh axis; the XLA partitioner inserts the token-shuffling
collectives the placement implies (the all-to-all of a hand-written MoE).

Routing is top-k: ``top_k=1`` (the default) is Switch — each token goes to
its argmax expert, scaled by the router probability (the gradient path to
the router); ``top_k>1`` is GShard-style — each token visits its k best
experts with renormalised gate weights.  Per-expert capacity is
``ceil(capacity_factor * top_k * N / E)`` slots, filled rank-major (first
choices always outrank second choices); assignments beyond capacity are
*dropped* (contribute zero) — deterministic, no jitter.  The load-balance
auxiliary loss ``E * sum_e f_e * P_e`` is exposed
through a mutable ``losses`` collection; the training engines add
``adapter.aux_loss(state)`` to the objective (ModelAdapter contract).
"""

from __future__ import annotations

import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from distkeras_tpu.models.transformer import _SelfAttention

__all__ = ["MoEFeedForward", "MoEEncoderBlock", "MoETransformerClassifier",
           "expert_partition"]


_EXPERT_PARAM_NAMES = frozenset({"w1", "b1", "w2", "b2"})


def expert_partition(num_experts: int, axis: str = "model"):
    """``spec_fn`` for GSPMDEngine: shard the leading expert axis of the
    MoE FFN stacks over ``axis``; everything else falls through to the
    engine's default TP rule.

    Matches by param *path* (a ``MoEFeedForward`` module owning a
    w1/b1/w2/b2 leaf) plus the ``[num_experts, ...]`` shape — a bare-shape
    rule would also capture e.g. an attention ``(heads, head_dim, dim)``
    kernel whenever ``heads == num_experts``."""

    def spec_fn(shape, path=()):
        in_moe = any("MoEFeedForward" in str(k) for k in path)
        named = path and str(path[-1]) in _EXPERT_PARAM_NAMES
        if in_moe and named and len(shape) >= 2 and shape[0] == num_experts:
            return P(axis)
        return None

    return spec_fn


class MoEFeedForward(nn.Module):
    """Routed FFN bank with static-shape dispatch/combine einsums.

    ``top_k=1`` is Switch (output scaled by the chosen expert's softmax
    prob — the router's gradient path); ``top_k>1`` is GShard-style (each
    token visits its top-k experts, combine weights are the top-k gates
    renormalised to sum to 1).  Capacity is per expert,
    ``ceil(capacity_factor * top_k * N / E)`` slots, filled rank-major so a
    token's first-choice assignment always outranks any second choice."""

    dim: int
    num_experts: int
    mlp_ratio: int = 4
    top_k: int = 1
    capacity_factor: float = 1.25
    aux_weight: float = 1e-2

    @nn.compact
    def __call__(self, x, training: bool = False):
        b, t, d = x.shape
        e = self.num_experts
        k = self.top_k
        if not 1 <= k <= e:
            raise ValueError(f"top_k={k} must be in [1, num_experts={e}]")
        n = b * t
        capacity = max(1, math.ceil(self.capacity_factor * k * n / e))
        hidden = self.dim * self.mlp_ratio

        tokens = x.reshape(n, d)
        router_logits = nn.Dense(e, name="router")(tokens)  # [N, E]
        gates = jax.nn.softmax(router_logits.astype(jnp.float32))
        top_gates, top_idx = jax.lax.top_k(gates, k)  # [N, k] each
        onehots = jax.nn.one_hot(top_idx, e, dtype=jnp.float32)  # [N, k, E]

        # capacity accounting, rank-major: every rank-0 assignment is queued
        # before any rank-1 assignment, so second choices only consume slots
        # first choices left free
        oh_flat = jnp.moveaxis(onehots, 1, 0).reshape(k * n, e)  # [kN, E]
        pos = (jnp.cumsum(oh_flat, axis=0) - 1.0) * oh_flat
        keep = (pos < capacity).astype(jnp.float32) * oh_flat
        slot = jax.nn.one_hot(pos.sum(-1).astype(jnp.int32), capacity,
                              dtype=jnp.float32)  # [kN, C]
        disp_ranks = (keep[:, :, None] * slot[:, None, :]).reshape(
            k, n, e, capacity)
        dispatch = disp_ranks.sum(0)  # [N, E, C]

        # combine weights: Switch prob for k=1, renormalised top-k otherwise
        if k == 1:
            scale = top_gates  # [N, 1]
        else:
            scale = top_gates / top_gates.sum(-1, keepdims=True)
        combine = jnp.einsum("rnec,nr->nec", disp_ranks, scale)

        # per-expert dense stacks [E, ...] — the leaves expert_partition shards
        w1 = self.param("w1", nn.initializers.lecun_normal(), (e, d, hidden))
        b1 = self.param("b1", nn.initializers.zeros, (e, hidden))
        w2 = self.param("w2", nn.initializers.lecun_normal(), (e, hidden, d))
        b2 = self.param("b2", nn.initializers.zeros, (e, d))

        xin = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), tokens)
        h = nn.gelu(jnp.einsum("ecd,edh->ech", xin, w1.astype(x.dtype))
                    + b1[:, None].astype(x.dtype))
        out = jnp.einsum("ech,ehd->ecd", h, w2.astype(x.dtype)) \
            + b2[:, None].astype(x.dtype)
        y = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), out)

        # load balance (Switch form, rank-0 assignments): E * sum_e f_e * P_e;
        # 1.0 at perfect balance.  Stored in a fixed-shape mutable variable
        # (not sow: sow appends and would change the pytree structure across
        # scanned steps).
        frac = onehots[:, 0].mean(0)
        prob = gates.mean(0)
        aux = self.variable("losses", "load_balance", lambda: jnp.zeros(()))
        if self.is_mutable_collection("losses"):
            aux.value = jnp.asarray(self.aux_weight * e * jnp.sum(frac * prob),
                                    jnp.float32)
        return y.reshape(b, t, d)


class MoEEncoderBlock(nn.Module):
    dim: int
    heads: int
    num_experts: int
    mlp_ratio: int = 4
    top_k: int = 1
    capacity_factor: float = 1.25
    aux_weight: float = 1e-2
    seq_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        h = nn.LayerNorm()(x)
        h = _SelfAttention(self.dim, self.heads, self.seq_axis)(h, training)
        x = x + h
        h = nn.LayerNorm()(x)
        h = MoEFeedForward(self.dim, self.num_experts, self.mlp_ratio,
                           self.top_k, self.capacity_factor,
                           self.aux_weight)(h, training)
        return x + h


class MoETransformerClassifier(nn.Module):
    """Token classifier with MoE encoder blocks ([batch, seq] int32 in)."""

    vocab_size: int
    num_classes: int = 2
    dim: int = 64
    heads: int = 2
    num_layers: int = 2
    num_experts: int = 4
    mlp_ratio: int = 4
    top_k: int = 1
    capacity_factor: float = 1.25
    aux_weight: float = 1e-2
    max_len: int = 2048

    @nn.compact
    def __call__(self, tokens, training: bool = False):
        tokens = tokens.astype(jnp.int32)
        positions = jnp.arange(tokens.shape[1])
        x = nn.Embed(self.vocab_size, self.dim, name="tok_embed")(tokens)
        x = x + nn.Embed(self.max_len, self.dim, name="pos_embed")(positions)[None]
        for i in range(self.num_layers):
            x = MoEEncoderBlock(
                self.dim, self.heads, self.num_experts, self.mlp_ratio,
                self.top_k, self.capacity_factor, self.aux_weight,
                name=f"block_{i}",
            )(x, training)
        x = nn.LayerNorm()(x)
        token_logits = nn.Dense(self.num_classes, name="head")(x)
        return token_logits.sum(axis=1) / tokens.shape[1]
