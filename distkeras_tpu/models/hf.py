"""HuggingFace Flax model adapter — train `transformers` checkpoints through
any trainer and parallelism axis.

The reference's model universe was "whatever Keras builds"
(``distkeras/utils.py :: serialize_keras_model`` ships arbitrary user
models); the modern analogue of that openness is the HuggingFace hub.  A
``transformers`` Flax model (``FlaxGPT2LMHeadModel``,
``Flax*ForSequenceClassification``, ...) is already a pure-functional
``module.apply`` underneath, so adapting one costs nothing at runtime: the
adapter forwards to the model's ``__call__`` with ``params`` threaded
explicitly, which jits, differentiates, and shards exactly like the
in-tree zoo.  Pretrained weights ride along as the initial center
variable — fine-tuning IS the training path.

No ``transformers`` import happens here; the adapter only touches the
instance the user already constructed, so the dependency stays optional.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax

from distkeras_tpu.models.adapter import ModelAdapter

__all__ = ["HuggingFaceModel"]

#: class-name fragments that mark per-token (causal/masked LM) heads —
#: their labels shard over the sequence axis with the tokens
_LM_HEAD_MARKERS = ("LMHeadModel", "ForCausalLM", "ForMaskedLM")


@dataclasses.dataclass
class HuggingFaceModel(ModelAdapter):
    """Adapter over a ``transformers`` **Flax** model instance.

    ``per_token_labels`` defaults from the head type: LM heads
    (``*LMHeadModel`` / ``*ForCausalLM`` / ``*ForMaskedLM``) train against
    per-token targets (use ``loss="token_crossentropy"``), classification
    heads against per-sequence ones.  Pass it explicitly to override.
    """

    model: Any
    per_token_labels: Any = None
    outputs_logits: bool = True

    def __post_init__(self):
        name = type(self.model).__name__
        if self.per_token_labels is None:
            self.per_token_labels = any(m in name for m in _LM_HEAD_MARKERS)
        self.per_token_labels = bool(self.per_token_labels)
        if not hasattr(self.model, "params") or not callable(self.model):
            raise TypeError(
                f"{name} does not look like a transformers Flax model "
                "(needs .params and __call__(input_ids, params=...)); "
                "PyTorch transformers models cannot run on the XLA path"
            )

    def init(self, rng, sample_input):
        """Adopt the model's own parameters (random per its constructor
        seed, or pretrained via ``from_pretrained``) — fine-tuning keeps
        the checkpoint; ``rng`` is unused because HF Flax models own their
        initialisation."""
        del rng, sample_input
        return jax.tree.map(lambda x: x, self.model.params), {}

    def apply(self, params, state, inputs, training=False, rng=None):
        kwargs = {"params": params, "train": bool(training)}
        if rng is not None:
            kwargs["dropout_rng"] = rng
        out = self.model(inputs, **kwargs)
        # configs carried over from torch codebases often set
        # return_dict=False, where __call__ returns a (logits, ...) tuple
        logits = out.logits if hasattr(out, "logits") else out[0]
        return logits, state
