"""Keras-3 (JAX backend) adapter — reference-API parity for user models.

The reference's whole API takes compiled Keras models
(``distkeras/trainers.py :: Trainer.__init__(keras_model, ...)``).  Keras 3
runs natively on JAX and exposes ``model.stateless_call`` — a pure function
over explicit trainable/non-trainable variable lists — which is exactly the
:class:`~distkeras_tpu.models.adapter.ModelAdapter` contract, so Keras models
train under ``jit``/``shard_map`` on TPU with zero translation.
"""

from __future__ import annotations

import os
from typing import Any, Tuple

import numpy as np

os.environ.setdefault("KERAS_BACKEND", "jax")

from distkeras_tpu.models.adapter import ModelAdapter

__all__ = ["KerasModel", "assign_keras_weights"]


class KerasModel(ModelAdapter):
    """Wrap a Keras 3 model as a pure functional adapter via ``stateless_call``."""

    # Keras models conventionally end in softmax/sigmoid activations.
    outputs_logits = False

    def __init__(self, model):
        import keras

        if keras.backend.backend() != "jax":
            raise RuntimeError(
                "distkeras_tpu requires the Keras JAX backend; set KERAS_BACKEND=jax "
                "before importing keras"
            )
        self.model = model

    def init(self, rng, sample_input) -> Tuple[Any, Any]:
        if not self.model.built:
            self.model.build(np.asarray(sample_input).shape)
        params = [v.value for v in self.model.trainable_variables]
        state = {"ntv": [v.value for v in self.model.non_trainable_variables]}
        return params, state

    def apply(self, params, state, inputs, training=False, rng=None):
        outputs, ntv = self.model.stateless_call(
            params, state["ntv"], inputs, training=training
        )
        return outputs, {"ntv": ntv}

    def assign(self, params, state=None):
        """Write trained values back onto the Keras model (what ``train`` returns)."""
        assign_keras_weights(self.model, params, (state or {}).get("ntv"))
        return self.model


def assign_keras_weights(model, trainable_values, non_trainable_values=None):
    for var, val in zip(model.trainable_variables, trainable_values):
        var.assign(np.asarray(val))
    if non_trainable_values is not None:
        for var, val in zip(model.non_trainable_variables, non_trainable_values):
            var.assign(np.asarray(val))
    return model
