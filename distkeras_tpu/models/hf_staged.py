"""Pretrained GPT-2 -> :class:`StagedLM`: HuggingFace checkpoints on the
pipeline mesh.

:class:`~distkeras_tpu.models.hf.HuggingFaceModel` already trains any
``transformers`` Flax model through the data/tensor/sequence axes, but an HF
module is a black box to the PIPELINE engine, which needs the staged
``{"embed", "blocks", "head"}`` layout with homogeneous blocks stacked
``[num_stages, blocks_per_stage, ...]``.  GPT-2's architecture is exactly
our :class:`TransformerEncoderBlock` — pre-LN, tanh-GELU 4x MLP, learned
positions, final LayerNorm, causal attention at ``1/sqrt(head_dim)`` — so a
checkpoint converts by pure weight re-layout, no re-expression of the math:

  * ``wte``/``wpe``            -> ``embed.tok_embed/pos_embed``
  * per block: ``ln_1``        -> ``LayerNorm_0``
  *   ``attn.c_attn`` [3d, d]  -> ``_SelfAttention_0.qkv``  [d, 3, h, hd]
  *   ``attn.c_proj`` [d, d]   -> ``_SelfAttention_0.proj`` [h, hd, d]
  *   ``ln_2``                 -> ``LayerNorm_1``
  *   ``mlp.c_fc/c_proj``      -> ``Dense_0`` / ``Dense_1``
  * ``ln_f``                   -> ``head.LayerNorm_0``
  * ``wte^T`` (tied) or the checkpoint's own ``lm_head`` (untied —
    ``cfg.tie_word_embeddings=False``) -> ``head.out``; either way the
    staged layout is untied from here on: fine-tuning trains embed and
    head independently, like every reference-style Keras model

HF's ``FlaxConv1D`` stores kernels ``(out, in)`` and transposes at use
(``modeling_flax_gpt2.FlaxConv1D``), hence the ``.T`` on every kernel.
Equality is asserted, not assumed: ``tests/test_hf_staged.py`` checks
converted logits against the HF model's own forward pass.

The returned adapter's ``init`` adopts the converted weights (the
:class:`HuggingFaceModel` convention), so the checkpoint becomes the
initial center variable for any trainer — including
``pipeline_stages=S, fsdp=True``, where the [vocab, dim] embedding and
head this conversion produces are exactly the leaves the stage-sharding
exists for.  ``greedy_generate`` / ``greedy_generate_staged_pipelined``
decode it unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import numpy as np

from distkeras_tpu.models.staged import StagedLM, stack_block_params

__all__ = ["PretrainedStagedLM", "gpt2_to_staged"]

#: tanh-approximation GELU names (== flax.linen.gelu(approximate=True))
_TANH_GELUS = ("gelu_new", "gelu_pytorch_tanh")


@dataclasses.dataclass
class PretrainedStagedLM(StagedLM):
    """A :class:`StagedLM` whose ``init`` adopts converted pretrained
    weights instead of sampling fresh ones (rng is unused, like
    :class:`HuggingFaceModel.init`)."""

    def init(self, rng, sample_input):
        del rng, sample_input
        if getattr(self, "_pretrained", None) is None:
            raise RuntimeError("construct via gpt2_to_staged()")
        # Host (numpy) leaves go out untouched: the engines' jitted state
        # builds place them under their target shardings in one transfer.
        # An eager jnp.asarray here would first materialise the full
        # checkpoint replicated on one device — the exact spike the
        # fsdp/stage shardings exist to avoid (engine.state_from_center
        # makes the same choice).
        return jax.tree.map(lambda x: x, self._pretrained), {}


def _require(cond, msg):
    if not cond:
        raise ValueError(msg)


def gpt2_to_staged(model, num_stages: int,
                   blocks_per_stage: Optional[int] = None,
                   seq_axis: Optional[str] = None) -> PretrainedStagedLM:
    """Convert a ``FlaxGPT2LMHeadModel`` (pretrained or fresh) into a
    pipeline-ready :class:`PretrainedStagedLM`.

    ``seq_axis`` builds the ring-attention variant for pp x sp fine-tuning
    (``pipeline_stages=S, seq_shards=k``) — set it here rather than via
    ``dataclasses.replace`` afterwards, which would drop the attached
    ``_pretrained`` checkpoint (replace builds a fresh instance)."""
    cfg = model.config
    _require(
        type(model).__name__ == "FlaxGPT2LMHeadModel",
        f"gpt2_to_staged converts FlaxGPT2LMHeadModel, got {type(model).__name__}",
    )
    _require(
        cfg.activation_function in _TANH_GELUS,
        f"block uses tanh-GELU; checkpoint has {cfg.activation_function!r}",
    )
    _require(
        cfg.n_inner is None or cfg.n_inner == 4 * cfg.n_embd,
        f"block MLP is 4x wide; checkpoint has n_inner={cfg.n_inner}",
    )
    _require(
        getattr(cfg, "scale_attn_weights", True)
        and not getattr(cfg, "scale_attn_by_inverse_layer_idx", False)
        and not getattr(cfg, "reorder_and_upcast_attn", False),
        "checkpoint uses non-standard attention scaling",
    )
    n_layer = int(cfg.n_layer)
    if blocks_per_stage is None:
        _require(
            n_layer % num_stages == 0,
            f"n_layer={n_layer} does not divide into {num_stages} stages",
        )
        blocks_per_stage = n_layer // num_stages
    _require(
        num_stages * blocks_per_stage == n_layer,
        f"{num_stages} x {blocks_per_stage} != n_layer={n_layer}",
    )

    dim, heads = int(cfg.n_embd), int(cfg.n_head)
    hd = dim // heads
    t = model.params["transformer"]
    f32 = lambda x: np.asarray(x, np.float32)

    def block_params(i):
        blk = t["h"][str(i)]
        return {
            "LayerNorm_0": {k: f32(v) for k, v in blk["ln_1"].items()},
            "_SelfAttention_0": {
                "qkv": {
                    "kernel": f32(blk["attn"]["c_attn"]["kernel"]).T.reshape(
                        dim, 3, heads, hd),
                    "bias": f32(blk["attn"]["c_attn"]["bias"]).reshape(
                        3, heads, hd),
                },
                "proj": {
                    "kernel": f32(blk["attn"]["c_proj"]["kernel"]).T.reshape(
                        heads, hd, dim),
                    "bias": f32(blk["attn"]["c_proj"]["bias"]),
                },
            },
            "LayerNorm_1": {k: f32(v) for k, v in blk["ln_2"].items()},
            "Dense_0": {"kernel": f32(blk["mlp"]["c_fc"]["kernel"]).T,
                        "bias": f32(blk["mlp"]["c_fc"]["bias"])},
            "Dense_1": {"kernel": f32(blk["mlp"]["c_proj"]["kernel"]).T,
                        "bias": f32(blk["mlp"]["c_proj"]["bias"])},
        }

    per_block = [block_params(i) for i in range(n_layer)]
    # xp=np keeps the converted checkpoint as host leaves (the engines'
    # jitted builds place shards directly)
    stacked = stack_block_params(per_block, num_stages, blocks_per_stage, xp=np)
    wte = f32(t["wte"]["embedding"])
    vocab = wte.shape[0]
    if getattr(cfg, "tie_word_embeddings", True):
        head_kernel = wte.T.copy()
    else:
        # untied checkpoints carry their own head (HF's FlaxGPT2LMHeadModule
        # uses params["lm_head"] instead of wte^T); nn.Dense kernels are
        # already (in, out) — no transpose
        head_kernel = f32(model.params["lm_head"]["kernel"])
        _require(
            head_kernel.shape == (dim, vocab),
            f"untied lm_head kernel has shape {head_kernel.shape}, "
            f"expected {(dim, vocab)}",
        )
    params = {
        "embed": {"tok_embed": {"embedding": wte},
                  "pos_embed": {"embedding": f32(t["wpe"]["embedding"])}},
        "blocks": stacked,
        "head": {"LayerNorm_0": {k: f32(v) for k, v in t["ln_f"].items()},
                 "out": {"kernel": head_kernel,
                         "bias": np.zeros((vocab,), np.float32)}},
    }

    staged = PretrainedStagedLM(
        vocab_size=vocab, dim=dim, heads=heads,
        num_stages=num_stages, blocks_per_stage=blocks_per_stage,
        max_len=int(cfg.n_positions), ln_eps=float(cfg.layer_norm_epsilon),
        seq_axis=seq_axis,
    )
    staged._pretrained = params
    return staged
