"""Model adapters: a single functional interface over Keras-3 and Flax models.

The reference ships Keras models into Spark executors as JSON+weights blobs
and calls ``model.train_on_batch`` inside the worker loop
(``distkeras/workers.py :: Worker.prepare_model / train``).  On TPU the model
must instead be a *pure function* ``(params, state, inputs) -> outputs`` so it
can be jitted, differentiated, and sharded.  ``ModelAdapter`` is that
interface; :class:`FlaxModel` wraps ``flax.linen`` modules from the in-tree
zoo and :mod:`distkeras_tpu.models.keras_adapter` wraps user Keras-3 models
(the reference's input type) via ``stateless_call``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["ModelAdapter", "FlaxModel", "TrainedModel", "as_adapter"]


class ModelAdapter:
    """Functional model interface.

    ``params``  — trainable parameter pytree (what the optimizer updates and
                  what the parameter-server center variable holds).
    ``state``   — non-trainable pytree (BatchNorm statistics etc.); may be an
                  empty dict.
    """

    #: whether ``apply`` outputs are logits (True for the in-tree zoo) or
    #: post-activation probabilities (Keras models with softmax heads).
    outputs_logits: bool = True

    #: model emits per-token outputs trained against per-token labels
    #: (language models).  The engines shard the label array exactly like
    #: the input array — under sequence parallelism each shard keeps its
    #: block's targets.
    per_token_labels: bool = False

    def init(self, rng: jax.Array, sample_input: np.ndarray) -> Tuple[Any, Any]:
        raise NotImplementedError

    def apply(
        self,
        params: Any,
        state: Any,
        inputs: jnp.ndarray,
        training: bool = False,
        rng: Optional[jax.Array] = None,
    ) -> Tuple[jnp.ndarray, Any]:
        raise NotImplementedError

    def aux_loss(self, state: Any):
        """Auxiliary training loss carried in the post-``apply`` state (e.g. a
        MoE router's load-balance term).  The engines add this to the
        objective each step; 0 for models without one."""
        del state
        return 0.0


@dataclasses.dataclass
class FlaxModel(ModelAdapter):
    """Adapter over a ``flax.linen.Module`` (used by the in-tree model zoo)."""

    module: Any
    outputs_logits: bool = True

    @property
    def per_token_labels(self) -> bool:
        """Inherited from the wrapped module (TransformerLM sets it)."""
        return bool(getattr(self.module, "per_token_labels", False))

    def init(self, rng, sample_input):
        variables = self.module.init(rng, jnp.asarray(sample_input), training=False)
        params = variables.get("params", {})
        state = {k: v for k, v in variables.items() if k != "params"}
        return params, state

    def apply(self, params, state, inputs, training=False, rng=None):
        variables = {"params": params, **state}
        rngs = {"dropout": rng} if rng is not None else {}
        if training and state:
            out, updates = self.module.apply(
                variables, inputs, training=True, rngs=rngs, mutable=list(state.keys())
            )
            return out, dict(updates)
        out = self.module.apply(variables, inputs, training=training, rngs=rngs)
        return out, state

    def aux_loss(self, state):
        """Sum of the mutable ``losses`` collection (MoE load balance etc.)."""
        from collections.abc import Mapping

        leaves = jax.tree.leaves(state.get("losses", {})) if isinstance(state, Mapping) else []
        if not leaves:
            return 0.0
        return sum(jnp.sum(l) for l in leaves)


@dataclasses.dataclass
class FunctionalModel(ModelAdapter):
    """Adapter over plain ``(init_fn, apply_fn)`` pairs (haiku-style)."""

    init_fn: Callable
    apply_fn: Callable
    outputs_logits: bool = True

    def init(self, rng, sample_input):
        params = self.init_fn(rng, jnp.asarray(sample_input))
        return params, {}

    def apply(self, params, state, inputs, training=False, rng=None):
        return self.apply_fn(params, inputs), state


class TrainedModel:
    """What trainers return on the pure-JAX path: params + a predict method.

    (On the Keras path trainers return the original Keras model with trained
    weights assigned, matching the reference's ``Trainer.train`` contract.)
    """

    def __init__(self, adapter: ModelAdapter, params, state, history=None):
        self.adapter = adapter
        self.params = params
        self.state = state
        self.history = history or {}
        self._jit_apply = jax.jit(
            lambda p, s, x: adapter.apply(p, s, x, training=False)[0]
        )

    def predict(self, inputs, batch_size: int = 1024) -> np.ndarray:
        inputs = np.asarray(inputs)
        outs = []
        for i in range(0, len(inputs), batch_size):
            outs.append(np.asarray(self._jit_apply(self.params, self.state, inputs[i : i + batch_size])))
        out = np.concatenate(outs) if outs else np.empty((0,))
        if self.adapter.outputs_logits:
            out = np.asarray(jax.nn.softmax(out, axis=-1)) if out.ndim > 1 and out.shape[-1] > 1 else out
        return out

    def __call__(self, inputs):
        return self._jit_apply(self.params, self.state, jnp.asarray(inputs))


def as_adapter(model) -> ModelAdapter:
    """Coerce user input (Keras model / flax module / adapter) to an adapter."""
    if isinstance(model, ModelAdapter):
        return model
    # flax linen module?
    try:
        import flax.linen as nn

        if isinstance(model, nn.Module):
            return FlaxModel(model)
    except ImportError:  # pragma: no cover
        pass
    # Keras model? (lazy import: keras is heavy)
    if type(model).__module__.split(".")[0] in ("keras", "tf_keras", "tensorflow"):
        from distkeras_tpu.models.keras_adapter import KerasModel

        return KerasModel(model)
    # transformers Flax model? (no transformers import needed)
    if type(model).__module__.split(".")[0] == "transformers":
        from distkeras_tpu.models.hf import HuggingFaceModel

        return HuggingFaceModel(model)
    raise TypeError(
        f"cannot adapt {type(model)!r}: pass a Keras 3 model, flax.linen.Module, "
        "or distkeras_tpu ModelAdapter"
    )
