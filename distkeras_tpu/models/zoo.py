"""In-tree model zoo (flax.linen) covering the reference's benchmark models.

The reference defines its models ad-hoc in ``examples/mnist.py`` /
``examples/mnist.ipynb`` (Keras Sequential MLP and CNN) and the README
experiments (CIFAR-10 CNN / ResNet-20, IMDB text-CNN per ``BASELINE.json``).
Here they are first-class flax modules, written TPU-first: channel counts
padded to MXU-friendly multiples, logits outputs (loss fuses the softmax),
NHWC conv layouts, and no data-dependent Python control flow.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from distkeras_tpu.ops.pooling import max_pool

__all__ = ["MLP", "MNISTCNN", "CIFARCNN", "ResNet20", "TextCNN"]


class MLP(nn.Module):
    """The reference MNIST MLP (examples/mnist.py: Dense stack + softmax head),
    emitted as logits."""

    features: Sequence[int] = (500, 250, 125)
    num_classes: int = 10
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = x.reshape((x.shape[0], -1))
        for f in self.features:
            x = nn.relu(nn.Dense(f, dtype=jnp.float32)(x))
            if self.dropout > 0:
                x = nn.Dropout(self.dropout, deterministic=not training)(x)
        return nn.Dense(self.num_classes)(x)


class MNISTCNN(nn.Module):
    """Small convnet for 28x28x1 inputs (reference: examples/mnist.py CNN)."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x, training: bool = False):
        if x.ndim == 2:  # flat 784 vectors from the DataFrame path
            x = x.reshape((x.shape[0], 28, 28, 1))
        x = nn.relu(nn.Conv(32, (3, 3))(x))
        x = max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (3, 3))(x))
        x = max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        return nn.Dense(self.num_classes)(x)


class CIFARCNN(nn.Module):
    """CIFAR-10 CNN — the headline benchmark model (BASELINE.json config 3)."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x, training: bool = False):
        if x.ndim == 2:
            x = x.reshape((x.shape[0], 32, 32, 3))
        for filters in (64, 128):
            x = nn.relu(nn.Conv(filters, (3, 3))(x))
            x = nn.relu(nn.Conv(filters, (3, 3))(x))
            x = max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(256)(x))
        return nn.Dense(self.num_classes)(x)


class _ResBlock(nn.Module):
    filters: int
    strides: int = 1

    @nn.compact
    def __call__(self, x, training: bool = False):
        norm = lambda: nn.BatchNorm(use_running_average=not training, momentum=0.9)
        y = nn.Conv(self.filters, (3, 3), strides=(self.strides, self.strides), use_bias=False)(x)
        y = nn.relu(norm()(y))
        y = nn.Conv(self.filters, (3, 3), use_bias=False)(y)
        y = norm()(y)
        if x.shape[-1] != self.filters or self.strides != 1:
            x = nn.Conv(self.filters, (1, 1), strides=(self.strides, self.strides), use_bias=False)(x)
        return nn.relu(x + y)


class ResNet20(nn.Module):
    """ResNet-20 (He et al.) for CIFAR-10 — BASELINE.json config 4 (ADAG).

    Carries BatchNorm running statistics as non-trainable model state, the
    hard case the reference never had to solve (Keras hid it); the engine
    synchronises these across workers at commit boundaries.
    """

    num_classes: int = 10

    @nn.compact
    def __call__(self, x, training: bool = False):
        if x.ndim == 2:
            x = x.reshape((x.shape[0], 32, 32, 3))
        x = nn.Conv(16, (3, 3), use_bias=False)(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not training, momentum=0.9)(x))
        for filters, strides in ((16, 1), (16, 1), (16, 1), (32, 2), (32, 1), (32, 1), (64, 2), (64, 1), (64, 1)):
            x = _ResBlock(filters, strides)(x, training=training)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


class TextCNN(nn.Module):
    """IMDB text-CNN (Kim 2014 style) — BASELINE.json config 5 (DynSGD).

    Input: int32 token ids [batch, seq_len].
    """

    vocab_size: int = 20000
    embed_dim: int = 128
    kernel_sizes: Sequence[int] = (3, 4, 5)
    filters: int = 128
    num_classes: int = 2
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = nn.Embed(self.vocab_size, self.embed_dim)(x.astype(jnp.int32))
        pooled = []
        for k in self.kernel_sizes:
            h = nn.relu(nn.Conv(self.filters, (k,))(x))  # [b, seq, filters]
            pooled.append(jnp.max(h, axis=1))
        x = jnp.concatenate(pooled, axis=-1)
        if self.dropout > 0:
            x = nn.Dropout(self.dropout, deterministic=not training)(x)
        return nn.Dense(self.num_classes)(x)
