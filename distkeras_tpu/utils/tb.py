"""Per-epoch scalar logging (SURVEY.md §5.5 rebuild note).

The reference's observability was a stdout print + the ``num_updates``
counter; here trainers accept ``tensorboard_dir`` and emit per-epoch
loss/metric scalars.  TensorBoard event files are written when a writer is
importable (``torch.utils.tensorboard``, then ``tf.summary``); otherwise the
scalars land in ``<dir>/scalars.jsonl`` — same data, greppable, no heavy
dependency on the training path.
"""

from __future__ import annotations

import json
import os

__all__ = ["ScalarLogger"]


class ScalarLogger:
    """Append-only scalar sink: ``log(step, loss=..., accuracy=...)``."""

    def __init__(self, logdir: str):
        self.logdir = os.path.abspath(logdir)
        os.makedirs(self.logdir, exist_ok=True)
        self._writer = None
        self._write = self._write_jsonl
        try:
            from torch.utils.tensorboard import SummaryWriter

            self._writer = SummaryWriter(self.logdir)
            self._write = self._write_torch
        except Exception:
            if os.environ.get("DISTKERAS_TB_TF"):
                # Opt-in only: initializing TensorFlow inside the live
                # training process can preallocate accelerator memory /
                # contend for libtpu — too big a side effect for a scalar
                # logger to take on by default.
                import tensorflow as tf

                self._writer = tf.summary.create_file_writer(self.logdir)
                self._write = self._write_tf
            else:
                self._jsonl = open(os.path.join(self.logdir, "scalars.jsonl"), "a")

    def _write_torch(self, step, scalars):
        for name, value in scalars.items():
            self._writer.add_scalar(name, value, step)
        self._writer.flush()

    def _write_tf(self, step, scalars):
        import tensorflow as tf

        with self._writer.as_default(step=step):
            for name, value in scalars.items():
                tf.summary.scalar(name, value)
        self._writer.flush()

    def _write_jsonl(self, step, scalars):
        self._jsonl.write(json.dumps({"step": step, **scalars}) + "\n")
        self._jsonl.flush()

    def log(self, step: int, **scalars: float) -> None:
        self._write(int(step), {k: float(v) for k, v in scalars.items()})

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
        elif hasattr(self, "_jsonl"):
            self._jsonl.close()
