"""Per-epoch scalar logging (SURVEY.md §5.5 rebuild note).

The reference's observability was a stdout print + the ``num_updates``
counter; here trainers accept ``tensorboard_dir`` and emit per-epoch
loss/metric scalars.  TensorBoard event files are written when a writer is
importable (``torch.utils.tensorboard``, then ``tf.summary``); otherwise the
scalars land in ``<dir>/scalars.jsonl`` — same data, greppable, no heavy
dependency on the training path.
"""

from __future__ import annotations

import json
import os

__all__ = ["ScalarLogger"]


class ScalarLogger:
    """Append-only scalar sink: ``log(step, loss=..., accuracy=...)``.

    Usable as a context manager (``with ScalarLogger(d) as log:``) so the
    underlying writer/file handle is released even when training raises.
    ``close()`` is idempotent and safe when nothing was ever written: the
    JSONL file opens lazily on the first ``log`` call.
    """

    def __init__(self, logdir: str):
        self.logdir = os.path.abspath(logdir)
        os.makedirs(self.logdir, exist_ok=True)
        self._writer = None
        self._jsonl = None
        self._write = self._write_jsonl
        if self._try_torch():
            self._write = self._write_torch
        elif os.environ.get("DISTKERAS_TB_TF"):
            # Opt-in only: initializing TensorFlow inside the live training
            # process can preallocate accelerator memory / contend for
            # libtpu — too big a side effect for a scalar logger to take on
            # by default.  If TF turns out to be unimportable anyway, fall
            # back to JSONL instead of failing the whole training run over
            # a logging preference.
            if self._try_tf():
                self._write = self._write_tf
            else:
                import warnings

                warnings.warn(
                    "DISTKERAS_TB_TF is set but tf.summary is not importable;"
                    " falling back to JSONL scalars in " + self.logdir,
                    RuntimeWarning,
                    stacklevel=2,
                )

    def _try_torch(self) -> bool:
        try:
            from torch.utils.tensorboard import SummaryWriter

            self._writer = SummaryWriter(self.logdir)
            return True
        except Exception:
            return False

    def _try_tf(self) -> bool:
        try:
            import tensorflow as tf

            self._writer = tf.summary.create_file_writer(self.logdir)
            return True
        except Exception:
            return False

    def _write_torch(self, step, scalars):
        for name, value in scalars.items():
            self._writer.add_scalar(name, value, step)
        self._writer.flush()

    def _write_tf(self, step, scalars):
        import tensorflow as tf

        with self._writer.as_default(step=step):
            for name, value in scalars.items():
                tf.summary.scalar(name, value)
        self._writer.flush()

    def _write_jsonl(self, step, scalars):
        if self._jsonl is None:
            self._jsonl = open(os.path.join(self.logdir, "scalars.jsonl"), "a")
        self._jsonl.write(json.dumps({"step": step, **scalars}) + "\n")
        self._jsonl.flush()

    def log(self, step: int, **scalars: float) -> None:
        self._write(int(step), {k: float(v) for k, v in scalars.items()})

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None

    def __enter__(self) -> "ScalarLogger":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        return False
