"""JAX version compatibility shims.

The codebase targets current JAX, where ``shard_map`` is a top-level API
taking ``check_vma`` and ``axis_names``.  On older installs (< 0.5) the same
machinery lives in ``jax.experimental.shard_map`` with the previous spelling
— ``check_rep``, and ``auto`` (the *complement* of the manual axis set).
Every engine routes through this wrapper so the rest of the code is written
against one surface.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "axis_size"]


def shard_map(f, mesh, in_specs, out_specs, check_vma=True, axis_names=None):
    if hasattr(jax, "shard_map"):
        kwargs = {"check_vma": check_vma}
        if axis_names:
            kwargs["axis_names"] = axis_names
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    auto = (
        frozenset(mesh.axis_names) - frozenset(axis_names)
        if axis_names
        else frozenset()
    )
    if auto:
        # Partial-manual (some axes left to the partitioner) aborts the whole
        # process on old XLA (hlo_sharding_util CHECK sharding.IsManualSubgroup
        # on jaxlib 0.4.x) — the experimental ``auto=`` never hardened.  Fail
        # as a catchable error instead of a SIGABRT that takes pytest with it.
        raise NotImplementedError(
            "partial-manual shard_map (axis_names=%r over mesh axes %r) "
            "requires jax >= 0.5; this install's experimental 'auto=' path "
            "crashes XLA" % (tuple(axis_names), tuple(mesh.axis_names))
        )
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma, auto=auto,
    )


def axis_size(axis_name):
    """Static size of a named mapped axis (``lax.axis_size`` on current JAX;
    ``psum(1)`` over the axis on older installs, which XLA folds to the same
    constant)."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)
