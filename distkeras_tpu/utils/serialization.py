"""Model / weight serialization with reference-API parity.

Mirrors ``distkeras/utils.py :: serialize_keras_model`` /
``deserialize_keras_model`` (architecture JSON + weight arrays in a dict), but
for Keras 3 models running on the JAX backend, plus numpy-native pytree
(de)serialization for the pure-JAX model path.  Nothing here uses pickle for
model weights — weights travel as raw numpy arrays inside an ``.npz``-style
dict, which is both safer and faster than the reference's pickled payloads.
"""

from __future__ import annotations

import io
import json
from typing import Any, Dict

import numpy as np

__all__ = [
    "serialize_keras_model",
    "deserialize_keras_model",
    "uniform_weights",
    "params_to_bytes",
    "params_from_bytes",
]


def serialize_keras_model(model) -> Dict[str, Any]:
    """Architecture-JSON + weights dict, like the reference's utils.

    Reference parity: ``distkeras/utils.py :: serialize_keras_model`` returns
    ``{'model': model.to_json(), 'weights': model.get_weights()}``.
    """
    return {"model": model.to_json(), "weights": [np.asarray(w) for w in model.get_weights()]}


def deserialize_keras_model(blob: Dict[str, Any]):
    """Rebuild a Keras model from :func:`serialize_keras_model` output."""
    import keras  # lazy: keras is optional for the pure-JAX path

    model = keras.models.model_from_json(blob["model"])
    model.set_weights(blob["weights"])
    return model


def uniform_weights(model, bounds=(-0.5, 0.5), seed: int | None = None):
    """Re-initialise all model weights uniformly in ``bounds`` (reference parity:
    ``distkeras/utils.py :: uniform_weights``)."""
    rng = np.random.default_rng(seed)
    lo, hi = bounds
    model.set_weights([rng.uniform(lo, hi, w.shape).astype(w.dtype) for w in model.get_weights()])
    return model


# -- pytree <-> bytes (for checkpointing-lite and the job-deployment path) --

def params_to_bytes(params) -> bytes:
    """Flatten a pytree of arrays to a self-describing npz byte blob."""
    import jax

    leaves, treedef = jax.tree.flatten(params)
    buf = io.BytesIO()
    np.savez(
        buf,
        __treedef__=np.frombuffer(str(treedef).encode(), dtype=np.uint8),
        **{f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)},
    )
    return buf.getvalue()


def params_from_bytes(blob: bytes, like) -> Any:
    """Rebuild a pytree from :func:`params_to_bytes`, using ``like``'s treedef."""
    import jax

    data = np.load(io.BytesIO(blob), allow_pickle=False)
    leaves = [data[f"leaf_{i}"] for i in range(len(data.files) - 1)]
    _, treedef = jax.tree.flatten(like)
    return jax.tree.unflatten(treedef, leaves)


def history_to_json(history) -> str:
    return json.dumps(history, default=float)
