"""Utilities: reference-parity helpers (``distkeras/utils.py``) + pytree math.

The reference's ``utils.py`` carries model (de)serialization, DataFrame row
helpers, shuffling, and dense-vector conversion.  The same surface lives here,
re-expressed for the columnar :mod:`distkeras_tpu.frame` DataFrame and JAX
pytrees.
"""

from __future__ import annotations

import numpy as np

from distkeras_tpu.frame import DataFrame, Row
from distkeras_tpu.utils.pytree import (
    tree_add,
    tree_add_scaled,
    tree_cast,
    tree_global_norm,
    tree_ones_like,
    tree_scale,
    tree_size,
    tree_sub,
    tree_where,
    tree_zeros_like,
)
from distkeras_tpu.utils.serialization import (
    deserialize_keras_model,
    params_from_bytes,
    params_to_bytes,
    serialize_keras_model,
    uniform_weights,
)

__all__ = [
    "shuffle",
    "new_dataframe_row",
    "to_dense_vector",
    "serialize_keras_model",
    "deserialize_keras_model",
    "uniform_weights",
    "params_to_bytes",
    "params_from_bytes",
    "tree_add",
    "tree_sub",
    "tree_scale",
    "tree_add_scaled",
    "tree_zeros_like",
    "tree_ones_like",
    "tree_global_norm",
    "tree_size",
    "tree_cast",
    "tree_where",
]


def shuffle(df: DataFrame, seed: int | None = None) -> DataFrame:
    """Random row permutation (reference parity: ``distkeras/utils.py :: shuffle``)."""
    return df.shuffle(seed)


def new_dataframe_row(row: Row, name: str, value) -> Row:
    """Copy a row with one extra column (reference parity:
    ``distkeras/utils.py :: new_dataframe_row``)."""
    out = Row(row)
    out[name] = value
    return out


def to_dense_vector(value, size: int) -> np.ndarray:
    """Class index -> one-hot dense vector (reference parity:
    ``distkeras/utils.py`` dense-vector conversion used by the MNIST example).

    Accepts a scalar class index (one-hot encode) or an already-dense vector
    (pass through, padded/truncated to ``size``).
    """
    arr = np.asarray(value)
    if arr.ndim == 0:
        out = np.zeros(size, dtype=np.float32)
        out[int(arr)] = 1.0
        return out
    out = np.zeros(size, dtype=np.float32)
    n = min(size, arr.shape[0])
    out[:n] = arr[:n]
    return out
