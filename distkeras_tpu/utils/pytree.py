"""Pytree arithmetic helpers used by the update rules and engines.

The reference operates on lists of numpy weight arrays (``distkeras/utils.py``
and the residual arithmetic inside ``distkeras/workers.py``).  Here model
parameters are JAX pytrees, so the same arithmetic is expressed with
``jax.tree_util`` maps; every helper is jit-safe and works on arbitrary
nested structures.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "tree_add",
    "tree_sub",
    "tree_scale",
    "tree_add_scaled",
    "tree_zeros_like",
    "tree_ones_like",
    "tree_global_norm",
    "tree_size",
    "tree_cast",
    "tree_where",
]


def tree_add(a, b):
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a, b):
    return jax.tree.map(jnp.subtract, a, b)


def tree_scale(a, s):
    return jax.tree.map(lambda x: x * s, a)


def tree_add_scaled(a, b, s):
    """a + s * b, fused per-leaf."""
    return jax.tree.map(lambda x, y: x + s * y, a, b)


def tree_zeros_like(a):
    return jax.tree.map(jnp.zeros_like, a)


def tree_ones_like(a):
    return jax.tree.map(jnp.ones_like, a)


def tree_global_norm(a):
    leaves = jax.tree.leaves(a)
    if not leaves:
        return jnp.zeros(())
    return jnp.sqrt(sum(jnp.sum(jnp.square(x)) for x in leaves))


def tree_size(a) -> int:
    """Total number of scalar parameters."""
    return sum(x.size for x in jax.tree.leaves(a))


def tree_cast(a, dtype):
    return jax.tree.map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x, a
    )


def tree_where(pred, a, b):
    """Per-leaf select; ``pred`` is a scalar boolean (jit-safe)."""
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)
