"""Async-SGD update rules — the pure-function form of the reference's
worker/parameter-server algorithm pairs (SURVEY.md §2, §3.3)."""

from distkeras_tpu.algorithms.adag import Adag
from distkeras_tpu.algorithms.adaptive import AdaptiveBound, AdaptiveDynSGD
from distkeras_tpu.algorithms.aeasgd import Aeasgd, Eamsgd
from distkeras_tpu.algorithms.base import CommitCtx, CommitResult, UpdateRule, make_ctx
from distkeras_tpu.algorithms.downpour import Downpour
from distkeras_tpu.algorithms.dynsgd import DynSGD
from distkeras_tpu.algorithms.sequential import OneShotAverage, Sequential

__all__ = [
    "UpdateRule",
    "CommitCtx",
    "CommitResult",
    "make_ctx",
    "Downpour",
    "Adag",
    "Aeasgd",
    "Eamsgd",
    "DynSGD",
    "AdaptiveDynSGD",
    "AdaptiveBound",
    "Sequential",
    "OneShotAverage",
]
