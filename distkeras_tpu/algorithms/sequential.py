"""Sequential (no-op) and one-shot-averaging rules.

``Sequential`` backs ``SingleTrainer`` (reference:
``distkeras/workers.py :: SequentialWorker`` — plain local SGD, no PS).
``OneShotAverage`` backs ``AveragingTrainer`` (reference:
``trainers.py :: AveragingTrainer.average_models`` — train independent
replicas, average the weights once at the end); on TPU the average is a
single ``pmean`` over the worker axis.
"""

from __future__ import annotations

import dataclasses

import jax

from distkeras_tpu.algorithms.base import CommitCtx, CommitResult, UpdateRule

__all__ = ["Sequential", "OneShotAverage"]


@dataclasses.dataclass(frozen=True)
class Sequential(UpdateRule):
    """No commits: pure local training (the reference's SequentialWorker)."""

    communication_window: int = 0  # 0 => never commit mid-training
    pulls: bool = False

    def commit(self, ctx: CommitCtx, local_params, center_params, local_state, center_state):
        return CommitResult(local_params, local_params, local_state, center_state)


@dataclasses.dataclass(frozen=True)
class OneShotAverage(UpdateRule):
    """Single synchronous weight average at end of training."""

    communication_window: int = 0
    pulls: bool = True

    def commit(self, ctx: CommitCtx, local_params, center_params, local_state, center_state):
        mean = jax.tree.map(lambda x: x / ctx.num_workers, ctx.psum(local_params))
        new_center_state = {
            "num_updates": center_state["num_updates"] + self._count_commits(ctx)
        }
        return CommitResult(mean, mean, local_state, new_center_state)
