"""DOWNPOUR (Dean et al., NIPS 2012) as a windowed-delta collective rule.

Reference semantics (``distkeras/workers.py :: DOWNPOURWorker.train`` +
``parameter_servers.py :: DeltaParameterServer.handle_commit``): each worker
accumulates the weight residual over ``communication_window`` local steps,
commits it (PS does ``center += delta``), then pulls the fresh center.

TPU form: the residual is ``local − anchor`` where ``anchor`` is the center
value at this worker's last pull; the PS apply becomes
``center += psum(residual)``; the pull is a masked adopt of the new center.
"""

from __future__ import annotations

import dataclasses

from distkeras_tpu.algorithms.base import CommitCtx, CommitResult, UpdateRule
from distkeras_tpu.utils.pytree import tree_add, tree_sub, tree_where

__all__ = ["Downpour"]


@dataclasses.dataclass(frozen=True)
class Downpour(UpdateRule):
    communication_window: int = 5

    def init_local_state(self, params):
        return {"anchor": params}

    def commit(self, ctx: CommitCtx, local_params, center_params, local_state, center_state):
        residual = tree_sub(local_params, local_state["anchor"])
        summed = ctx.psum(self._masked(ctx, residual))
        new_center = tree_add(center_params, summed)
        new_local = self._pull(ctx, new_center, local_params)
        new_anchor = tree_where(ctx.mask, new_center, local_state["anchor"])
        new_center_state = {
            "num_updates": center_state["num_updates"] + self._count_commits(ctx)
        }
        return CommitResult(new_local, new_center, {"anchor": new_anchor}, new_center_state)
