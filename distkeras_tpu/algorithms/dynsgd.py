"""DynSGD — staleness-aware dynamic-learning-rate SGD (Jiang et al.,
SIGMOD 2017, as implemented by the reference).

Reference semantics (``distkeras/workers.py :: DynSGDWorker`` +
``parameter_servers.py :: DynSGDParameterServer.handle_commit``): each commit
carries the worker's update clock; the PS computes
``staleness = num_updates − worker_clock`` and applies
``center += delta / (staleness + 1)`` so stale contributions are damped.

TPU form: staleness is *modeled deterministically* — per-worker clocks are
carried in rule state, ``num_updates`` is the replicated commit counter, and
staleness is computed against the counter value *before* the current commit
batch.  Under uniform synchronous windows every staleness is 0 (DynSGD ≡
DOWNPOUR — the correct degenerate case); with per-worker commit schedules
(the staleness-simulation engine) slow-committing workers see positive
staleness exactly as they would racing a real parameter server, but
bit-for-bit reproducibly.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from distkeras_tpu.algorithms.base import CommitCtx, CommitResult, UpdateRule
from distkeras_tpu.utils.pytree import tree_add, tree_where

__all__ = ["DynSGD"]


@dataclasses.dataclass(frozen=True)
class DynSGD(UpdateRule):
    communication_window: int = 5

    def init_local_state(self, params):
        return {"anchor": params, "clock": jnp.zeros((), jnp.int32)}

    def dynamics(self, ctx: CommitCtx, local_params, center_params, local_state, center_state):
        """Expose the staleness the next commit will damp by: the gap between
        the replicated update counter and this worker's clock (and the
        resulting ``1/(staleness+1)`` scale) — the quantity DynSGD's whole
        design turns on, previously invisible outside the jitted program."""
        del ctx, local_params, center_params
        staleness = (center_state["num_updates"] - local_state["clock"]).astype(jnp.float32)
        return {"rule_staleness": staleness,
                "rule_scale": 1.0 / (staleness + 1.0)}

    def commit(self, ctx: CommitCtx, local_params, center_params, local_state, center_state):
        num_updates = center_state["num_updates"]
        staleness = (num_updates - local_state["clock"]).astype(jnp.float32)
        scale = 1.0 / (staleness + 1.0)
        delta = jax.tree.map(
            lambda x, a: (x - a) * scale, local_params, local_state["anchor"]
        )
        summed = ctx.psum(self._masked(ctx, delta))
        new_center = tree_add(center_params, summed)
        new_num_updates = num_updates + self._count_commits(ctx)
        new_local = self._pull(ctx, new_center, local_params)
        new_state = {
            "anchor": tree_where(ctx.mask, new_center, local_state["anchor"]),
            "clock": jnp.where(ctx.mask, new_num_updates, local_state["clock"]),
        }
        return CommitResult(new_local, new_center, new_state, {"num_updates": new_num_updates})
