"""Adaptive-staleness DynSGD — ABS/DynSSP-style online bound control.

Two cooperating halves:

* :class:`AdaptiveDynSGD` — a DynSGD variant whose center state carries a
  ``staleness_bound`` scalar **as traced data** (a float32 leaf, so the
  host can move it between epochs without retracing).  A commit whose
  staleness exceeds the bound is *dropped* — its delta never reaches the
  center — but the worker still pulls the fresh center and re-anchors,
  i.e. a straggler degrades into a catch-up pull instead of poisoning the
  center with ancient gradients (SSP-style bounded staleness, per DynSSP
  arXiv:1908.11848).  With the bound at its ``inf`` default the rule is
  bit-for-bit DynSGD.

* :class:`AdaptiveBound` — the host-side policy (ABS arXiv:2301.08895
  style): between epochs it reads the dynamics summary the telemetry layer
  already computes (``divergence_max``, ``rule_staleness_max``) and
  tightens the bound multiplicatively when divergence spikes against its
  running median, loosens it gently while training is stable.  Trainers
  apply the returned bound by replacing the ``staleness_bound`` leaf.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Optional

import jax
import jax.numpy as jnp

from distkeras_tpu.algorithms.base import CommitCtx, CommitResult
from distkeras_tpu.algorithms.dynsgd import DynSGD
from distkeras_tpu.utils.pytree import tree_add, tree_where

__all__ = ["AdaptiveBound", "AdaptiveDynSGD"]

BOUND_KEY = "staleness_bound"


@dataclasses.dataclass(frozen=True)
class AdaptiveDynSGD(DynSGD):
    communication_window: int = 5
    #: initial staleness bound; ``inf`` = plain DynSGD until a policy tightens it
    initial_bound: float = float("inf")

    def init_center_state(self):
        state = super().init_center_state()
        state[BOUND_KEY] = jnp.asarray(self.initial_bound, jnp.float32)
        return state

    def dynamics(self, ctx: CommitCtx, local_params, center_params,
                 local_state, center_state):
        out = super().dynamics(ctx, local_params, center_params,
                               local_state, center_state)
        staleness = out["rule_staleness"]
        bound = center_state[BOUND_KEY]
        out["rule_bound"] = jnp.broadcast_to(bound, staleness.shape)
        out["rule_dropped"] = (staleness > bound).astype(jnp.float32)
        return out

    def commit(self, ctx: CommitCtx, local_params, center_params,
               local_state, center_state):
        num_updates = center_state["num_updates"]
        staleness = (num_updates - local_state["clock"]).astype(jnp.float32)
        # the SSP gate: over-bound commits contribute nothing to the center
        # (and don't count as updates), but the worker still re-anchors below
        commit_mask = ctx.mask & (staleness <= center_state[BOUND_KEY])
        scale = 1.0 / (staleness + 1.0)
        delta = jax.tree.map(
            lambda x, a: (x - a) * scale, local_params, local_state["anchor"]
        )
        gated = CommitCtx(ctx.psum, commit_mask, ctx.steps_in_window,
                          ctx.num_workers)
        summed = ctx.psum(self._masked(gated, delta))
        new_center = tree_add(center_params, summed)
        new_num_updates = num_updates + self._count_commits(gated)
        # pull/re-anchor on the ORIGINAL boundary mask: a dropped (too-stale)
        # worker adopts the fresh center and resets its clock — graceful
        # catch-up instead of blocking the window
        new_local = self._pull(ctx, new_center, local_params)
        new_state = {
            "anchor": tree_where(ctx.mask, new_center, local_state["anchor"]),
            "clock": jnp.where(ctx.mask, new_num_updates, local_state["clock"]),
        }
        return CommitResult(new_local, new_center, new_state,
                            {"num_updates": new_num_updates,
                             BOUND_KEY: center_state[BOUND_KEY]})


class AdaptiveBound:
    """Host-side bound controller, applied between epochs.

    ``observe(summary)`` consumes one epoch's dynamics summary
    (:func:`distkeras_tpu.telemetry.dynamics.summarize` keys) and returns
    the bound the next epoch should run under:

    * divergence above ``divergence_factor`` x its running median →
      **tighten** (``bound *= tighten``, floored at ``min_bound``) — stale
      commits are hurting, gate them harder;
    * stable divergence → **loosen** (``bound *= loosen``, capped at
      ``max_bound``) — admit more asynchrony while it is safe.

    The bound also never tightens below the observed median staleness + 1:
    a bound under what healthy workers actually exhibit would starve the
    center entirely.
    """

    def __init__(self, initial: float = 16.0, min_bound: float = 1.0,
                 max_bound: float = 256.0, tighten: float = 0.5,
                 loosen: float = 1.25, divergence_factor: float = 2.0,
                 history: int = 8):
        self.bound = float(initial)
        self.min_bound = float(min_bound)
        self.max_bound = float(max_bound)
        self.tighten = float(tighten)
        self.loosen = float(loosen)
        self.divergence_factor = float(divergence_factor)
        self._divergences: deque = deque(maxlen=int(history))
        self.tightened = 0
        self.loosened = 0

    @staticmethod
    def _median(values) -> Optional[float]:
        if not values:
            return None
        ordered = sorted(values)
        mid = len(ordered) // 2
        if len(ordered) % 2:
            return ordered[mid]
        return 0.5 * (ordered[mid - 1] + ordered[mid])

    def observe(self, summary: dict) -> float:
        div = summary.get("divergence_max")
        staleness = summary.get("rule_staleness_mean",
                                summary.get("rule_staleness"))
        baseline = self._median(list(self._divergences))
        if div is not None:
            self._divergences.append(float(div))
        if (div is not None and baseline is not None and baseline > 0
                and float(div) > self.divergence_factor * baseline):
            self.bound = max(self.min_bound, self.bound * self.tighten)
            self.tightened += 1
        else:
            self.bound = min(self.max_bound, self.bound * self.loosen)
            self.loosened += 1
        if staleness is not None:
            # never gate below what live workers actually exhibit
            self.bound = max(self.bound, float(staleness) + 1.0)
        self.bound = min(self.bound, self.max_bound)
        return self.bound
