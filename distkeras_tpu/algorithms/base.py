"""Update-rule interface: the pure-function form of the reference's
parameter-server protocols.

In the reference, each algorithm is split across a Worker (client: accumulate
a residual, ``commit``/``pull`` over TCP — ``distkeras/workers.py``) and a
ParameterServer (server: apply the committed delta to the center variable —
``distkeras/parameter_servers.py :: handle_commit``).  On TPU both halves fuse
into one pure ``commit`` function executed *inside* the SPMD program at a
window boundary: the worker-side delta computation runs per-device, the
server-side "apply to center" is an ``psum`` over the worker mesh axis
followed by a replicated center update.  The TCP round-trip disappears; its
semantics remain.

Every rule is a pure pytree transform, unit-testable against the closed-form
math in SURVEY.md §3.3 without any mesh at all (pass ``psum=identity``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from distkeras_tpu.utils.pytree import tree_sub, tree_where, tree_zeros_like

__all__ = ["CommitCtx", "CommitResult", "UpdateRule", "make_ctx"]


class CommitCtx(NamedTuple):
    """Execution context handed to ``commit`` at a window boundary.

    ``psum``  — sum over the worker axis (identity when testing single-worker).
    ``mask``  — scalar bool: does *this* worker commit at this boundary?  In
                the synchronous-window engine it is constant True; in the
                staleness-simulation engine it encodes each worker's own
                commit schedule, which is what models real-world asynchrony
                deterministically.
    ``steps_in_window`` — local optimizer steps since this worker's last
                commit (ADAG normalises by it).
    """

    psum: Callable[[Any], Any]
    mask: jnp.ndarray
    steps_in_window: jnp.ndarray
    num_workers: int


class CommitResult(NamedTuple):
    local_params: Any
    center_params: Any
    local_state: Any
    center_state: Any


def make_ctx(axis_name=None, mask=True, steps_in_window=1, num_workers=1) -> CommitCtx:
    psum = (lambda t: jax.tree.map(lambda x: lax.psum(x, axis_name), t)) if axis_name else (lambda t: t)
    return CommitCtx(
        psum=psum,
        mask=jnp.asarray(mask),
        steps_in_window=jnp.asarray(steps_in_window, jnp.float32),
        num_workers=num_workers,
    )


@dataclasses.dataclass(frozen=True)
class UpdateRule:
    """Base class: one async-SGD variant = one subclass.

    ``communication_window`` mirrors the reference trainers' kwarg of the same
    name: number of local steps between commits.
    """

    communication_window: int = 5

    #: do committing workers re-pull (adopt) the center after commit?
    pulls: bool = True

    def init_local_state(self, params) -> Any:
        """Per-worker rule state (anchors, clocks); params = initial center."""
        return ()

    def init_center_state(self) -> Any:
        """Replicated center-side state (update counters)."""
        return {"num_updates": jnp.zeros((), jnp.int32)}

    def commit(
        self, ctx: CommitCtx, local_params, center_params, local_state, center_state
    ) -> CommitResult:
        raise NotImplementedError

    def dynamics(
        self, ctx: CommitCtx, local_params, center_params, local_state, center_state
    ) -> dict:
        """Per-worker scalar diagnostics for ``telemetry.dynamics``.

        Called in-graph at the commit boundary with *pre-commit* values (the
        same arguments ``commit`` is about to see).  Returned scalars merge
        into the engine's dynamics stats leaves as per-worker series; keys
        should be ``rule_*``-prefixed to stay clear of the engine's own
        leaves.  The base rules expose nothing."""
        del ctx, local_params, center_params, local_state, center_state
        return {}

    # -- shared helpers ----------------------------------------------------
    @staticmethod
    def _masked(ctx: CommitCtx, tree):
        m = ctx.mask.astype(jnp.float32)
        return jax.tree.map(lambda x: x * m, tree)

    @staticmethod
    def _count_commits(ctx: CommitCtx):
        return ctx.psum(ctx.mask.astype(jnp.int32))

    @staticmethod
    def _pull(ctx: CommitCtx, new_center, local_params):
        """Committing workers adopt the fresh center (the reference's ``pull``)."""
        return tree_where(ctx.mask, new_center, local_params)
