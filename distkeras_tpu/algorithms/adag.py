"""ADAG — Accumulated-Gradient Normalization (Hermans, arXiv:1710.02368).

Reference semantics (``distkeras/workers.py :: ADAGWorker.train``): like
DOWNPOUR, but the accumulated residual is normalised by the number of local
steps in the window before committing, which keeps the effective update
magnitude independent of the communication window and (per the paper)
stabilises convergence as worker count grows.

TPU form: ``center += psum((local − anchor) / steps_in_window)``.
"""

from __future__ import annotations

import dataclasses

import jax

from distkeras_tpu.algorithms.base import CommitCtx, CommitResult, UpdateRule
from distkeras_tpu.utils.pytree import tree_add, tree_sub, tree_where

__all__ = ["Adag"]


@dataclasses.dataclass(frozen=True)
class Adag(UpdateRule):
    communication_window: int = 12

    def init_local_state(self, params):
        return {"anchor": params}

    def dynamics(self, ctx: CommitCtx, local_params, center_params, local_state, center_state):
        """Expose the accumulation state: the norm of the residual gathered
        since the anchor and the ``1/steps_in_window`` normaliser it will be
        scaled by at commit."""
        import jax.numpy as jnp

        from distkeras_tpu.telemetry.dynamics import tree_sq_dist

        del center_params, center_state
        return {
            "rule_accum_norm": jnp.sqrt(tree_sq_dist(local_params, local_state["anchor"])),
            "rule_accum_steps": ctx.steps_in_window,
        }

    def commit(self, ctx: CommitCtx, local_params, center_params, local_state, center_state):
        inv_w = 1.0 / ctx.steps_in_window
        residual = jax.tree.map(
            lambda x, a: (x - a) * inv_w, local_params, local_state["anchor"]
        )
        summed = ctx.psum(self._masked(ctx, residual))
        new_center = tree_add(center_params, summed)
        new_local = self._pull(ctx, new_center, local_params)
        new_anchor = tree_where(ctx.mask, new_center, local_state["anchor"])
        new_center_state = {
            "num_updates": center_state["num_updates"] + self._count_commits(ctx)
        }
        return CommitResult(new_local, new_center, {"anchor": new_anchor}, new_center_state)
