"""AEASGD / EAMSGD — (Momentum) Asynchronous Elastic Averaging SGD
(Zhang, Choromanska & LeCun, NIPS 2015).

Reference semantics (``distkeras/workers.py :: AEASGDWorker.train``, §3.3 of
SURVEY.md): every ``communication_window`` (τ) steps the worker computes the
elastic difference ``E = α·(x − center)`` with ``α = learning_rate·ρ``,
subtracts it from its local variable, and commits it; the PS does
``center += E``.  Workers never pull — the elastic force is the only coupling,
which is what lets local variables *explore* around the center.

EAMSGD is identical at the commit boundary; the momentum lives in the local
optimizer (the engine uses Nesterov-momentum SGD as the worker optimizer, the
TPU-native form of the reference's explicit velocity update).

TPU form: ``E_i = α(x_i − center)``; ``x_i −= E_i``; ``center += psum(E_i)``.
"""

from __future__ import annotations

import dataclasses

import jax

from distkeras_tpu.algorithms.base import CommitCtx, CommitResult, UpdateRule
from distkeras_tpu.utils.pytree import tree_add, tree_sub

__all__ = ["Aeasgd", "Eamsgd"]


@dataclasses.dataclass(frozen=True)
class Aeasgd(UpdateRule):
    communication_window: int = 32
    rho: float = 5.0
    learning_rate: float = 0.1
    pulls: bool = False

    @property
    def alpha(self) -> float:
        return self.learning_rate * self.rho

    def init_local_state(self, params):
        return ()

    def commit(self, ctx: CommitCtx, local_params, center_params, local_state, center_state):
        alpha = self.alpha
        elastic = jax.tree.map(lambda x, c: alpha * (x - c), local_params, center_params)
        elastic = self._masked(ctx, elastic)
        new_local = tree_sub(local_params, elastic)
        new_center = tree_add(center_params, ctx.psum(elastic))
        new_center_state = {
            "num_updates": center_state["num_updates"] + self._count_commits(ctx)
        }
        return CommitResult(new_local, new_center, local_state, new_center_state)


@dataclasses.dataclass(frozen=True)
class Eamsgd(Aeasgd):
    """EAMSGD: elastic averaging + Nesterov momentum on the local variable.

    The commit rule is AEASGD's; trainers pair it with a momentum worker
    optimizer (reference parity: ``EAMSGDWorker``'s explicit velocity).
    """

    momentum: float = 0.9
