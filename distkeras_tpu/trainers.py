"""Trainers — the user-facing API, signature-compatible with the reference.

Reference surface (``distkeras/trainers.py``): construct a trainer around a
compiled Keras model and call ``trainer.train(dataframe)`` to get a trained
model back.  The class family is preserved exactly — ``SingleTrainer``,
``AveragingTrainer``, ``EnsembleTrainer``, ``DistributedTrainer``,
``AsynchronousDistributedTrainer``, ``DOWNPOUR``, ``AEASGD``, ``EAMSGD``,
``ADAG``, ``DynSGD`` — as are the kwargs the notebooks use
(``features_col``, ``label_col``, ``batch_size``, ``num_epoch``,
``communication_window``, ``rho``, ``learning_rate``, ``momentum``,
``num_workers``, ``master_port``, ``parallelism_factor``).

What changed underneath: ``train`` no longer launches a Spark job against a
socket parameter server — it compiles one SPMD program over a TPU mesh
(:mod:`distkeras_tpu.parallel.engine`) where the PS center variable is
replicated on-device and commits are ICI collectives.  Models may be Keras 3
(JAX backend), flax modules, or adapters; Keras models are returned as Keras
models with trained weights, matching the reference contract.
"""

from __future__ import annotations

import dataclasses
import time
import warnings
from typing import Any, List, Optional, Sequence

import jax
import numpy as np

from distkeras_tpu import chaos as _chaos
from distkeras_tpu import fleet as _fleet
from distkeras_tpu import sanitizer
from distkeras_tpu import telemetry
from distkeras_tpu import workers as workers_mod
from distkeras_tpu.data import epoch_arrays
from distkeras_tpu.frame import DataFrame
from distkeras_tpu.models.adapter import ModelAdapter, TrainedModel, as_adapter
from distkeras_tpu.parallel.engine import WindowedEngine
from distkeras_tpu.parameter_servers import (
    ADAGParameterServer,
    DeltaParameterServer,
    DynSGDParameterServer,
    ParameterServer,
)

__all__ = [
    "Trainer",
    "SingleTrainer",
    "AveragingTrainer",
    "EnsembleTrainer",
    "DistributedTrainer",
    "AsynchronousDistributedTrainer",
    "DOWNPOUR",
    "AEASGD",
    "EAMSGD",
    "ADAG",
    "DynSGD",
    "AdaptiveDynSGD",
]


def _serving_twin(adapter: ModelAdapter) -> ModelAdapter:
    """The single-device twin of a sequence-parallel adapter (same params).

    A seq_axis-bearing model jit-traces ring-attention collectives and
    cannot run outside its mesh; every trainer return path hands back the
    seq_axis=None twin so the reference contract — ``train(df)`` returns a
    servable model — holds for sp-trained models too.  No-op for adapters
    without a seq axis."""
    module = getattr(adapter, "module", None)
    if module is not None and getattr(module, "seq_axis", None) is not None:
        from distkeras_tpu.models.adapter import FlaxModel

        return FlaxModel(module.clone(seq_axis=None), adapter.outputs_logits)
    if (dataclasses.is_dataclass(adapter)
            and getattr(adapter, "seq_axis", None) is not None):
        # Staged adapters (pp x sp) are dataclasses, not FlaxModel
        # wrappers — same twin rule via replace.  replace() builds a
        # fresh instance, so carry over the non-field checkpoint slot
        # PretrainedStagedLM's init requires.
        twin = dataclasses.replace(adapter, seq_axis=None)
        pretrained = getattr(adapter, "_pretrained", None)
        if pretrained is not None:
            twin._pretrained = pretrained
        return twin
    return adapter


def _epoch_mean(stats, key):
    """Per-epoch mean of ``stats[key]`` over its window axis, weighted by
    per-window step counts when the streaming path recorded a ragged tail
    (``window_steps``, :meth:`WindowedEngine.run_epoch_streaming`).  A
    ragged tail window averages fewer steps than the full windows, so the
    unweighted mean over-weights it; weighting by steps makes the epoch
    mean match the in-memory path's mean over all steps.  Uniform windows
    (and the in-memory path, which records no ``window_steps``) take the
    plain ``np.mean`` branch so existing histories stay bitwise unchanged."""
    values = np.asarray(stats[key])
    weights = stats.get("window_steps") if isinstance(stats, dict) else None
    if (weights is not None and values.ndim >= 1
            and values.shape[0] == len(weights)
            and int(np.min(weights)) != int(np.max(weights))):
        return np.average(values, axis=0, weights=np.asarray(weights))
    return np.mean(values, axis=0) if values.ndim > 1 else np.mean(values)


class Trainer:
    """Base trainer: model + loss + worker optimizer + wall-clock bookkeeping
    (reference parity: ``trainers.py :: Trainer``)."""

    def __init__(
        self,
        keras_model: Any,
        loss: Any = "categorical_crossentropy",
        worker_optimizer: Any = "sgd",
        metrics: Sequence = ("accuracy",),
        features_col: str = "features",
        label_col: str = "label",
        batch_size: int = 32,
        num_epoch: int = 1,
        seed: int = 0,
        compute_dtype: Any = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 1,
        resume: bool = False,
        profile_dir: Optional[str] = None,
        seq_shards: int = 1,
        tp_shards: int = 1,
        fsdp: bool = False,
        tensorboard_dir: Optional[str] = None,
        streaming: bool = False,
        remat: bool = False,
        unroll=1,
        dispatch_epochs: int = 1,
        pipeline_stages: int = 1,
        pp_microbatches: Optional[int] = None,
        tp_spec_fn: Optional[Any] = None,
        prefetch: int = 0,
        checkpoint_blocks: int = 0,
    ):
        self.master_model = keras_model
        self.loss = loss
        self.worker_optimizer = worker_optimizer
        self.metrics = tuple(metrics)
        self.features_col = features_col
        self.label_col = label_col
        self.batch_size = int(batch_size)
        self.num_epoch = int(num_epoch)
        self.seed = seed
        if isinstance(compute_dtype, str):
            import jax.numpy as jnp

            compute_dtype = jnp.dtype(compute_dtype)
        self.compute_dtype = compute_dtype
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = checkpoint_every
        self.resume = resume
        # SURVEY.md §5.1: the reference only wall-clocked training; we add
        # optional per-epoch device tracing viewable in TensorBoard/Perfetto.
        self.profile_dir = profile_dir
        # SURVEY.md §5.5: optional per-epoch loss/metric scalars (TensorBoard
        # event files when a writer is importable, JSONL otherwise).
        self.tensorboard_dir = tensorboard_dir
        # Streaming data path: feed the engine window-sized blocks through a
        # double-buffered iterator instead of materialising whole epochs
        # (identical trajectory; for datasets approaching HBM size).
        self.streaming = bool(streaming)
        # Rematerialise forward activations on the backward pass
        # (jax.checkpoint in both engines): trades FLOPs for HBM — the lever
        # for deep models (ResNet-scale+) whose per-window activations
        # outgrow the chip.  Gradients are mathematically identical; see
        # tests/test_fixes_r3.py (trajectory-equality on ResNet20).
        self.remat = bool(remat)
        # Per-step scan unroll factor (int, or True = full unroll) — see
        # WindowedEngine._finish_init.  Math is unroll-invariant.
        self.unroll = unroll
        # >1: run up to this many epochs per device dispatch
        # (engine.run_epochs) with ON-DEVICE inter-epoch reshuffling,
        # amortising the fixed per-epoch host round-trip (measurement:
        # WindowedEngine._make_multi_epoch_fn).  The reshuffle draws from the
        # device RNG stream, not the host rng, so trajectories legitimately
        # differ from dispatch_epochs=1 (both are uniform permutations).
        # Checkpoint cadence is preserved: chunks never straddle a
        # checkpoint_every boundary.  Incompatible with streaming=True and
        # with staleness schedules (both need per-epoch host involvement).
        self.dispatch_epochs = int(dispatch_epochs)
        if self.dispatch_epochs < 1:
            raise ValueError(
                f"dispatch_epochs must be >= 1, got {dispatch_epochs}"
            )
        # sequence parallelism (ring attention) shards: >1 requires a
        # seq-axis-aware model (models/transformer.py)
        self.seq_shards = int(seq_shards)
        # tensor parallelism shards: >1 selects the GSPMD engine (param
        # leaves sharded over a 'model' mesh axis; any model, unmodified)
        self.tp_shards = int(tp_shards)
        # ZeRO-3-style sharding of the center variable over the workers axis
        # (GSPMD engine; composes with tp_shards) — pure layout change, the
        # replicated parameter-server copy stops costing num_devices x HBM
        self.fsdp = bool(fsdp)
        # pipeline parallelism stages: >1 selects the pipeline engine
        # (microbatch ppermute pipeline over a 'stages' mesh axis; requires a
        # staged adapter, models/staged.StagedTransformer, with num_stages ==
        # pipeline_stages)
        self.pipeline_stages = int(pipeline_stages)
        self.pp_microbatches = pp_microbatches
        # optional GSPMD leaf-placement override, (shape, path) ->
        # PartitionSpec|None — e.g. models.expert_partition for MoE expert
        # sharding over the model axis
        self.tp_spec_fn = tp_spec_fn
        if tp_spec_fn is not None and self.tp_shards <= 1:
            raise ValueError(
                "tp_spec_fn places leaves on the model mesh axis, which only "
                "exists with tp_shards>1 (the GSPMD engine); without it the "
                "override would be silently ignored"
            )
        # >0 with streaming=True: wrap the epoch's block iterator in a
        # datapipe.PrefetchRing of this depth — gathers (and the h2d put)
        # move to a producer thread and overlap device steps.  The block
        # order and payloads are untouched, so the trajectory stays bitwise
        # identical (tests/test_datapipe.py pins it).
        self.prefetch = int(prefetch)
        if self.prefetch < 0:
            raise ValueError(f"prefetch must be >= 0, got {prefetch}")
        # >0 with streaming + checkpoint_dir: additionally checkpoint every N
        # consumed blocks MID-epoch (model state + datapipe.DataState cursor),
        # so a killed run resumes at the block it died on, not the epoch
        # boundary.  Needs the streaming path — the in-memory path dispatches
        # whole epochs, leaving no block boundary to save at.
        self.checkpoint_blocks = int(checkpoint_blocks)
        if self.checkpoint_blocks < 0:
            raise ValueError(
                f"checkpoint_blocks must be >= 0, got {checkpoint_blocks}"
            )
        if self.checkpoint_blocks and not self.streaming:
            raise ValueError(
                "checkpoint_blocks>0 saves at streaming block boundaries; "
                "set streaming=True (the in-memory path dispatches whole "
                "epochs, so there is no mid-epoch point to save at)"
            )
        self.history: dict = {}
        self.training_time: float = 0.0
        self._t0: Optional[float] = None

    # -- wall-clock bookkeeping (reference parity) --------------------------
    def record_training_start(self) -> None:
        # monotonic clock: wall-clock (time.time) can jump under NTP slew,
        # yielding negative or wildly wrong durations
        self._t0 = time.perf_counter()

    def record_training_stop(self) -> None:
        if self._t0 is None:  # stop without start: no interval to measure
            self.training_time = 0.0
        else:
            self.training_time = time.perf_counter() - self._t0

    def get_training_time(self) -> float:
        return self.training_time

    def get_history(self) -> dict:
        return self.history

    def _effective_worker_optimizer(self):
        """The optimizer spec handed to engines/workers.  Subclasses with an
        algorithm-specific default (EAMSGD) override this instead of mutating
        ``self.worker_optimizer``, so retraining after changing hyperparams
        resolves a fresh spec."""
        return self.worker_optimizer

    # -- internals ----------------------------------------------------------
    def _load_columns(self, dataframe: DataFrame):
        # Integer token features (TextCNN) must stay integral; every other
        # feature column materialises as one float32 matrix.  Dtype is
        # decided from the raw column BEFORE materialising, so the full
        # dataset is copied exactly once per call.
        f_raw = dataframe.column(self.features_col)
        if f_raw.dtype != object and np.issubdtype(f_raw.dtype, np.integer):
            feats = f_raw.astype(np.int32)
        else:
            feats = dataframe.matrix(self.features_col, dtype=np.float32)
        labels_raw = dataframe.column(self.label_col)
        if labels_raw.dtype == object:
            labels = dataframe.matrix(self.label_col, dtype=np.float32)
        elif np.issubdtype(labels_raw.dtype, np.integer):
            labels = labels_raw.astype(np.int32)
        else:
            labels = labels_raw.astype(np.float32)
        return feats, labels

    def _restore_state(self, ckpt, engine, state, elastic: bool, step=None):
        """Resume from ``checkpoint_dir``: bitwise when the checkpoint was
        written at this trainer's worker count; **elastic** otherwise — the
        restored center variable (and its commit counters and epoch) carry
        over, and the new worker set re-pulls it as fresh local replicas,
        which is the reference's worker-retry semantics (a retried Spark
        task reconnects to the PS and pulls — SURVEY.md §5.3).  Beyond
        reference: upstream had no way to continue a run on a different
        cluster size at all."""
        if not elastic:
            return ckpt.restore(like=state, step=step)  # bitwise, single read
        # elastic: only center/rule/epoch read here; the per-worker
        # [N_old, ...] model-state stack never materialises whole — it
        # reduces to its worker mean in budget-bounded partial restores
        # (checkpoint.model_state_worker_mean), the same semantic
        # sync_model_state applies at every commit.  Both reads pin the
        # step resolved in _fit, so a save landing mid-resume cannot mix
        # checkpoints.
        raw = ckpt.restore_center(step, include_model_state=False)
        epoch = int(np.asarray(raw["epoch"]))
        model_state = ckpt.model_state_worker_mean(step)
        return engine.state_from_center(
            jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch),
            raw["center_params"], raw["center_rule"], model_state, epoch,
        )

    def _watchdog_rollback(self, engine, ckpt, state, watchdog):
        """Restore the last checkpoint after a watchdog trip (policy
        ``rollback``): the diverged state is discarded and training
        continues from the restored center/workers — the same
        :meth:`_restore_state` path a crash-resume takes."""
        reason = watchdog.pending_rollback
        # verified: rolling back onto a corrupt checkpoint would trade a
        # diverged run for a crashed one
        step = ckpt.latest_verified() if ckpt is not None else None
        if step is None:
            raise telemetry.dynamics.TrainingDiverged(
                f"{reason} — rollback requested but no checkpoint has been "
                "saved yet"
            )
        state = self._restore_state(ckpt, engine, state, elastic=False, step=step)
        watchdog.rolled_back()
        if telemetry.enabled():
            telemetry.metrics.counter(
                "dynamics_rollbacks_total",
                help="watchdog-triggered checkpoint restores",
            ).inc()
        return state

    def _apply_staleness_bound(self, policy, summary, state):
        """Feed the finished epoch's dynamics summary to the host-side
        staleness policy and swap the rule's ``staleness_bound`` leaf with
        the bound it returns.  The leaf is traced *data* (same float32
        scalar shape), so the swap never retraces the epoch program; rules
        without the leaf (plain DynSGD) pass through untouched."""
        from distkeras_tpu.algorithms.adaptive import BOUND_KEY

        if BOUND_KEY not in state.center_rule:
            return state
        import jax.numpy as jnp

        bound = float(policy.observe(summary))
        rule_state = dict(state.center_rule)
        rule_state[BOUND_KEY] = jnp.asarray(bound, jnp.float32)
        if telemetry.enabled():
            telemetry.metrics.gauge(
                "dynamics_staleness_bound",
                help="adaptive DynSGD staleness bound in force",
            ).set(bound)
        return state.replace(center_rule=rule_state)

    def _elastic_resize(self, build_engine, engine, state, ckpt, epoch, rng,
                        shuffle, new_workers):
        """Mid-run worker-count change at an epoch boundary: drain to a
        boundary checkpoint, gather the center off the old engine, re-plan,
        and rebuild state at ``new_workers`` via the same
        ``state_from_center`` path an elastic *resume* takes — but live, with
        no process restart.  Progress (center params, rule counters, epoch)
        carries over; local replicas re-pull the center, exactly the
        reference's worker-(re)connect semantics."""
        from distkeras_tpu.parallel.engine import plan_workers

        if ckpt is not None:
            # leave a boundary checkpoint first: if the rebuild dies (OOM on
            # a shrunken mesh, say), train_with_recovery resumes from here
            from distkeras_tpu.datapipe import DataState

            ckpt.save_partial(state, epoch, DataState(
                epoch=epoch + 1, block_cursor=0,
                rng_state=(rng.bit_generator.state if shuffle else None)))
            ckpt.wait()
        from distkeras_tpu.checkpoint import worker_mean

        center = jax.tree.map(np.asarray, engine.gather_center(state))
        center_rule = jax.tree.map(np.asarray, state.center_rule)
        # per-worker model state reduces to its worker mean — the same
        # semantic sync_model_state applies at every commit boundary
        model_state = jax.tree.map(
            lambda v: worker_mean(np.asarray(v)), state.model_state)
        devices_used, _ = plan_workers(new_workers, jax.device_count())
        engine.clear_program_cache()
        new_engine = build_engine(new_workers)
        new_state = new_engine.state_from_center(
            jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch + 1),
            center, center_rule, model_state, epoch + 1,
        )
        if telemetry.enabled():
            telemetry.metrics.counter(
                "elastic_resizes_total",
                help="mid-run worker-count rebuilds",
            ).inc()
            telemetry.metrics.gauge(
                "elastic_workers", help="current logical worker count"
            ).set(new_workers)
            telemetry.metrics.gauge(
                "elastic_devices", help="devices the worker axis occupies"
            ).set(devices_used)
        return new_engine, new_state

    def _fit(self, *args, **kwargs):
        """Crash-forensics boundary around :meth:`_fit_inner`.

        Mints/propagates the fleet ``run_id``, starts the live HTTP exporter
        when one is configured, and — on ANY unhandled exception, including
        watchdog halts and strict sanitizer violations — dumps the
        flight-recorder blackbox into the telemetry dir before re-raising.
        With telemetry off this is one cached-bool check per fit.
        """
        if telemetry.enabled():
            telemetry.flightdeck.activate()
        try:
            return self._fit_inner(*args, **kwargs)
        except Exception as e:
            if telemetry.enabled():
                telemetry.flightdeck.on_crash(
                    f"{type(self).__name__}._fit: {type(e).__name__}: {e}")
            raise

    def _fit_inner(
        self,
        dataframe: DataFrame,
        rule,
        num_workers: int,
        *,
        shuffle: bool = True,
        average_at_end: bool = False,
        commit_schedule: Optional[np.ndarray] = None,
    ):
        adapter = as_adapter(self.master_model)
        # Local canonicalised copy: per-token models rename accuracy ->
        # token_accuracy so history/TensorBoard keys match the engine's
        # metric names, WITHOUT mutating the user-visible self.metrics the
        # caller constructed the trainer with.
        metrics = self.metrics
        if getattr(adapter, "per_token_labels", False):
            from distkeras_tpu.ops.metrics import per_token_metric_names

            metrics = per_token_metric_names(metrics)
        with telemetry.trace.span("load_columns", phase="data"):
            feats, labels = self._load_columns(dataframe)

        # One engine-construction recipe, parameterised by worker count, so
        # an elastic resize can re-plan and rebuild mid-run with exactly the
        # configuration the original engine was built under.
        def build_engine(n_workers: int):
            if self.pipeline_stages > 1:
                if self.tp_spec_fn is not None:
                    raise ValueError(
                        "tp_spec_fn is a GSPMD-engine override; the pipeline "
                        "engine places the model axis by its staged-leaf shape "
                        "rule"
                    )
                if commit_schedule is not None:
                    raise ValueError(
                        "pipeline_stages>1 is incompatible with commit_schedule "
                        "(the staleness simulation dispatches per step)"
                    )
                if getattr(adapter, "num_stages", None) != self.pipeline_stages:
                    raise ValueError(
                        f"pipeline_stages={self.pipeline_stages} needs a staged "
                        f"adapter with num_stages={self.pipeline_stages} (e.g. "
                        "models.StagedTransformer); got "
                        f"{type(self.master_model).__name__}"
                    )
                from distkeras_tpu.parallel.pipeline import PipelineEngine

                return PipelineEngine(
                    adapter,
                    self.loss,
                    self._effective_worker_optimizer(),
                    rule,
                    n_workers,
                    microbatches=self.pp_microbatches,
                    tp_shards=self.tp_shards,
                    seq_shards=self.seq_shards,
                    fsdp=self.fsdp,
                    metrics=metrics,
                    compute_dtype=self.compute_dtype,
                    remat=self.remat,
                    unroll=self.unroll,
                )
            if self.tp_shards > 1 or (self.fsdp and self.seq_shards == 1):
                if self.seq_shards > 1:
                    raise ValueError(
                        "tp_shards>1 (GSPMD engine) is incompatible with "
                        "seq_shards>1 (ring attention needs the shard_map "
                        "engine); fsdp + seq_shards IS supported — drop tp_shards"
                    )
                from distkeras_tpu.parallel.gspmd import GSPMDEngine

                return GSPMDEngine(
                    adapter,
                    self.loss,
                    self._effective_worker_optimizer(),
                    rule,
                    n_workers,
                    tp_shards=self.tp_shards,
                    fsdp=self.fsdp,
                    spec_fn=self.tp_spec_fn,
                    metrics=metrics,
                    compute_dtype=self.compute_dtype,
                    commit_schedule=commit_schedule,
                    remat=self.remat,
                    unroll=self.unroll,
                )
            return WindowedEngine(
                adapter,
                self.loss,
                self._effective_worker_optimizer(),
                rule,
                n_workers,
                metrics=metrics,
                compute_dtype=self.compute_dtype,
                commit_schedule=commit_schedule,
                seq_shards=self.seq_shards,
                # fsdp x sp: seq-axis ZeRO center sharding in the shard_map
                # engine (fsdp alone routed to the GSPMD engine above)
                fsdp=self.fsdp and self.seq_shards > 1,
                remat=self.remat,
                unroll=self.unroll,
            )

        engine = build_engine(num_workers)
        window = rule.communication_window if rule.communication_window > 0 else None
        rng = np.random.default_rng(self.seed)

        ckpt = None
        start_epoch = 0
        resuming = False
        elastic = False
        if self.checkpoint_dir:
            from distkeras_tpu.checkpoint import CheckpointManager

            ckpt = CheckpointManager(self.checkpoint_dir, every=self.checkpoint_every)
            # resolve the resume step ONCE; every read below pins it, so a
            # concurrent writer (second elastic job, in-flight async save)
            # cannot hand different reads different checkpoints.  Verified
            # resolution: a step whose bytes no longer match its manifest
            # (torn write, bit rot) is quarantined here and resume falls to
            # the newest step that proves out — never loaded, never trusted
            resume_step = ckpt.latest_verified() if self.resume else None
            resuming = resume_step is not None
            elastic = resuming and ckpt.saved_worker_count(resume_step) != engine.num_workers
            if elastic and rule.communication_window <= 0:
                # no-commit rules (Sequential/OneShotAverage) never fold
                # progress into the center mid-training, so an elastic
                # resume would silently restart from initialization with a
                # nonzero epoch counter — refuse loudly instead
                raise ValueError(
                    f"elastic resume (checkpoint at "
                    f"{ckpt.saved_worker_count(resume_step)} workers, trainer at "
                    f"{engine.num_workers}) requires a committing rule; "
                    f"{type(rule).__name__} only produces its result at the "
                    "end of training, so the checkpointed center carries no "
                    "progress to adopt.  Resume with the original "
                    "num_workers instead."
                )

        # Divergence watchdog: armed only when the engine traces dynamics
        # stats (DISTKERAS_DYNAMICS=1 and not the pipeline engine).  All its
        # checks run on host numpy AFTER the epoch's stats land — never
        # inside the step loop (dklint DK107).
        watchdog = None
        if getattr(engine, "_dynamics", False):
            watchdog = telemetry.dynamics.DivergenceWatchdog.from_config()
        if watchdog is not None and watchdog.policy == "rollback":
            if ckpt is None:
                raise ValueError(
                    "watchdog policy 'rollback' needs checkpoint_dir set so "
                    "there is a checkpoint to restore"
                )
            if self.dispatch_epochs > 1:
                raise ValueError(
                    "watchdog policy 'rollback' needs the per-epoch loop; "
                    "dispatch_epochs>1 runs whole chunks per dispatch with no "
                    "epoch boundary to restore at"
                )

        # Elastic membership: poll the fleet's membership epoch at epoch
        # boundaries and resize the worker set mid-run.  Only meaningful for
        # committing rules (progress must live in the center to carry across
        # a rebuild) on the per-epoch loop.
        elastic_ctl = getattr(self, "elastic", None)
        if elastic_ctl is not None and (
            rule.communication_window <= 0
            or commit_schedule is not None
            or self.pipeline_stages > 1
            or self.dispatch_epochs > 1
        ):
            warnings.warn(
                "elastic membership polling disabled: it requires a "
                "committing rule on the per-epoch loop (no commit_schedule, "
                "pipeline_stages=1, dispatch_epochs=1)",
                RuntimeWarning,
            )
            elastic_ctl = None

        # AdaptiveBound staleness policy: applied between epochs by swapping
        # the rule's traced staleness_bound scalar (same dtype/shape, so no
        # retrace).  Needs the dynamics summary the telemetry layer traces.
        staleness_policy = getattr(self, "staleness_policy", None)
        if staleness_policy is not None and not getattr(engine, "_dynamics", False):
            warnings.warn(
                "staleness_policy set but dynamics telemetry is off "
                "(DISTKERAS_DYNAMICS); the bound will not adapt",
                RuntimeWarning,
            )
            staleness_policy = None

        # The elastic path builds its state straight from the partial
        # restore — a fresh init_state would be thrown away (and costs a
        # full-state materialisation).  The pipeline engine still needs
        # init_state first (it probes the staged shapes there), and the
        # bitwise path needs it as the restore template.
        state = None
        if not elastic or self.pipeline_stages > 1:
            state = engine.init_state(
                jax.random.PRNGKey(self.seed), feats[: self.batch_size]
            )
        resume_data = None
        if resuming:
            state = self._restore_state(ckpt, engine, state, elastic, step=resume_step)
            start_epoch = int(np.asarray(state.epoch))
            # data checkpoint sidecar (datapipe.DataState): exact RNG bit
            # state + mid-epoch block cursor.  A sidecar whose epoch doesn't
            # match the restored model epoch (external writer, older layout)
            # is ignored — the legacy fast-forward below still aligns the
            # shuffle stream at epoch granularity.
            resume_data = ckpt.restore_data_state(resume_step)
            if resume_data is not None and int(resume_data.epoch) != start_epoch:
                resume_data = None
            if (resume_data is not None and resume_data.block_cursor
                    and not self.streaming):
                raise ValueError(
                    f"checkpoint at step {resume_step} was saved mid-epoch "
                    f"(block cursor {resume_data.block_cursor}); resuming it "
                    "requires streaming=True — the in-memory path dispatches "
                    "whole epochs and cannot skip consumed blocks"
                )

        # keep the host RNG stream aligned with the epoch counter on resume:
        # exact bit-state restore when a DataState sidecar was saved, else
        # the legacy epoch-granularity fast-forward.  (Chunked dispatch
        # shuffles on device, keyed by state.epoch — its alignment is free
        # and the host stream is never drawn from.)
        if self.dispatch_epochs == 1:
            if resume_data is not None and resume_data.rng_state is not None:
                resume_data.restore_rng(rng)
            else:
                for _ in range(start_epoch):
                    rng.permutation(len(feats))

        scalar_log = None
        if self.tensorboard_dir:
            from distkeras_tpu.utils.tb import ScalarLogger

            scalar_log = ScalarLogger(self.tensorboard_dir)
        # env-driven step-windowed jax.profiler capture; profile_dir (the
        # explicit per-trainer knob below) takes precedence — both would
        # race on one global profiler session
        prof = None if self.profile_dir else telemetry.ProfilerHook.from_env()
        if telemetry.enabled():
            telemetry.install_jax_hooks()

        last_summary: dict = {}

        def _materialise(stats, epoch_idx):
            stats = jax.tree.map(np.asarray, stats)
            dyn = stats.get("dynamics")
            summary = None
            if dyn is not None:
                # gauges first so the scalar-logger bridge below picks up
                # this epoch's values, then the full series into the
                # metrics JSONL
                summary = telemetry.dynamics.summarize(dyn, loss=stats["loss"])
                telemetry.dynamics.record(epoch_idx, dyn, summary)
                last_summary["value"] = summary
            if scalar_log is not None:
                scalars = {"loss": float(_epoch_mean(stats, "loss"))}
                mets = np.asarray(stats["metrics"])
                if mets.size:
                    per_metric = _epoch_mean(stats, "metrics")
                    for i, name in enumerate(metrics):
                        key = name if isinstance(name, str) else getattr(name, "__name__", f"metric_{i}")
                        scalars[key] = float(per_metric[i])
                scalar_log.log(epoch_idx, **scalars)
                if telemetry.enabled():
                    telemetry.metrics.to_scalar_logger(scalar_log, epoch_idx)
            if summary is not None and watchdog is not None:
                # after logging so a halting epoch still reaches the logs;
                # raises TrainingDiverged under the halt policy
                watchdog.observe(epoch_idx, summary)
            return stats

        epoch_stats: List[dict] = []
        self.record_training_start()
        # try/finally so the scalar logger and profiler release their file
        # handles / capture session even when an epoch raises (previously a
        # failed epoch leaked the ScalarLogger's writer)
        try:
            if self.streaming and commit_schedule is not None:
                raise ValueError(
                    "streaming=True is incompatible with commit_schedule: the "
                    "staleness simulation scans the whole epoch in one program"
                )
            if self.dispatch_epochs > 1:
                if self.streaming:
                    raise ValueError(
                        "dispatch_epochs>1 needs the whole epoch on device; "
                        "streaming=True feeds it window by window"
                    )
                if commit_schedule is not None:
                    raise ValueError(
                        "dispatch_epochs>1 is incompatible with commit_schedule "
                        "(the staleness simulation dispatches per epoch)"
                    )
                state, epoch_stats = self._train_chunked(
                    engine, state, feats, labels, num_workers, window, shuffle,
                    ckpt, start_epoch, _materialise,
                )
                # all epochs consumed; the per-epoch loop below runs zero times
                start_epoch = self.num_epoch
            stream_window = window
            if self.streaming and window is None:
                # No-commit trainers (SingleTrainer/Ensemble) have no natural
                # window; stream in fixed blocks with a ragged tail
                # (pad_to_window=False below), so the step count — and therefore
                # the trajectory — matches the in-memory path exactly.  The tail
                # costs one extra compile; forcing divisor-sized blocks instead
                # could degenerate to 1-step dispatches on prime step counts.
                from distkeras_tpu.data import plan_epoch

                steps = plan_epoch(len(feats), num_workers, self.batch_size, 1)[0]
                stream_window = min(steps, 32)
            for epoch in range(start_epoch, self.num_epoch):
                if _chaos.enabled():
                    _chaos.fault("epoch")  # seeded kill entering this epoch
                if prof is not None:
                    prof.on_step(epoch)
                with telemetry.trace.span("epoch", epoch=epoch):
                    if self.streaming:
                        from distkeras_tpu.data import epoch_window_iter, plan_epoch

                        if window is not None:
                            total_windows = plan_epoch(
                                len(feats), num_workers, self.batch_size, window)[0]
                        else:
                            steps = plan_epoch(
                                len(feats), num_workers, self.batch_size, 1)[0]
                            total_windows = -(-steps // stream_window)
                        start_block = 0
                        if resume_data is not None and epoch == start_epoch:
                            start_block = min(
                                int(resume_data.block_cursor), total_windows)
                        # bit state BEFORE this epoch's shuffle — what a
                        # mid-epoch DataState must carry (the window iterator
                        # is lazy: the shuffle is drawn at its first next())
                        rng_bits = rng.bit_generator.state if shuffle else None
                        blocks = epoch_window_iter(
                            feats, labels, num_workers, self.batch_size, stream_window,
                            rng=rng if shuffle else None,
                            pad_to_window=window is not None,
                            feature_dtype=self.compute_dtype,
                            start_block=start_block,
                        )
                        if self.prefetch > 0:
                            from distkeras_tpu.datapipe import PrefetchRing

                            blocks = PrefetchRing(
                                blocks, depth=self.prefetch,
                                put_fn=engine.stream_put,
                            )
                        if _chaos.enabled():
                            # seeded kill/stall at a block index, downstream
                            # of the prefetch ring so the fault reaches the
                            # consumer directly (host-side only — the jitted
                            # program is untouched)
                            blocks = _chaos.wrap_blocks(blocks)
                        on_window = None
                        if ckpt is not None and self.checkpoint_blocks:
                            from distkeras_tpu.datapipe import DataState

                            def on_window(live_state, done, _epoch=epoch,
                                          _base=start_block, _bits=rng_bits,
                                          _total=total_windows):
                                # ``done`` windows consumed this run; the
                                # live epoch counter reads _epoch + done
                                # (run_epoch_streaming's end-of-epoch fixup
                                # hasn't happened yet), so rewind it to the
                                # epoch being trained.  Skip the final block
                                # — the epoch-boundary save supersedes it.
                                cursor = _base + done
                                if done % self.checkpoint_blocks or cursor >= _total:
                                    return
                                ckpt.save_partial(
                                    live_state.replace(
                                        epoch=live_state.epoch - done),
                                    _epoch,
                                    DataState(epoch=_epoch, block_cursor=cursor,
                                              rng_state=_bits),
                                )

                        run_one = (
                            lambda blocks=blocks, on_window=on_window:
                            engine.run_epoch_streaming(
                                state, blocks, on_window=on_window))
                    else:
                        if window is None:
                            # single window spanning the whole epoch (no commits)
                            from distkeras_tpu.data import plan_epoch

                            steps = plan_epoch(len(feats), num_workers, self.batch_size, 1)[0]
                            xs, ys = epoch_arrays(
                                feats, labels, num_workers, self.batch_size, steps,
                                rng=rng if shuffle else None,
                            )
                        else:
                            xs, ys = epoch_arrays(
                                feats, labels, num_workers, self.batch_size, window,
                                stepwise=commit_schedule is not None,
                                rng=rng if shuffle else None,
                            )
                        xs, ys = engine.shard_batches(xs, ys)
                        run_one = lambda xs=xs, ys=ys: engine.run_epoch(state, xs, ys)
                    # Trace the second epoch (the first includes compilation),
                    # or the only epoch when there is just one.
                    if self.profile_dir and epoch == min(start_epoch + 1, self.num_epoch - 1):
                        with jax.profiler.trace(self.profile_dir):
                            state, stats = run_one()
                            jax.block_until_ready(state.center_params)
                    else:
                        state, stats = run_one()
                    ps = getattr(self, "parameter_server", None)
                    if ps is not None:
                        # live PS observability: copy the commit counter off
                        # this epoch's state before the next dispatch donates it
                        ps.track(getattr(state, "center_rule", None))
                    # keep the current epoch's stats as device arrays: dispatch
                    # is async, so the next epoch's host-side batching overlaps
                    # this epoch's device compute.  Materialise the previous
                    # epoch's stats now (its compute is long done) so retention
                    # stays O(1).
                    if epoch_stats and not isinstance(
                            jax.tree.leaves(epoch_stats[-1])[0], np.ndarray):
                        epoch_stats[-1] = _materialise(epoch_stats[-1], epoch - 1)
                    epoch_stats.append(stats)
                    if watchdog is not None:
                        # an armed watchdog trades the one-epoch async
                        # overlap for prompt detection: materialise (and
                        # observe) the epoch that just ran instead of
                        # deferring it to the next iteration
                        epoch_stats[-1] = _materialise(stats, epoch)
                        if watchdog.pending_rollback:
                            state = self._watchdog_rollback(
                                engine, ckpt, state, watchdog)
                            continue  # don't checkpoint the diverged state
                    if ckpt is not None:
                        # epoch-boundary DataState: cursor 0 at the next
                        # epoch, RNG bits as they stand now (= before the
                        # next epoch's shuffle) — resume restores the exact
                        # bit state instead of replaying permutations
                        from distkeras_tpu.datapipe import DataState

                        ckpt.maybe_save(state, epoch, data_state=DataState(
                            epoch=epoch + 1, block_cursor=0,
                            rng_state=(rng.bit_generator.state
                                       if shuffle else None),
                        ))
                    if staleness_policy is not None:
                        # adapt the staleness bound from THIS epoch's summary
                        # (costs the one-epoch async overlap, same trade the
                        # watchdog makes)
                        if epoch_stats and not isinstance(
                                jax.tree.leaves(epoch_stats[-1])[0],
                                np.ndarray):
                            epoch_stats[-1] = _materialise(
                                epoch_stats[-1], epoch)
                        summary = last_summary.get("value")
                        if summary is not None:
                            state = self._apply_staleness_bound(
                                staleness_policy, summary, state)
                    if _fleet.preemption_requested():
                        # SIGTERM arrived: leave a boundary checkpoint for
                        # whoever resumes, then exit loudly instead of dying
                        # mid-step on the follow-up SIGKILL
                        if ckpt is not None:
                            if (epoch + 1) % self.checkpoint_every:
                                from distkeras_tpu.datapipe import DataState

                                ckpt.save_partial(state, epoch, DataState(
                                    epoch=epoch + 1, block_cursor=0,
                                    rng_state=(rng.bit_generator.state
                                               if shuffle else None)))
                            ckpt.wait()
                        raise _fleet.Preempted(
                            f"preempted (SIGTERM); drained to the epoch "
                            f"{epoch + 1} boundary"
                            + (" checkpoint" if ckpt is not None else ""))
                    if elastic_ctl is not None and epoch + 1 < self.num_epoch:
                        desired = elastic_ctl.poll()
                        if desired and desired != num_workers:
                            engine, state = self._elastic_resize(
                                build_engine, engine, state, ckpt, epoch,
                                rng, shuffle, desired)
                            num_workers = desired
                            resume_data = None
            if epoch_stats and not isinstance(
                    jax.tree.leaves(epoch_stats[-1])[0], np.ndarray):
                epoch_stats[-1] = _materialise(epoch_stats[-1], self.num_epoch - 1)
            if ckpt is not None:
                ckpt.wait()  # flush in-flight async saves before declaring done
        finally:
            if prof is not None:
                prof.close()
            if scalar_log is not None:
                scalar_log.close()
        if average_at_end:
            state, _ = engine.average_workers(state)

        losses_per_epoch = [float(_epoch_mean(s, "loss")) for s in epoch_stats]
        metrics_per_epoch = [
            _epoch_mean(s, "metrics") for s in epoch_stats
            if np.asarray(s["metrics"]).size
        ]
        self.record_training_stop()

        self.history = {"loss": losses_per_epoch, "training_time": self.get_training_time()}
        for i, name in enumerate(metrics):
            if metrics_per_epoch:
                key = name if isinstance(name, str) else getattr(name, "__name__", f"metric_{i}")
                self.history[key] = [float(m[i]) for m in metrics_per_epoch]
        if telemetry.enabled():
            tt = self.get_training_time()
            telemetry.metrics.gauge(
                "training_seconds", help="wall seconds of the last fit"
            ).set(tt)
            if tt > 0 and epoch_stats:
                telemetry.metrics.gauge(
                    "samples_per_sec_per_chip",
                    help="trained samples per second per device (last fit)",
                ).set(len(epoch_stats) * len(feats) / tt
                      / int(engine.mesh.devices.size))
            # one file pair per process under DISTKERAS_TELEMETRY[_DIR]:
            # the Chrome trace (open in Perfetto) and a metrics snapshot
            telemetry.flush()
        if sanitizer.enabled() and not sanitizer.strict():
            # record mode: per-violation warnings fire once per guard kind,
            # so close the fit with the full tally — the operator's cue to
            # re-run strict (or dklint) before this reaches a TPU pod
            recorded = sanitizer.violations()
            if recorded:
                kinds = sorted({k for k, _ in recorded})
                warnings.warn(
                    f"sanitizer recorded {len(recorded)} violation(s) during "
                    f"this fit ({', '.join(kinds)} guard"
                    f"{'s' if len(kinds) > 1 else ''}); see the sanitizer_* "
                    "counters, or run with DISTKERAS_SANITIZE=strict to fail "
                    "at the offending dispatch",
                    RuntimeWarning,
                )
        return engine, state, adapter

    def _train_chunked(
        self, engine, state, feats, labels, num_workers, window,
        shuffle, ckpt, start_epoch, _materialise,
    ):
        """The ``dispatch_epochs>1`` epoch loop: up to ``dispatch_epochs``
        epochs per device dispatch via :meth:`WindowedEngine.run_epochs`,
        reshuffling ON DEVICE between epochs when ``shuffle`` is set.

        Chunks never straddle a ``checkpoint_every`` boundary, so the set of
        checkpointed epochs is identical to the per-epoch loop's.  Returns
        ``(state, epoch_stats)`` with every epoch's stats but the last
        already materialised — the caller's trailing ``_materialise`` call
        finishes the last one, same invariant as the per-epoch loop.
        """
        from distkeras_tpu.data import plan_epoch

        if window is None:
            steps = plan_epoch(len(feats), num_workers, self.batch_size, 1)[0]
            xs, ys = epoch_arrays(feats, labels, num_workers, self.batch_size, steps)
        else:
            xs, ys = epoch_arrays(feats, labels, num_workers, self.batch_size, window)
        xs, ys = engine.shard_batches(xs, ys)
        shuffle_seed = self.seed if shuffle else None

        def split(stats, chunk):
            """Chunk stats -> per-epoch dicts (leaves keep [n_windows, ...])."""
            out = []
            for e in range(chunk):
                out.append(jax.tree.map(
                    lambda a: a.reshape((chunk, a.shape[0] // chunk) + a.shape[1:])[e],
                    stats,
                ))
            return out

        epoch_stats: List[dict] = []
        epoch = start_epoch
        chunk_idx = 0
        first_chunk_size = None
        while epoch < self.num_epoch:
            chunk = min(self.dispatch_epochs, self.num_epoch - epoch)
            if ckpt is not None:
                chunk = min(chunk, self.checkpoint_every - epoch % self.checkpoint_every)
            if first_chunk_size is None:
                first_chunk_size = chunk
            # Trace the second chunk — but only if it reuses the first
            # chunk's compiled program (same chunk size); a differently-sized
            # tail chunk would trace a fresh XLA compile, not steady state.
            # With a single chunk, trace it (compile included — better than
            # nothing, and the per-epoch loop has the same property at
            # num_epoch == 1).
            last_chunk = epoch + chunk >= self.num_epoch
            # "epoch" span per chunk dispatch (attrs carry how many epochs it
            # covers) so chunked runs keep the epoch→window→commit nesting
            with telemetry.trace.span("epoch", epoch=epoch, epochs=chunk):
                if self.profile_dir and (
                    (chunk_idx == 1 and chunk == first_chunk_size)
                    or (chunk_idx == 0 and last_chunk)
                ):
                    with jax.profiler.trace(self.profile_dir):
                        state, stats = engine.run_epochs(
                            state, xs, ys, chunk, shuffle_seed=shuffle_seed)
                        jax.block_until_ready(state.center_params)
                else:
                    state, stats = engine.run_epochs(
                        state, xs, ys, chunk, shuffle_seed=shuffle_seed)
            # Same O(1)-retention scheme as the per-epoch loop: materialise
            # the previous chunk's stats (long computed) while this chunk's
            # stay device-resident.
            for i, s in enumerate(epoch_stats):
                if not isinstance(jax.tree.leaves(s)[0], np.ndarray):
                    epoch_stats[i] = _materialise(s, i + start_epoch)
            epoch_stats.extend(split(stats, chunk))
            epoch += chunk
            chunk_idx += 1
            if ckpt is not None:
                ckpt.maybe_save(state, epoch - 1)
        for i, s in enumerate(epoch_stats[:-1]):
            if not isinstance(jax.tree.leaves(s)[0], np.ndarray):
                epoch_stats[i] = _materialise(s, i + start_epoch)
        return state, epoch_stats

    def _finalize(self, engine: WindowedEngine, state, adapter: ModelAdapter, use_center: bool = True):
        """Materialise the trained model in the same type the user passed in."""
        if use_center:
            params = jax.tree.map(np.asarray, engine.gather_center(state))
        else:
            params = engine.worker_slice(state.local_params, 0)
        model_state = jax.tree.map(np.asarray, engine.final_model_state(state))
        adapter = _serving_twin(adapter)
        if hasattr(adapter, "assign"):  # Keras path: mutate + return the Keras model
            return adapter.assign(params, model_state)
        return TrainedModel(adapter, params, model_state, history=self.history)

    def train(self, dataframe: DataFrame, shuffle: bool = False):
        raise NotImplementedError


class SingleTrainer(Trainer):
    """Single-worker baseline (reference parity: ``SingleTrainer`` — coalesce
    to one partition, run a SequentialWorker)."""

    def train(self, dataframe: DataFrame, shuffle: bool = False):
        worker = workers_mod.SequentialWorker(self.worker_optimizer, self.batch_size)
        engine, state, adapter = self._fit(
            dataframe, worker.rule, num_workers=1, shuffle=shuffle
        )
        return self._finalize(engine, state, adapter, use_center=False)


class AveragingTrainer(Trainer):
    """Synchronous one-shot weight averaging (reference parity:
    ``AveragingTrainer.average_models``): N independent replicas, averaged once
    at the end via a single ``pmean`` over the mesh."""

    def __init__(self, *args, num_workers: int = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.num_workers = num_workers or jax.device_count()

    def train(self, dataframe: DataFrame, shuffle: bool = False):
        worker = workers_mod.AveragingWorker(self.worker_optimizer, self.batch_size)
        engine, state, adapter = self._fit(
            dataframe, worker.rule, self.num_workers, shuffle=shuffle, average_at_end=True
        )
        return self._finalize(engine, state, adapter, use_center=True)


class EnsembleTrainer(Trainer):
    """Train N independent models, return all of them (reference parity:
    ``EnsembleTrainer``)."""

    def __init__(self, *args, num_models: int = 2, **kwargs):
        super().__init__(*args, **kwargs)
        self.num_models = num_models

    def train(self, dataframe: DataFrame, shuffle: bool = False) -> List:
        worker = workers_mod.SequentialWorker(self.worker_optimizer, self.batch_size)
        engine, state, adapter = self._fit(
            dataframe, worker.rule, self.num_models, shuffle=shuffle
        )
        adapter = _serving_twin(adapter)
        if hasattr(adapter, "assign"):
            # Keras in -> Keras models out (reference parity: the reference's
            # EnsembleTrainer returned N deserialised Keras models).  One
            # independent clone per ensemble member, each carrying its own
            # worker's weights — adapter.assign would mutate the single
            # shared wrapped model N times, leaving N handles to the last
            # worker's weights.
            import keras

            from distkeras_tpu.models.keras_adapter import assign_keras_weights

            models = []
            for i in range(self.num_models):
                params_i = engine.worker_slice(state.local_params, i)
                state_i = engine.worker_slice(state.model_state, i)
                clone = keras.models.clone_model(adapter.model)
                if not clone.built:
                    clone.build(adapter.model.input_shape)
                assign_keras_weights(clone, params_i, state_i.get("ntv"))
                models.append(clone)
            return models
        model_state = jax.tree.map(np.asarray, engine.final_model_state(state))
        return [
            TrainedModel(adapter, engine.worker_slice(state.local_params, i),
                         model_state, history=self.history)
            for i in range(self.num_models)
        ]


class DistributedTrainer(Trainer):
    """Parameter-server training base (reference parity: ``DistributedTrainer``).

    Owns the PS lifecycle (`service`/`stop_service` are retained as no-op-ish
    facades over the on-device center variable) and the worker allocation
    hook; subclasses pick the algorithm.
    """

    parameter_server_class = DeltaParameterServer

    def __init__(
        self,
        keras_model: Any,
        loss: Any = "categorical_crossentropy",
        worker_optimizer: Any = "sgd",
        metrics: Sequence = ("accuracy",),
        num_workers: Optional[int] = None,
        batch_size: int = 32,
        features_col: str = "features",
        label_col: str = "label",
        num_epoch: int = 1,
        master_port: int = 5000,
        seed: int = 0,
        compute_dtype: Any = None,
        commit_schedule: Optional[Sequence[int]] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 1,
        resume: bool = False,
        profile_dir: Optional[str] = None,
        seq_shards: int = 1,
        tp_shards: int = 1,
        fsdp: bool = False,
        tensorboard_dir: Optional[str] = None,
        streaming: bool = False,
        remat: bool = False,
        unroll=1,
        dispatch_epochs: int = 1,
        pipeline_stages: int = 1,
        pp_microbatches: Optional[int] = None,
        tp_spec_fn: Optional[Any] = None,
        prefetch: int = 0,
        checkpoint_blocks: int = 0,
        elastic: Optional[Any] = None,
        staleness_policy: Optional[Any] = None,
    ):
        super().__init__(
            keras_model, loss, worker_optimizer, metrics,
            features_col, label_col, batch_size, num_epoch, seed, compute_dtype,
            checkpoint_dir, checkpoint_every, resume, profile_dir, seq_shards,
            tp_shards, fsdp, tensorboard_dir, streaming, remat, unroll,
            dispatch_epochs, pipeline_stages, pp_microbatches, tp_spec_fn,
            prefetch, checkpoint_blocks,
        )
        self.num_workers = num_workers or jax.device_count()
        self.master_port = master_port
        #: fleet.ElasticMembership (or any object with ``poll() -> int|None``)
        #: — polled at epoch boundaries to resize the worker set mid-run
        self.elastic = elastic
        #: adaptive.AdaptiveBound (or any ``observe(summary) -> float``) —
        #: retunes an AdaptiveDynSGD rule's staleness bound between epochs
        self.staleness_policy = staleness_policy
        self.parameter_server: Optional[ParameterServer] = None
        # Optional per-worker commit periods: the deterministic staleness
        # simulation (SURVEY.md §7 "asynchrony semantics on SPMD hardware").
        self.commit_schedule = (
            None if commit_schedule is None else np.asarray(commit_schedule, np.int32)
        )

    def allocate_worker(self) -> workers_mod.Worker:
        raise NotImplementedError

    def allocate_parameter_server(self) -> ParameterServer:
        return self.parameter_server_class(self.master_model, self.master_port)

    def service(self) -> None:
        """Reference parity: started the PS thread.  Here the center variable
        is created on-device by the engine; this just builds the facade."""
        self.parameter_server = self.allocate_parameter_server()
        self.parameter_server.start()

    def stop_service(self) -> None:
        if self.parameter_server is not None:
            self.parameter_server.stop()

    @property
    def num_updates(self) -> int:
        return self.parameter_server.num_updates if self.parameter_server else 0

    def train_with_recovery(self, dataframe: DataFrame, shuffle: bool = False,
                            max_retries: int = 2, backoff_base: float = 0.5,
                            backoff_cap: float = 30.0):
        """Failure-tolerant training (SURVEY.md §5.3).

        The reference leaned on Spark task retries (a retried worker
        reconnects to the PS and keeps training); a JAX SPMD program instead
        fails as a unit, so the recovery unit is the epoch: on an exception
        the trainer reloads the latest checkpoint and resumes.  Requires
        ``checkpoint_dir``; each retry restarts from the last completed
        checkpointed epoch (bit-exact — see test_checkpoint).

        Retries are reserved for transient failures: a retry happens only if
        a checkpoint exists to restore from, and never for the same exception
        signature twice in a row — a deterministic bug (shape error, OOM)
        surfaces immediately instead of being re-run ``max_retries`` times.
        Retries back off exponentially (``backoff_base * 2^k`` capped at
        ``backoff_cap``, x0.5–1.0 jitter) so a fleet of recovering workers
        doesn't stampede the shared checkpoint store, and a SIGTERM
        preemption (:class:`distkeras_tpu.fleet.Preempted`) is never
        retried — the boundary checkpoint is on disk and the process is
        meant to exit.
        """
        if not self.checkpoint_dir:
            raise ValueError("train_with_recovery requires checkpoint_dir")
        from distkeras_tpu.checkpoint import committed_steps, latest_step

        _fleet.install_preemption_handler()
        attempts = 0
        last_failure = None
        last_step = None
        while True:
            try:
                return self.train(dataframe, shuffle)
            except _fleet.Preempted:
                raise  # drained to a boundary checkpoint; exit, don't retry
            except Exception as e:  # noqa: BLE001 — re-raised unless retryable
                failure = (type(e), str(e))
                try:
                    step = latest_step(self.checkpoint_dir)
                except Exception:  # noqa: BLE001 — see below
                    # latest_step flushes in-flight async saves, so a save
                    # that failed in the background re-raises HERE — it
                    # must not mask the training error we're handling or
                    # bypass the retry.  Fall back to the committed
                    # directory listing (final step_ names only appear
                    # after commit, so no flush is needed for those).
                    on_disk = committed_steps(self.checkpoint_dir)
                    step = on_disk[-1] if on_disk else None
                if step != last_step:
                    # checkpointed progress since the previous failure: a
                    # repeating signature is a recurring *transient* (e.g.
                    # periodic preemption), not a deterministic bug
                    last_failure = None
                attempts += 1
                if attempts > max_retries or failure == last_failure or step is None:
                    raise
                last_failure = failure
                last_step = step
                self.resume = True  # pick up from the latest checkpoint
                if backoff_base > 0:
                    import random as _random

                    delay = min(backoff_cap,
                                backoff_base * (2 ** (attempts - 1)))
                    time.sleep(delay * (0.5 + 0.5 * _random.random()))

    @property
    def _logical_workers(self) -> int:
        """Logical worker count; AsynchronousDistributedTrainer multiplies by
        ``parallelism_factor`` (the reference's Spark over-partitioning),
        realised here as virtual workers per device."""
        return self.num_workers * getattr(self, "parallelism_factor", 1)

    def train(self, dataframe: DataFrame, shuffle: bool = False):
        worker = self.allocate_worker()
        self.service()
        engine, state, adapter = self._fit(
            dataframe, worker.rule, self._logical_workers, shuffle=shuffle,
            commit_schedule=self.commit_schedule,
        )
        self.parameter_server.attach(
            engine.gather_center(state), jax.tree.map(np.asarray, state.center_rule),
        )
        self.stop_service()
        model = self._finalize(engine, state, adapter, use_center=True)
        self.parameter_server.model = model
        return model


class AsynchronousDistributedTrainer(DistributedTrainer):
    """Reference parity: adds ``parallelism_factor`` (Spark over-partitioning
    so stragglers overlap).  On a synchronous mesh there are no stragglers; the
    knob is kept for API compat and maps onto the staleness simulation."""

    def __init__(self, *args, parallelism_factor: int = 1, **kwargs):
        super().__init__(*args, **kwargs)
        self.parallelism_factor = parallelism_factor


class DOWNPOUR(AsynchronousDistributedTrainer):
    """Downpour SGD (Dean et al. 2012) — windowed delta commits."""

    def __init__(self, *args, communication_window: int = 5, **kwargs):
        super().__init__(*args, **kwargs)
        self.communication_window = communication_window

    def allocate_worker(self):
        return workers_mod.DOWNPOURWorker(
            self.worker_optimizer, self.batch_size, self.features_col,
            self.label_col, self.communication_window,
        )


class AEASGD(AsynchronousDistributedTrainer):
    """Asynchronous Elastic Averaging SGD (Zhang et al. 2015)."""

    def __init__(self, *args, communication_window: int = 32, rho: float = 5.0,
                 learning_rate: float = 0.1, **kwargs):
        super().__init__(*args, **kwargs)
        self.communication_window = communication_window
        self.rho = rho
        self.learning_rate = learning_rate

    def allocate_worker(self):
        return workers_mod.AEASGDWorker(
            self.worker_optimizer, self.batch_size, self.features_col, self.label_col,
            self.communication_window, self.rho, self.learning_rate,
        )


class EAMSGD(AsynchronousDistributedTrainer):
    """Elastic Averaging with (Nesterov) momentum (Zhang et al. 2015)."""

    def __init__(self, *args, communication_window: int = 32, rho: float = 5.0,
                 learning_rate: float = 0.1, momentum: float = 0.9, **kwargs):
        # Default worker_optimizer to None (=> Nesterov momentum SGD via
        # _effective_worker_optimizer) ONLY when the caller didn't pass one —
        # positionally (reference style: EAMSGD(model, loss, "sgd")) or by
        # keyword.  args[2] is worker_optimizer in the Trainer signature.
        if len(args) < 3 and "worker_optimizer" not in kwargs:
            kwargs["worker_optimizer"] = None
        super().__init__(*args, **kwargs)
        self.communication_window = communication_window
        self.rho = rho
        self.learning_rate = learning_rate
        self.momentum = momentum

    def _effective_worker_optimizer(self):
        # default worker optimizer = Nesterov momentum SGD (the reference's
        # explicit velocity update on the local variable), resolved fresh per
        # train() call so changed learning_rate/momentum take effect on retrain
        if self.worker_optimizer is not None:
            return self.worker_optimizer
        return (
            "sgd",
            {"learning_rate": self.learning_rate, "momentum": self.momentum, "nesterov": True},
        )

    def allocate_worker(self):
        return workers_mod.EAMSGDWorker(
            self._effective_worker_optimizer(), self.batch_size, self.features_col,
            self.label_col, self.communication_window, self.rho, self.learning_rate,
            self.momentum,
        )


class ADAG(AsynchronousDistributedTrainer):
    """Accumulated-Gradient Normalisation (Hermans, arXiv:1710.02368)."""

    parameter_server_class = ADAGParameterServer

    def __init__(self, *args, communication_window: int = 12, **kwargs):
        super().__init__(*args, **kwargs)
        self.communication_window = communication_window

    def allocate_worker(self):
        return workers_mod.ADAGWorker(
            self.worker_optimizer, self.batch_size, self.features_col,
            self.label_col, self.communication_window,
        )


class DynSGD(AsynchronousDistributedTrainer):
    """Staleness-aware dynamic-LR SGD (SIGMOD'17 rule)."""

    parameter_server_class = DynSGDParameterServer

    def __init__(self, *args, communication_window: int = 5, **kwargs):
        super().__init__(*args, **kwargs)
        self.communication_window = communication_window

    def allocate_worker(self):
        return workers_mod.DynSGDWorker(
            self.worker_optimizer, self.batch_size, self.features_col,
            self.label_col, self.communication_window,
        )


class AdaptiveDynSGD(DynSGD):
    """DynSGD with an SSP-style staleness bound carried in the center state
    (beyond reference; ABS arXiv:2301.08895 / DynSSP arXiv:1908.11848).

    Pass ``staleness_policy=AdaptiveBound(...)`` to retune the bound online
    between epochs from the dynamics telemetry (needs
    ``DISTKERAS_DYNAMICS=1``); with the default ``inf`` bound and no policy
    the trajectory is bit-for-bit DynSGD."""

    def __init__(self, *args, communication_window: int = 5,
                 initial_bound: float = float("inf"), **kwargs):
        super().__init__(*args, communication_window=communication_window,
                         **kwargs)
        self.initial_bound = initial_bound

    def allocate_worker(self):
        return workers_mod.AdaptiveDynSGDWorker(
            self.worker_optimizer, self.batch_size, self.features_col,
            self.label_col, self.communication_window, self.initial_bound,
        )
