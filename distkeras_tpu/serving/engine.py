"""Continuous-batching serving engine: one jitted decode step, forever.

The generation story before this module was *call-shaped*:
``greedy_generate`` compiles one program per ``(model, steps)`` and runs a
whole batch in lockstep — every sequence starts together, finishes together,
and the program is torn through per call.  An online service sees none of
that structure: requests arrive whenever, want different lengths and
sampling, and must not pay a compile.  This engine is the standard
continuous-batching formulation (Orca/vLLM):

* a fixed ring of ``num_slots`` **batch slots**;
* ONE jitted single-token **decode step** over all slots, compiled once at
  construction — every per-request quantity (position, last token, RNG key,
  temperature/top-k/top-p, active flag) is *data*, so admitting or retiring
  a request never retraces (dklint DK102);
* a **paged KV cache** (:mod:`distkeras_tpu.serving.cache`): K/V pools
  shared by all slots, per-slot page tables, pages allocated at admission
  and freed at retirement;
* between decode steps the host loop **admits** queued requests into free
  slots (prefill) and **retires** finished ones (EOS / max-new-tokens), so
  a long request never convoys short ones;
* SLO metrics through the telemetry registry — TTFT and per-token-latency
  histograms, queue depth, token/request counters — visible on the
  flightdeck ``/metrics`` scrape.

Numerics: the engine re-runs the model's own flax submodules
(``nn.LayerNorm`` / ``nn.DenseGeneral`` / ``nn.Dense`` / the
``_decode_attention`` masking math) over param subtrees sliced out by the
model's ``decode_spec`` hook, so greedy requests emit tokens **bitwise
identical** to ``greedy_generate`` (tests/test_serving.py pins this under
staggered concurrent arrival).  Prefill pads the prompt to the slot's full
page capacity — positions past the prompt are causally masked and their
cache rows are overwritten by decode before ever becoming visible, so
padding changes nothing but FLOPs.  (A production build would bucket
prefill widths; one width keeps this engine at exactly two programs.)

RNG: each request carries its own ``PRNGKey(seed)`` chain, split once per
token *of that request* — sampled output is a function of (params, prompt,
knobs, seed) alone, independent of whatever else shares the batch.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.sanitizer import lockwatch
from distkeras_tpu.serving.cache import PagedKVCache
from distkeras_tpu.serving.frontend import (
    GenerateRequest,
    GenerateResult,
    RequestQueue,
)
from distkeras_tpu.serving.sampling import sample_one, sample_tokens

__all__ = ["ServingEngine", "serving_metrics"]


def serving_metrics(registry=None) -> dict:
    """Get-or-create the engine's SLO instruments on ``registry`` (default:
    the process-global one).  One canonical home for the names/help text so
    the engine, the golden test, and the CI smoke assert the same schema."""
    if registry is None:
        from distkeras_tpu.telemetry.metrics import metrics as registry
    return {
        "ttft": registry.histogram(
            "serving_ttft_seconds",
            help="time from request admission-queue entry to first token",
        ),
        "token_latency": registry.histogram(
            "serving_token_latency_seconds",
            help="wall time of one continuous-batching decode step",
        ),
        "queue_depth": registry.gauge(
            "serving_queue_depth", help="requests waiting for a batch slot"
        ),
        "active_slots": registry.gauge(
            "serving_active_slots", help="batch slots generating right now"
        ),
        "pages_in_use": registry.gauge(
            "serving_kv_pages_in_use", help="allocated KV cache pages"
        ),
        "tokens": registry.counter(
            "serving_tokens_total", help="tokens generated across all requests"
        ),
        "requests": registry.counter(
            "serving_requests_total", help="requests completed (any finish reason)"
        ),
        "rejected": registry.counter(
            "serving_requests_rejected_total",
            help="requests shed by queue backpressure",
        ),
    }


# ------------------------------------------------------------ model slicing


@dataclasses.dataclass
class _Spec:
    """Normalized decode view of one causal LM: embedding tables, per-block
    param subtrees, final LN + head, and the static config the step
    functions close over.  Built from the model's ``decode_spec`` hook."""

    tok: Any
    pos: Any
    blocks: List[Any]
    final_ln: Any
    head: Any
    dim: int
    heads: int
    head_dim: int
    max_len: int
    vocab: int
    ln_eps: float

    def params(self) -> dict:
        """The pytree passed (not closed over) to the jitted steps, so big
        leaves ride as runtime buffers rather than baked constants."""
        return {
            "tok": self.tok, "pos": self.pos, "blocks": list(self.blocks),
            "final_ln": self.final_ln, "head": self.head,
        }


def _resolve_spec(model, params) -> _Spec:
    """Accept a ``TrainedModel``, a ``FlaxModel`` adapter + params, or a raw
    module/adapter with a ``decode_spec`` hook + params."""
    from distkeras_tpu.models.adapter import FlaxModel, TrainedModel

    if isinstance(model, TrainedModel):
        return _resolve_spec(model.adapter, model.params)
    if isinstance(model, FlaxModel):
        model = model.module
    hook = getattr(model, "decode_spec", None)
    if hook is None:
        raise TypeError(
            f"{type(model).__name__} has no decode_spec hook; serving "
            "supports TransformerLM and StagedLM"
        )
    if params is None:
        raise ValueError(
            "params required when passing a bare module/adapter "
            "(a TrainedModel carries its own)"
        )
    raw = hook(params)
    cfg = raw["config"]
    qkv = raw["blocks"][0]["_SelfAttention_0"]["qkv"]["kernel"]
    return _Spec(
        tok=jnp.asarray(raw["embed"]["tok"]),
        pos=jnp.asarray(raw["embed"]["pos"]),
        blocks=list(raw["blocks"]),
        final_ln=raw["final_ln"],
        head=raw["head"],
        dim=int(cfg["dim"]),
        heads=int(qkv.shape[-2]),
        head_dim=int(qkv.shape[-1]),
        max_len=int(cfg["max_len"]),
        vocab=int(cfg["vocab_size"]),
        ln_eps=float(cfg["ln_eps"]),
    )


def _block_apply(bp, x, attend, eps):
    """One encoder block over param subtree ``bp``, reusing the model's own
    flax submodules so the math is bit-identical to training/`generate`.
    ``attend(q, k, v)`` supplies the paged-cache attention."""
    ap = bp["_SelfAttention_0"]
    dim = bp["Dense_1"]["kernel"].shape[-1]
    mlp = bp["Dense_0"]["kernel"].shape[-1]
    heads, head_dim = ap["qkv"]["kernel"].shape[-2:]
    h = nn.LayerNorm(epsilon=eps).apply({"params": bp["LayerNorm_0"]}, x)
    qkv = nn.DenseGeneral((3, heads, head_dim)).apply({"params": ap["qkv"]}, h)
    q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
    out = attend(q, k, v)
    h = nn.DenseGeneral(dim, axis=(-2, -1)).apply({"params": ap["proj"]}, out)
    x = x + h
    h = nn.LayerNorm(epsilon=eps).apply({"params": bp["LayerNorm_1"]}, x)
    h = nn.Dense(mlp).apply({"params": bp["Dense_0"]}, h)
    h = nn.gelu(h)
    h = nn.Dense(dim).apply({"params": bp["Dense_1"]}, h)
    return x + h


def _head_apply(final_ln, head, x, eps):
    h = nn.LayerNorm(epsilon=eps).apply({"params": final_ln}, x)
    return nn.Dense(head["kernel"].shape[-1]).apply({"params": head}, h)


# -------------------------------------------------------------- bookkeeping


class _Pending:
    """Handle returned by :meth:`ServingEngine.submit` — resolves to a
    :class:`GenerateResult` when the request retires."""

    __slots__ = ("request", "max_new", "enqueue_t", "_event", "_result")

    def __init__(self, request: GenerateRequest, max_new: int, enqueue_t: float):
        self.request = request
        self.max_new = max_new
        self.enqueue_t = enqueue_t
        self._event = threading.Event()
        self._result: Optional[GenerateResult] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> Optional[GenerateResult]:
        """Block for the result; ``None`` on timeout."""
        if not self._event.wait(timeout):
            return None
        return self._result

    def _resolve(self, result: GenerateResult) -> None:
        self._result = result
        self._event.set()


class _SlotState:
    """Host-side record for one occupied batch slot."""

    __slots__ = ("pending", "tokens", "plen", "ttft_s")

    def __init__(self, pending: _Pending, plen: int):
        self.pending = pending
        self.tokens: List[int] = []
        self.plen = plen
        self.ttft_s = 0.0


# -------------------------------------------------------------------- engine


class ServingEngine:
    """Online inference engine with continuous batching over a paged KV
    cache.  See the module docstring for the design; quick start::

        engine = ServingEngine(trained_model, num_slots=4, page_size=16)
        out = engine.generate([1, 2, 3], max_new_tokens=8)   # blocking
        pending = engine.submit(GenerateRequest(prompt=[1, 2, 3]))  # async
        result = pending.result(timeout=30)
        engine.stop()

    The host loop runs on a daemon thread started lazily by the first
    ``submit``/``generate`` (or explicitly via :meth:`start`).  ``model``
    is a ``TrainedModel``, or a ``TransformerLM``/``StagedLM`` (raw or
    behind ``FlaxModel``) plus ``params``.
    """

    def __init__(self, model, params=None, *, num_slots: int = 4,
                 page_size: int = 16, pages_per_slot: Optional[int] = None,
                 num_pages: Optional[int] = None, queue_size: int = 64,
                 registry=None, dtype=jnp.float32):
        self._spec = _resolve_spec(model, params)
        spec = self._spec
        if pages_per_slot is None:
            pages_per_slot = -(-spec.max_len // page_size)
        self.num_slots = int(num_slots)
        self._cache = PagedKVCache(
            num_layers=len(spec.blocks), num_slots=num_slots,
            page_size=page_size, pages_per_slot=pages_per_slot,
            heads=spec.heads, head_dim=spec.head_dim,
            num_pages=num_pages, dtype=dtype,
        )
        # one prefill width = the slot's whole page capacity (see module doc)
        self._width = self._cache.max_context()
        self._queue = RequestQueue(queue_size)
        self._metrics = serving_metrics(registry)

        s = self.num_slots
        self._slots: List[Optional[_SlotState]] = [None] * s
        self._pos = np.zeros(s, np.int32)        # position of the fed token
        self._last = np.zeros(s, np.int32)       # token being fed this step
        self._keys = np.zeros((s, 2), np.uint32)
        self._temp = np.zeros(s, np.float32)
        self._topk = np.zeros(s, np.int32)
        self._topp = np.ones(s, np.float32)
        self._active = np.zeros(s, bool)

        self._cv = lockwatch.maybe_wrap(threading.Condition(), "serving.engine")
        self._running = False
        self._thread: Optional[threading.Thread] = None

        # Both programs compile exactly once, here — never per request
        # (the retrace pin in tests/test_serving.py counts on it).
        self._prefill = jax.jit(self._build_prefill(), donate_argnums=(1, 2))
        self._decode = jax.jit(self._build_decode(), donate_argnums=(1, 2))

    # ------------------------------------------------------- traced programs

    def _build_prefill(self):
        spec, cache = self._spec, self._cache
        ps, pps, width = cache.page_size, cache.pages_per_slot, self._width
        heads, head_dim, eps = spec.heads, spec.head_dim, spec.ln_eps

        def prefill(params, kpool, vpool, tokens, table, length, key,
                    temp, top_k, top_p):
            # tokens [1, width] right-padded; table [pps]; length traced.
            positions = jnp.clip(jnp.arange(width), 0, spec.max_len - 1)
            x = params["tok"][tokens] + params["pos"][positions][None]
            pools = {"k": kpool, "v": vpool}

            def paged_attend(li):
                def attend(q, k, v):
                    # stash the whole padded chunk into this slot's pages;
                    # rows past `length` land on scratch/overwritten pages
                    # and are causally masked below — never attended.
                    kc = k[0].reshape(pps, ps, heads, head_dim)
                    vc = v[0].reshape(pps, ps, heads, head_dim)
                    pools["k"] = pools["k"].at[li, table].set(kc)
                    pools["v"] = pools["v"].at[li, table].set(vc)
                    # causal attention over the chunk itself (same masking
                    # math as _SelfAttention._decode_attention)
                    qt = jnp.moveaxis(q, 1, 2)
                    kt = jnp.moveaxis(k, 1, 2)
                    vt = jnp.moveaxis(v, 1, 2)
                    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
                    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
                    q_pos = jnp.arange(width)[:, None]
                    k_pos = jnp.arange(width)[None, :]
                    s = jnp.where(k_pos <= q_pos, s, -jnp.inf)
                    out = jnp.einsum(
                        "bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), vt
                    )
                    return jnp.moveaxis(out, 1, 2)

                return attend

            for li, bp in enumerate(params["blocks"]):
                x = _block_apply(bp, x, paged_attend(li), eps)
            logits = _head_apply(params["final_ln"], params["head"], x, eps)
            row = jax.lax.dynamic_index_in_dim(
                logits[0], length - 1, axis=0, keepdims=False
            )
            key, sub = jax.random.split(key)
            tok = sample_one(row, sub, temp, top_k, top_p)
            return pools["k"], pools["v"], tok, key

        return prefill

    def _build_decode(self):
        spec, cache = self._spec, self._cache
        ps, pps = cache.page_size, cache.pages_per_slot
        s, ctx = self.num_slots, self._width
        heads, head_dim, eps = spec.heads, spec.head_dim, spec.ln_eps

        def decode(params, kpool, vpool, tables, pos, last, keys,
                   temp, top_k, top_p, active):
            # One token for every slot.  Inactive slots compute garbage into
            # the scratch page (their tables point at physical page 0) and
            # sample token 0 — all masked out host-side.
            x = params["tok"][last] + params["pos"][
                jnp.clip(pos, 0, spec.max_len - 1)
            ]
            x = x[:, None, :]  # [slots, 1, dim]
            pools = {"k": kpool, "v": vpool}
            slot_ix = jnp.arange(s)
            phys = tables[slot_ix, jnp.clip(pos // ps, 0, pps - 1)]
            off = pos % ps

            def paged_attend(li):
                def attend(q, k, v):
                    pools["k"] = pools["k"].at[li, phys, off].set(k[:, 0])
                    pools["v"] = pools["v"].at[li, phys, off].set(v[:, 0])
                    kg = pools["k"][li][tables].reshape(s, ctx, heads, head_dim)
                    vg = pools["v"][li][tables].reshape(s, ctx, heads, head_dim)
                    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
                    sc = jnp.einsum("shd,skhd->shk", q[:, 0], kg) * scale
                    mask = jnp.arange(ctx)[None, :] <= pos[:, None]
                    sc = jnp.where(mask[:, None, :], sc, -jnp.inf)
                    out = jnp.einsum(
                        "shk,skhd->shd", jax.nn.softmax(sc, axis=-1), vg
                    )
                    return out[:, None]

                return attend

            for li, bp in enumerate(params["blocks"]):
                x = _block_apply(bp, x, paged_attend(li), eps)
            logits = _head_apply(params["final_ln"], params["head"], x, eps)[:, 0]
            split = jax.vmap(jax.random.split)(keys)
            new_keys, subs = split[:, 0], split[:, 1]
            tok = sample_tokens(logits, subs, temp, top_k, top_p)
            tok = jnp.where(active, tok, 0)
            return pools["k"], pools["v"], tok, new_keys

        return decode

    # ----------------------------------------------------------- public API

    def start(self) -> None:
        """Start the host loop thread (idempotent; ``submit`` calls this)."""
        with self._cv:
            if self._running:
                return
            self._running = True
            self._thread = threading.Thread(
                target=self._loop, name="serving-engine", daemon=True
            )
            self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        """Stop the loop; queued and in-flight requests resolve with
        ``finish_reason="aborted"`` (partial tokens included)."""
        with self._cv:
            if not self._running:
                thread = None
            else:
                self._running = False
                thread = self._thread
                self._thread = None
            self._cv.notify_all()
        if thread is not None:
            thread.join(timeout=timeout)
        for slot in range(self.num_slots):
            if self._slots[slot] is not None:
                self._retire(slot, "aborted")
        while True:
            pending = self._queue.pop()
            if pending is None:
                break
            self._finish(pending, [], "aborted", 0.0)
        self._metrics["queue_depth"].set(0)

    def submit(self, request: GenerateRequest) -> _Pending:
        """Validate + enqueue; returns a :class:`_Pending` handle.  Raises
        :class:`~distkeras_tpu.serving.frontend.QueueFull` under
        backpressure and ``ValueError`` for an unservable request."""
        request.validate()
        plen = len(request.prompt)
        if plen > self._width or plen >= self._spec.max_len:
            raise ValueError(
                f"prompt length {plen} exceeds serviceable context "
                f"(width {self._width}, model max_len {self._spec.max_len})"
            )
        if int(np.max(request.prompt)) >= self._spec.vocab:
            raise ValueError("prompt token id out of vocabulary")
        max_new = min(request.max_new_tokens, self._spec.max_len - plen,
                      self._width - plen)
        pending = _Pending(request, max_new, time.perf_counter())
        try:
            self._queue.put(pending)
        except Exception:
            self._metrics["rejected"].inc()
            raise
        self._metrics["queue_depth"].set(len(self._queue))
        self.start()
        with self._cv:
            self._cv.notify_all()
        return pending

    def generate(self, prompt, max_new_tokens: int = 16,
                 timeout: Optional[float] = 60.0,
                 **knobs) -> GenerateResult:
        """Blocking convenience: submit one request, wait for its result.
        ``knobs`` forwards temperature/top_k/top_p/seed/eos_id."""
        req = GenerateRequest(prompt=[int(t) for t in prompt],
                              max_new_tokens=max_new_tokens, **knobs)
        result = self.submit(req).result(timeout=timeout)
        if result is None:
            raise TimeoutError(f"generation did not finish in {timeout}s")
        return result

    def stats(self) -> Dict[str, float]:
        """Host-side snapshot for bench/debug (not the metrics surface)."""
        return {
            "queue_depth": float(len(self._queue)),
            "active_slots": float(int(self._active.sum())),
            "pages_in_use": float(self._cache.pages_in_use),
            "pages_free": float(self._cache.pages_free),
        }

    # ------------------------------------------------------------ host loop

    def _loop(self) -> None:
        while True:
            with self._cv:
                if not self._running:
                    return
            progressed = self._admit()
            progressed = self._decode_once() or progressed
            if not progressed:
                with self._cv:
                    if self._running and len(self._queue) == 0:
                        self._cv.wait(timeout=0.05)

    def _admit(self) -> bool:
        """Move queued requests into free slots (prefill).  FIFO with
        head-of-line blocking: when the page pool can't fit the next
        request yet, it waits for a retirement rather than being skipped —
        no starvation of big requests."""
        admitted = False
        while True:
            free = [i for i, st in enumerate(self._slots) if st is None]
            if not free:
                break
            pending = self._queue.pop()
            if pending is None:
                break
            need = self._cache.pages_needed(
                len(pending.request.prompt) + pending.max_new
            )
            if not self._cache.can_alloc(need):
                self._queue.requeue_front(pending)
                break
            self._prefill_into(free[0], pending, need)
            admitted = True
        self._metrics["queue_depth"].set(len(self._queue))
        return admitted

    def _prefill_into(self, slot: int, pending: _Pending, need: int) -> None:
        req = pending.request
        plen = len(req.prompt)
        self._cache.alloc(slot, need)
        tokens = np.zeros((1, self._width), np.int32)
        tokens[0, :plen] = req.prompt
        kp, vp, tok, key = self._prefill(
            self._spec.params(), self._cache.k_pages, self._cache.v_pages,
            jnp.asarray(tokens), jnp.asarray(self._cache.tables[slot]),
            jnp.int32(plen), jax.random.PRNGKey(req.seed),
            jnp.float32(req.temperature), jnp.int32(req.top_k),
            jnp.float32(req.top_p),
        )
        self._cache.k_pages, self._cache.v_pages = kp, vp
        tok0 = int(np.asarray(tok))
        now = time.perf_counter()

        state = _SlotState(pending, plen)
        state.tokens.append(tok0)
        state.ttft_s = now - pending.enqueue_t
        self._metrics["ttft"].observe(state.ttft_s)
        self._metrics["tokens"].inc()
        self._slots[slot] = state
        self._pos[slot] = plen
        self._last[slot] = tok0
        self._keys[slot] = np.asarray(key)
        self._temp[slot] = req.temperature
        self._topk[slot] = req.top_k
        self._topp[slot] = req.top_p
        self._active[slot] = True
        self._refresh_gauges()

        if req.eos_id is not None and tok0 == req.eos_id:
            self._retire(slot, "eos")
        elif len(state.tokens) >= pending.max_new:
            self._retire(slot, "length")

    def _decode_once(self) -> bool:
        """One continuous-batching decode step over every active slot."""
        if not self._active.any():
            return False
        t0 = time.perf_counter()
        kp, vp, tok, keys = self._decode(
            self._spec.params(), self._cache.k_pages, self._cache.v_pages,
            jnp.asarray(self._cache.tables), jnp.asarray(self._pos),
            jnp.asarray(self._last), jnp.asarray(self._keys),
            jnp.asarray(self._temp), jnp.asarray(self._topk),
            jnp.asarray(self._topp), jnp.asarray(self._active),
        )
        self._cache.k_pages, self._cache.v_pages = kp, vp
        toks = np.asarray(tok)          # device sync: the step is done here
        self._keys = np.array(keys)     # np.array: keep the host copy writable
        self._metrics["token_latency"].observe(time.perf_counter() - t0)

        for slot in range(self.num_slots):
            state = self._slots[slot]
            if state is None or not self._active[slot]:
                continue
            t = int(toks[slot])
            state.tokens.append(t)
            self._metrics["tokens"].inc()
            self._pos[slot] += 1
            self._last[slot] = t
            eos = state.pending.request.eos_id
            if eos is not None and t == eos:
                self._retire(slot, "eos")
            elif len(state.tokens) >= state.pending.max_new:
                self._retire(slot, "length")
        return True

    def _retire(self, slot: int, reason: str) -> None:
        state = self._slots[slot]
        self._cache.free(slot)
        self._slots[slot] = None
        self._active[slot] = False
        self._pos[slot] = 0
        self._last[slot] = 0
        self._temp[slot] = 0.0
        self._topk[slot] = 0
        self._topp[slot] = 1.0
        self._finish(state.pending, state.tokens, reason, state.ttft_s)
        self._refresh_gauges()

    def _finish(self, pending: _Pending, tokens: List[int], reason: str,
                ttft_s: float) -> None:
        self._metrics["requests"].inc()
        pending._resolve(GenerateResult(
            request_id=pending.request.request_id,
            prompt=list(pending.request.prompt),
            tokens=list(tokens),
            finish_reason=reason,
            ttft_s=ttft_s,
            latency_s=time.perf_counter() - pending.enqueue_t,
        ))

    def _refresh_gauges(self) -> None:
        self._metrics["active_slots"].set(int(self._active.sum()))
        self._metrics["pages_in_use"].set(self._cache.pages_in_use)
