"""Continuous-batching serving engine: one jitted decode step, forever.

The generation story before this module was *call-shaped*:
``greedy_generate`` compiles one program per ``(model, steps)`` and runs a
whole batch in lockstep — every sequence starts together, finishes together,
and the program is torn through per call.  An online service sees none of
that structure: requests arrive whenever, want different lengths and
sampling, and must not pay a compile.  This engine is the standard
continuous-batching formulation (Orca/vLLM):

* a fixed ring of ``num_slots`` **batch slots**;
* ONE jitted single-token **decode step** over all slots — every
  per-request quantity (position, last token, RNG key,
  temperature/top-k/top-p, active flag, speculative opt-in) is *data*, so
  admitting or retiring a request never retraces (dklint DK102);
* a **paged KV cache** (:mod:`distkeras_tpu.serving.cache`): K/V pools
  shared by all slots, per-slot page tables, pages allocated at admission
  and freed at retirement;
* between decode steps the host loop **admits** queued requests into free
  slots (prefill) and **retires** finished ones (EOS / max-new-tokens), so
  a long request never convoys short ones;
* SLO metrics through the telemetry registry — TTFT and per-token-latency
  histograms, queue depth, token/request counters — visible on the
  flightdeck ``/metrics`` scrape.

Fast paths (each optional, all compile-count pinned):

* **Prefill width bucketing** — prompts prefill at the smallest
  power-of-two page-multiple width that fits them (``prefill_buckets``)
  instead of the slot's full page capacity, so a 12-token prompt stops
  paying max-context FLOPs.  One program per *used* bucket, compiled
  lazily; ``serving_prefill_padded_tokens`` counts the padding burned so
  the win is visible on ``/metrics``.
* **Speculative decoding** (``draft_model``) — a cheaper draft model
  (anything with a ``decode_spec``, e.g. a shallower ``TransformerLM``)
  proposes ``spec_tokens`` tokens per engine iteration via single-token
  draft steps; ONE multi-token target step verifies the window against the
  paged cache and emits the accepted prefix plus a correction token
  (Leviathan et al., arXiv:2211.17192 — see
  :func:`distkeras_tpu.serving.sampling.speculative_verify`).  There is no
  bonus token, so draft and target caches never develop holes.  Greedy
  emitted tokens are always target-argmax rows, hence bitwise identical to
  the non-speculative greedy stream regardless of draft quality; stochastic
  requests use exact acceptance-rejection resampling.  Requests opt out per
  call (``speculative=False``) and ride the same program as traced data.
* **Sharded decode** (``mesh``) — the target's prefill/decode/verify
  programs run under a tensor-parallel ``shard_map`` (heads sharded, MLP
  and embeddings replicated), so one engine serves from every local device.

Numerics: the engine re-runs the model's own flax submodules
(``nn.LayerNorm`` / ``nn.DenseGeneral`` / ``nn.Dense`` / the
``_decode_attention`` masking math) over param subtrees sliced out by the
model's ``decode_spec`` hook, so greedy requests emit tokens **bitwise
identical** to ``greedy_generate`` (tests/test_serving.py pins this under
staggered concurrent arrival).  Prefill pads the prompt to its bucket
width — positions past the prompt are causally masked and their cache rows
are overwritten by decode before ever becoming visible, so padding changes
nothing but FLOPs.

RNG: each request carries its own ``PRNGKey(seed)`` chain, split once per
engine iteration *of that request* — sampled output is a function of
(params, prompt, knobs, seed) alone, independent of whatever else shares
the batch.  Speculative opt-out slots consume the exact non-speculative
key chain, so a request's tokens don't change when its neighbours opt in.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu import chaos as _chaos
from distkeras_tpu.sanitizer import lockwatch
from distkeras_tpu.telemetry import accounting as _accounting
from distkeras_tpu.telemetry import runtime as _truntime
from distkeras_tpu.telemetry.trace import NOOP_SPAN, trace as _trace
from distkeras_tpu.serving.cache import PagedKVCache, append_rows, rollback_rows
from distkeras_tpu.serving.frontend import (
    GenerateRequest,
    GenerateResult,
    RequestQueue,
)
from distkeras_tpu.serving.sampling import (
    modified_probs,
    sample_one,
    sample_tokens,
    speculative_verify_tokens,
)

__all__ = ["EngineCrashed", "ServingEngine", "serving_metrics"]


class EngineCrashed(RuntimeError):
    """The engine's host loop died (chaos ``kill_replica`` or an equivalent
    hard fault): every request aborted, the replica is dead.  Raised by
    ``submit``/``hot_swap`` so a router can tell "dead" from "saturated"."""


def serving_metrics(registry=None) -> dict:
    """Get-or-create the engine's SLO instruments on ``registry`` (default:
    the process-global one).  One canonical home for the names/help text so
    the engine, the golden test, and the CI smoke assert the same schema."""
    if registry is None:
        from distkeras_tpu.telemetry.metrics import metrics as registry
    return {
        "ttft": registry.histogram(
            "serving_ttft_seconds",
            help="time from request admission-queue entry to first token",
        ),
        "token_latency": registry.histogram(
            "serving_token_latency_seconds",
            help="wall time of one continuous-batching decode step",
        ),
        "prefill_seconds": registry.histogram(
            "serving_prefill_seconds",
            help="wall time of one prefill dispatch (bucketed width)",
        ),
        "queue_depth": registry.gauge(
            "serving_queue_depth", help="requests waiting for a batch slot"
        ),
        "active_slots": registry.gauge(
            "serving_active_slots", help="batch slots generating right now"
        ),
        "pages_in_use": registry.gauge(
            "serving_kv_pages_in_use", help="allocated KV cache pages"
        ),
        "tokens": registry.counter(
            "serving_tokens_total", help="tokens generated across all requests"
        ),
        "requests": registry.counter(
            "serving_requests_total", help="requests completed (any finish reason)"
        ),
        "rejected": registry.counter(
            "serving_requests_rejected_total",
            help="requests shed by queue backpressure",
        ),
        "prefill_padded": registry.counter(
            "serving_prefill_padded_tokens",
            help="padding tokens burned by bucketed prefill (width - prompt)",
        ),
        "decode_steps": registry.counter(
            "serving_decode_steps_total",
            help="target decode/verify iterations (speculative emits >1 "
                 "token per step, so steps/tokens < 1)",
        ),
        "spec_proposed": registry.counter(
            "serving_spec_proposed_total",
            help="draft tokens proposed by speculative decoding",
        ),
        "spec_accepted": registry.counter(
            "serving_spec_accepted_total",
            help="draft tokens accepted by target verification",
        ),
        "hot_swaps": registry.counter(
            "serving_hot_swaps_total",
            help="in-place param hot-swaps applied by this engine",
        ),
    }


# ------------------------------------------------------------ model slicing


@dataclasses.dataclass
class _Spec:
    """Normalized decode view of one causal LM: embedding tables, per-block
    param subtrees, final LN + head, and the static config the step
    functions close over.  Built from the model's ``decode_spec`` hook."""

    tok: Any
    pos: Any
    blocks: List[Any]
    final_ln: Any
    head: Any
    dim: int
    heads: int
    head_dim: int
    max_len: int
    vocab: int
    ln_eps: float

    def params(self) -> dict:
        """The pytree passed (not closed over) to the jitted steps, so big
        leaves ride as runtime buffers rather than baked constants."""
        return {
            "tok": self.tok, "pos": self.pos, "blocks": list(self.blocks),
            "final_ln": self.final_ln, "head": self.head,
        }


def _resolve_spec(model, params) -> _Spec:
    """Accept a ``TrainedModel``, a ``FlaxModel`` adapter + params, or a raw
    module/adapter with a ``decode_spec`` hook + params."""
    from distkeras_tpu.models.adapter import FlaxModel, TrainedModel

    if isinstance(model, TrainedModel):
        return _resolve_spec(model.adapter, model.params)
    if isinstance(model, FlaxModel):
        model = model.module
    hook = getattr(model, "decode_spec", None)
    if hook is None:
        raise TypeError(
            f"{type(model).__name__} has no decode_spec hook; serving "
            "supports TransformerLM and StagedLM"
        )
    if params is None:
        raise ValueError(
            "params required when passing a bare module/adapter "
            "(a TrainedModel carries its own)"
        )
    raw = hook(params)
    cfg = raw["config"]
    qkv = raw["blocks"][0]["_SelfAttention_0"]["qkv"]["kernel"]
    # prefer the config's head geometry (authoritative even if the kernels
    # are resharded later); fall back to kernel shapes for older hooks
    return _Spec(
        tok=jnp.asarray(raw["embed"]["tok"]),
        pos=jnp.asarray(raw["embed"]["pos"]),
        blocks=list(raw["blocks"]),
        final_ln=raw["final_ln"],
        head=raw["head"],
        dim=int(cfg["dim"]),
        heads=int(cfg.get("heads", qkv.shape[-2])),
        head_dim=int(cfg.get("head_dim", qkv.shape[-1])),
        max_len=int(cfg["max_len"]),
        vocab=int(cfg["vocab_size"]),
        ln_eps=float(cfg["ln_eps"]),
    )


def _block_apply(bp, x, attend, eps, psum=None):
    """One encoder block over param subtree ``bp``, reusing the model's own
    flax submodules so the math is bit-identical to training/`generate`.
    ``attend(q, k, v)`` supplies the paged-cache attention.  Head counts are
    read off the (possibly shard-local) kernel shapes, so the same function
    serves both the replicated and the tensor-parallel build; ``psum`` is
    the cross-shard reduction under ``shard_map`` (None when unsharded)."""
    ap = bp["_SelfAttention_0"]
    dim = bp["Dense_1"]["kernel"].shape[-1]
    mlp = bp["Dense_0"]["kernel"].shape[-1]
    heads, head_dim = ap["qkv"]["kernel"].shape[-2:]
    h = nn.LayerNorm(epsilon=eps).apply({"params": bp["LayerNorm_0"]}, x)
    qkv = nn.DenseGeneral((3, heads, head_dim)).apply({"params": ap["qkv"]}, h)
    q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
    out = attend(q, k, v)
    if psum is None:
        h = nn.DenseGeneral(dim, axis=(-2, -1)).apply({"params": ap["proj"]}, out)
    else:
        # tensor-parallel: each shard contracts its local heads bias-free,
        # the psum sums the partials, and the replicated bias is added once
        # (DenseGeneral per shard would add it axis-size times)
        h = jnp.einsum("...hd,hdo->...o", out, ap["proj"]["kernel"])
        h = psum(h) + ap["proj"]["bias"]
    x = x + h
    h = nn.LayerNorm(epsilon=eps).apply({"params": bp["LayerNorm_1"]}, x)
    h = nn.Dense(mlp).apply({"params": bp["Dense_0"]}, h)
    h = nn.gelu(h)
    h = nn.Dense(dim).apply({"params": bp["Dense_1"]}, h)
    return x + h


def _head_apply(final_ln, head, x, eps):
    h = nn.LayerNorm(epsilon=eps).apply({"params": final_ln}, x)
    return nn.Dense(head["kernel"].shape[-1]).apply({"params": head}, h)


def _resolve_buckets(prefill_buckets, page_size: int, max_context: int):
    """The prefill width ladder: ascending page-multiple widths ending at
    ``max_context``.  Default: ``page_size * 2**i`` capped at capacity."""
    if prefill_buckets is None:
        widths, w = [], page_size
        while w < max_context:
            widths.append(w)
            w *= 2
        widths.append(max_context)
        return tuple(widths)
    widths = sorted({int(w) for w in prefill_buckets})
    if not widths:
        raise ValueError("prefill_buckets must be non-empty")
    for w in widths:
        if w < 1 or w > max_context or w % page_size:
            raise ValueError(
                f"prefill bucket {w} must be a positive multiple of "
                f"page_size {page_size} and <= max context {max_context}"
            )
    if widths[-1] != max_context:
        widths.append(max_context)  # every admissible prompt needs a bucket
    return tuple(widths)


# -------------------------------------------------------------- bookkeeping


class _Pending:
    """Handle returned by :meth:`ServingEngine.submit` — resolves to a
    :class:`GenerateResult` when the request retires."""

    __slots__ = ("request", "max_new", "enqueue_t", "_event", "_result")

    def __init__(self, request: GenerateRequest, max_new: int, enqueue_t: float):
        self.request = request
        self.max_new = max_new
        self.enqueue_t = enqueue_t
        self._event = threading.Event()
        self._result: Optional[GenerateResult] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> Optional[GenerateResult]:
        """Block for the result; ``None`` on timeout."""
        if not self._event.wait(timeout):
            return None
        return self._result

    def _resolve(self, result: GenerateResult) -> None:
        self._result = result
        self._event.set()


class _SlotState:
    """Host-side record for one occupied batch slot."""

    __slots__ = ("pending", "tokens", "plen", "ttft_s", "pages", "admit_t")

    def __init__(self, pending: _Pending, plen: int):
        self.pending = pending
        self.tokens: List[int] = []
        self.plen = plen
        self.ttft_s = 0.0
        self.pages = 0        # pages held — the page-seconds numerator
        self.admit_t = 0.0    # prefill-done wall time — its clock start


# -------------------------------------------------------------------- engine


class ServingEngine:
    """Online inference engine with continuous batching over a paged KV
    cache.  See the module docstring for the design; quick start::

        engine = ServingEngine(trained_model, num_slots=4, page_size=16)
        out = engine.generate([1, 2, 3], max_new_tokens=8)   # blocking
        pending = engine.submit(GenerateRequest(prompt=[1, 2, 3]))  # async
        result = pending.result(timeout=30)
        engine.stop()

    The host loop runs on a daemon thread started lazily by the first
    ``submit``/``generate`` (or explicitly via :meth:`start`).  ``model``
    is a ``TrainedModel``, or a ``TransformerLM``/``StagedLM`` (raw or
    behind ``FlaxModel``) plus ``params``.

    Fast-path knobs: ``prefill_buckets`` (width ladder; default
    power-of-two), ``draft_model``/``draft_params``/``spec_tokens``
    (speculative decoding), ``mesh`` (a 1-D tensor-parallel
    ``jax.sharding.Mesh``; ``heads`` must divide by its size).
    """

    def __init__(self, model, params=None, *, num_slots: int = 4,
                 page_size: int = 16, pages_per_slot: Optional[int] = None,
                 num_pages: Optional[int] = None, queue_size: int = 64,
                 registry=None, dtype=jnp.float32,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 draft_model=None, draft_params=None, spec_tokens: int = 4,
                 mesh=None):
        self._spec = _resolve_spec(model, params)
        spec = self._spec
        if pages_per_slot is None:
            pages_per_slot = -(-spec.max_len // page_size)
        self.num_slots = int(num_slots)
        self._cache = PagedKVCache(
            num_layers=len(spec.blocks), num_slots=num_slots,
            page_size=page_size, pages_per_slot=pages_per_slot,
            heads=spec.heads, head_dim=spec.head_dim,
            num_pages=num_pages, dtype=dtype,
        )
        self._width = self._cache.max_context()
        self._buckets = _resolve_buckets(
            prefill_buckets, self._cache.page_size, self._width)
        self._queue = RequestQueue(queue_size)
        self._metrics = serving_metrics(registry)
        # per-tenant ledger (None when DISTKERAS_ACCOUNTING is off): every
        # billing site meters from already-host-visible bookkeeping, so the
        # flag-off path keeps a single `is None` check and the traced
        # programs are byte-identical either way
        self._ledger = _accounting.maybe_ledger(registry)

        # ------------------------------------------------ tensor parallelism
        self._mesh = mesh
        self._psum = None
        if mesh is not None:
            if len(mesh.axis_names) != 1:
                raise ValueError(
                    "serving mesh must be 1-D (one tensor-parallel axis); "
                    f"got axes {mesh.axis_names}"
                )
            self._tp_axis = mesh.axis_names[0]
            tp = int(mesh.devices.size)
            if spec.heads % tp:
                raise ValueError(
                    f"model heads {spec.heads} not divisible by mesh size {tp}"
                )
            axis = self._tp_axis
            self._psum = lambda x: jax.lax.psum(x, axis)
            from jax.sharding import NamedSharding, PartitionSpec as P

            pool_sharding = NamedSharding(mesh, P(None, None, None, axis, None))
            self._cache.k_pages = jax.device_put(self._cache.k_pages, pool_sharding)
            self._cache.v_pages = jax.device_put(self._cache.v_pages, pool_sharding)

        # --------------------------------------------------- draft / verify
        self._draft_spec = None
        self._draft_cache = None
        self._spec_tokens = int(spec_tokens)
        if draft_model is not None:
            if self._spec_tokens < 1:
                raise ValueError("spec_tokens must be >= 1")
            dspec = _resolve_spec(draft_model, draft_params)
            if dspec.vocab != spec.vocab:
                raise ValueError(
                    f"draft vocab {dspec.vocab} != target vocab {spec.vocab}"
                )
            serviceable = min(self._width, spec.max_len)
            if dspec.max_len < serviceable:
                raise ValueError(
                    f"draft max_len {dspec.max_len} < serviceable context "
                    f"{serviceable}; pick a draft trained at the same length"
                )
            self._draft_spec = dspec
            # same page geometry so the target's page tables address the
            # draft pools directly; bookkeeping (free list) is never used —
            # the draft is replicated even under a mesh (it's cheap by
            # construction, and sharding it would serialize two shard_maps)
            self._draft_cache = PagedKVCache(
                num_layers=len(dspec.blocks), num_slots=num_slots,
                page_size=page_size, pages_per_slot=pages_per_slot,
                heads=dspec.heads, head_dim=dspec.head_dim,
                num_pages=self._cache.num_pages, dtype=dtype,
            )

        s = self.num_slots
        self._slots: List[Optional[_SlotState]] = [None] * s
        self._pos = np.zeros(s, np.int32)        # position of the fed token
        self._last = np.zeros(s, np.int32)       # token being fed this step
        self._keys = np.zeros((s, 2), np.uint32)
        self._draft_keys = np.zeros((s, 2), np.uint32)
        self._temp = np.zeros(s, np.float32)
        self._topk = np.zeros(s, np.int32)
        self._topp = np.ones(s, np.float32)
        self._active = np.zeros(s, bool)
        self._spec_on = np.zeros(s, bool)

        self._cv = lockwatch.maybe_wrap(threading.Condition(), "serving.engine")
        self._running = False
        self._thread: Optional[threading.Thread] = None
        # tier hooks: drain/hot-swap/cancel state, all owned by the loop
        # thread except the flags themselves (set under _cv by callers)
        self._crashed = False
        self._draining = False
        self._drain_ack = False
        self._swap: Optional[Tuple[_Spec, threading.Event]] = None
        self._cancelled: List[_Pending] = []

        # Programs compile once per (engine, mesh) config — never per
        # request (the retrace pin in tests/test_serving.py counts on it):
        # one decode OR (one draft step + one verify), plus one prefill per
        # *used* bucket width, built lazily in _prefill_for.
        self._prefill_fns: Dict[Tuple[str, int], Any] = {}
        if self._draft_spec is None:
            self._decode = jax.jit(
                self._maybe_shard(self._build_decode(), n_rest=8, n_out=2),
                donate_argnums=(1, 2))
        else:
            self._draft_step = jax.jit(
                self._build_draft_step(), donate_argnums=(1, 2))
            self._verify = jax.jit(
                self._maybe_shard(self._build_verify(), n_rest=11, n_out=4),
                donate_argnums=(1, 2))

    # ------------------------------------------------------- traced programs

    def _target_param_specs(self):
        """PartitionSpecs for the target params under the tensor-parallel
        mesh: qkv sharded over heads, attention proj contracting over the
        sharded heads, everything else (embeddings, LN, MLP, head)
        replicated."""
        from jax.sharding import PartitionSpec as P

        axis = self._tp_axis
        with self._cv:
            spec = self._spec
        specs = jax.tree.map(lambda _: P(), spec.params())
        for bs in specs["blocks"]:
            ap = bs["_SelfAttention_0"]
            ap["qkv"]["kernel"] = P(None, None, axis, None)
            ap["qkv"]["bias"] = P(None, axis, None)
            ap["proj"]["kernel"] = P(axis, None, None)
        return specs

    def _maybe_shard(self, fn, n_rest: int, n_out: int):
        """Wrap a ``(params, kpool, vpool, *rest) -> (kpool, vpool, *outs)``
        step in a tensor-parallel shard_map when the engine has a mesh.
        Pools are heads-sharded; every other input/output is replicated."""
        if self._mesh is None:
            return fn
        from jax.sharding import PartitionSpec as P

        from distkeras_tpu.utils import compat

        pool = P(None, None, None, self._tp_axis, None)
        in_specs = (self._target_param_specs(), pool, pool) + (P(),) * n_rest
        out_specs = (pool, pool) + (P(),) * n_out
        # check_vma=False: replication of the sampled outputs holds by
        # construction (inputs replicated, every cross-head contraction is
        # psummed) but jax 0.4's check_rep can't always prove it
        return compat.shard_map(
            fn, self._mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False)

    def _prefill_for(self, width: int, role: str = "target"):
        """The jitted prefill program for one bucket width, compiled on
        first use.  ``role`` is "target" (samples the first token) or
        "draft" (cache writes only)."""
        key = (role, width)
        fn = self._prefill_fns.get(key)
        if fn is None:
            with self._cv:
                spec = self._spec
            if role == "target":
                fn = jax.jit(
                    self._maybe_shard(
                        self._build_prefill(width, spec, sample=True,
                                            psum=self._psum),
                        n_rest=7, n_out=2),
                    donate_argnums=(1, 2))
            else:
                fn = jax.jit(
                    self._build_prefill(width, self._draft_spec, sample=False,
                                        psum=None),
                    donate_argnums=(1, 2))
            self._prefill_fns[key] = fn
        return fn

    def _build_prefill(self, width: int, spec: _Spec, *, sample: bool, psum):
        ps = self._cache.page_size
        npages = width // ps
        eps = spec.ln_eps

        def trunk(params, pools, tokens, table):
            # tokens [1, width] right-padded; table [npages].
            positions = jnp.clip(jnp.arange(width), 0, spec.max_len - 1)
            x = params["tok"][tokens] + params["pos"][positions][None]

            def paged_attend(li):
                def attend(q, k, v):
                    # stash the whole padded chunk into this slot's pages;
                    # rows past the prompt land on scratch/overwritten pages
                    # and are causally masked below — never attended.
                    kc = k[0].reshape(npages, ps, *k.shape[-2:])
                    vc = v[0].reshape(npages, ps, *v.shape[-2:])
                    pools["k"] = pools["k"].at[li, table].set(kc)
                    pools["v"] = pools["v"].at[li, table].set(vc)
                    # causal attention over the chunk itself (same masking
                    # math as _SelfAttention._decode_attention)
                    qt = jnp.moveaxis(q, 1, 2)
                    kt = jnp.moveaxis(k, 1, 2)
                    vt = jnp.moveaxis(v, 1, 2)
                    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
                    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
                    q_pos = jnp.arange(width)[:, None]
                    k_pos = jnp.arange(width)[None, :]
                    s = jnp.where(k_pos <= q_pos, s, -jnp.inf)
                    out = jnp.einsum(
                        "bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), vt
                    )
                    return jnp.moveaxis(out, 1, 2)

                return attend

            for li, bp in enumerate(params["blocks"]):
                x = _block_apply(bp, x, paged_attend(li), eps, psum=psum)
            return x

        if not sample:
            def prefill_cache_only(params, kpool, vpool, tokens, table):
                # draft prefill: only the K/V writes matter — XLA dead-code
                # eliminates the attention outputs, leaving the cheap qkv
                # projections per layer
                pools = {"k": kpool, "v": vpool}
                trunk(params, pools, tokens, table)
                return pools["k"], pools["v"]

            return prefill_cache_only

        def prefill(params, kpool, vpool, tokens, table, length, key,
                    temp, top_k, top_p):
            pools = {"k": kpool, "v": vpool}
            x = trunk(params, pools, tokens, table)
            logits = _head_apply(params["final_ln"], params["head"], x, eps)
            row = jax.lax.dynamic_index_in_dim(
                logits[0], length - 1, axis=0, keepdims=False
            )
            key, sub = jax.random.split(key)
            tok = sample_one(row, sub, temp, top_k, top_p)
            return pools["k"], pools["v"], tok, key

        return prefill

    def _build_decode(self):
        spec, cache = self._spec, self._cache
        s, ctx = self.num_slots, self._width
        eps = spec.ln_eps
        psum = self._psum

        def decode(params, kpool, vpool, tables, pos, last, keys,
                   temp, top_k, top_p, active):
            # One token for every slot.  Inactive slots compute garbage into
            # the scratch page (their tables point at physical page 0) and
            # sample token 0 — all masked out host-side.
            x = params["tok"][last] + params["pos"][
                jnp.clip(pos, 0, spec.max_len - 1)
            ]
            x = x[:, None, :]  # [slots, 1, dim]
            pools = {"k": kpool, "v": vpool}

            def paged_attend(li):
                def attend(q, k, v):
                    pools["k"] = append_rows(pools["k"], li, tables, pos, k)
                    pools["v"] = append_rows(pools["v"], li, tables, pos, v)
                    kg = pools["k"][li][tables]
                    kg = kg.reshape(s, ctx, *kg.shape[-2:])
                    vg = pools["v"][li][tables]
                    vg = vg.reshape(s, ctx, *vg.shape[-2:])
                    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
                    sc = jnp.einsum("shd,skhd->shk", q[:, 0], kg) * scale
                    mask = jnp.arange(ctx)[None, :] <= pos[:, None]
                    sc = jnp.where(mask[:, None, :], sc, -jnp.inf)
                    out = jnp.einsum(
                        "shk,skhd->shd", jax.nn.softmax(sc, axis=-1), vg
                    )
                    return out[:, None]

                return attend

            for li, bp in enumerate(params["blocks"]):
                x = _block_apply(bp, x, paged_attend(li), eps, psum=psum)
            logits = _head_apply(params["final_ln"], params["head"], x, eps)[:, 0]
            split = jax.vmap(jax.random.split)(keys)
            new_keys, subs = split[:, 0], split[:, 1]
            tok = sample_tokens(logits, subs, temp, top_k, top_p)
            tok = jnp.where(active, tok, 0)
            return pools["k"], pools["v"], tok, new_keys

        return decode

    def _build_draft_step(self):
        """One single-token draft step over all slots: writes draft K/V at
        ``pos``, samples the proposal, and returns the draft's *modified*
        distribution (the q of the acceptance test).  Always replicated."""
        dspec, cache = self._draft_spec, self._cache
        s, ctx = self.num_slots, self._width
        eps = dspec.ln_eps

        def draft_step(params, kpool, vpool, tables, pos, last, keys,
                       temp, top_k, top_p, active):
            x = params["tok"][last] + params["pos"][
                jnp.clip(pos, 0, dspec.max_len - 1)
            ]
            x = x[:, None, :]
            pools = {"k": kpool, "v": vpool}

            def paged_attend(li):
                def attend(q, k, v):
                    pools["k"] = append_rows(pools["k"], li, tables, pos, k)
                    pools["v"] = append_rows(pools["v"], li, tables, pos, v)
                    kg = pools["k"][li][tables]
                    kg = kg.reshape(s, ctx, *kg.shape[-2:])
                    vg = pools["v"][li][tables]
                    vg = vg.reshape(s, ctx, *vg.shape[-2:])
                    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
                    sc = jnp.einsum("shd,skhd->shk", q[:, 0], kg) * scale
                    mask = jnp.arange(ctx)[None, :] <= pos[:, None]
                    sc = jnp.where(mask[:, None, :], sc, -jnp.inf)
                    out = jnp.einsum(
                        "shk,skhd->shd", jax.nn.softmax(sc, axis=-1), vg
                    )
                    return out[:, None]

                return attend

            for li, bp in enumerate(params["blocks"]):
                x = _block_apply(bp, x, paged_attend(li), eps)
            logits = _head_apply(params["final_ln"], params["head"], x, eps)[:, 0]
            split = jax.vmap(jax.random.split)(keys)
            new_keys, subs = split[:, 0], split[:, 1]
            tok = sample_tokens(logits, subs, temp, top_k, top_p)
            tok = jnp.where(active, tok, 0)
            qprobs = jax.vmap(modified_probs)(logits, temp, top_k, top_p)
            return pools["k"], pools["v"], tok, qprobs, new_keys

        return draft_step

    def _build_verify(self):
        """The multi-token target step: feed the window ``[last, d_1 ..
        d_{m-1}]``, write its K/V through the page tables, compute all m
        next-token logits in one pass, judge the drafts per slot
        (:func:`speculative_verify_tokens`), and roll the rejected suffix
        rows back out of the pools."""
        spec = self._spec
        s, ctx, m = self.num_slots, self._width, self._spec_tokens
        eps = spec.ln_eps
        psum = self._psum

        def verify(params, kpool, vpool, tables, pos, last, drafts, qprobs,
                   keys, temp, top_k, top_p, active, spec_on):
            # drafts: tuple of m [slots] proposals (d_1..d_m); qprobs: tuple
            # of m [slots, vocab] draft distributions.  Stacked here, inside
            # the program, so the host loop ships the draft step's outputs
            # without an extra dispatch.
            d = jnp.stack(drafts, axis=1)        # [slots, m]
            q_d = jnp.stack(qprobs, axis=1)      # [slots, m, vocab]
            fed = jnp.concatenate([last[:, None], d[:, :-1]], axis=1)
            positions = pos[:, None] + jnp.arange(m)[None, :]  # [slots, m]
            x = params["tok"][fed] + params["pos"][
                jnp.clip(positions, 0, spec.max_len - 1)
            ]
            pools = {"k": kpool, "v": vpool}

            def paged_attend(li):
                def attend(q, k, v):
                    pools["k"] = append_rows(pools["k"], li, tables, pos, k)
                    pools["v"] = append_rows(pools["v"], li, tables, pos, v)
                    kg = pools["k"][li][tables]
                    kg = kg.reshape(s, ctx, *kg.shape[-2:])
                    vg = pools["v"][li][tables]
                    vg = vg.reshape(s, ctx, *vg.shape[-2:])
                    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
                    sc = jnp.einsum("smhd,skhd->smhk", q, kg) * scale
                    mask = jnp.arange(ctx)[None, None, :] <= positions[:, :, None]
                    sc = jnp.where(mask[:, :, None, :], sc, -jnp.inf)
                    out = jnp.einsum(
                        "smhk,skhd->smhd", jax.nn.softmax(sc, axis=-1), vg
                    )
                    return out

                return attend

            for li, bp in enumerate(params["blocks"]):
                x = _block_apply(bp, x, paged_attend(li), eps, psum=psum)
            logits = _head_apply(params["final_ln"], params["head"], x, eps)
            out, count, accepted, new_keys = speculative_verify_tokens(
                logits, d, q_d, keys, temp, top_k, top_p, spec_on & active)
            out = jnp.where(active[:, None], out, 0)
            # erase the rejected suffix so the pools only ever hold
            # accepted-token K/V between iterations
            for li in range(len(params["blocks"])):
                pools["k"] = rollback_rows(pools["k"], li, tables, pos, count, m)
                pools["v"] = rollback_rows(pools["v"], li, tables, pos, count, m)
            return pools["k"], pools["v"], out, count, accepted, new_keys

        return verify

    # ----------------------------------------------------------- public API

    def start(self) -> None:
        """Start the host loop thread (idempotent; ``submit`` calls this)."""
        with self._cv:
            if self._running:
                return
            self._running = True
            self._thread = threading.Thread(
                target=self._loop, name="serving-engine", daemon=True
            )
            self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        """Stop the loop; queued and in-flight requests resolve with
        ``finish_reason="aborted"`` (partial tokens included)."""
        with self._cv:
            if not self._running:
                thread = None
            else:
                self._running = False
                thread = self._thread
                self._thread = None
            self._cv.notify_all()
        if thread is not None:
            thread.join(timeout=timeout)
        for slot in range(self.num_slots):
            if self._slots[slot] is not None:
                self._retire(slot, "aborted")
        while True:
            pending = self._queue.pop()
            if pending is None:
                break
            self._finish(pending, [], "aborted", 0.0)
        self._metrics["queue_depth"].set(0)

    def submit(self, request: GenerateRequest) -> _Pending:
        """Validate + enqueue; returns a :class:`_Pending` handle.  Raises
        :class:`~distkeras_tpu.serving.frontend.QueueFull` under
        backpressure and ``ValueError`` for an unservable request.  The
        admission is a ``serving.admit`` span — on the caller's thread, so
        it nests under whatever hop span (``tier.attempt``,
        ``serving.http_request``) drove the submit."""
        span = NOOP_SPAN
        if _truntime.enabled():
            span = _trace.span(
                "serving.admit", request_id=request.request_id,
                trace_id=request.trace_id)
        with span:
            return self._submit(request)

    def _submit(self, request: GenerateRequest) -> _Pending:
        with self._cv:
            # snapshot the published spec: hot-swap replaces it under _cv,
            # so validating against a local ref sees one coherent geometry
            crashed, spec = self._crashed, self._spec
        if crashed:
            raise EngineCrashed("serving engine crashed; replica is dead")
        request.validate()
        plen = len(request.prompt)
        if plen > self._width or plen >= spec.max_len:
            raise ValueError(
                f"prompt length {plen} exceeds serviceable context "
                f"(width {self._width}, model max_len {spec.max_len})"
            )
        if int(np.max(request.prompt)) >= spec.vocab:
            raise ValueError("prompt token id out of vocabulary")
        if request.speculative and self._draft_spec is None:
            raise ValueError(
                "request asks for speculative decoding but the engine was "
                "built without a draft_model"
            )
        max_new = min(request.max_new_tokens, spec.max_len - plen,
                      self._width - plen)
        pending = _Pending(request, max_new, time.perf_counter())
        try:
            self._queue.put(pending)
        except Exception:
            self._metrics["rejected"].inc()
            raise
        self._metrics["queue_depth"].set(len(self._queue))
        self.start()
        with self._cv:
            self._cv.notify_all()
        return pending

    def generate(self, prompt, max_new_tokens: int = 16,
                 timeout: Optional[float] = 60.0,
                 **knobs) -> GenerateResult:
        """Blocking convenience: submit one request, wait for its result.
        ``knobs`` forwards temperature/top_k/top_p/seed/eos_id/speculative."""
        req = GenerateRequest(prompt=[int(t) for t in prompt],
                              max_new_tokens=max_new_tokens, **knobs)
        result = self.submit(req).result(timeout=timeout)
        if result is None:
            raise TimeoutError(f"generation did not finish in {timeout}s")
        return result

    def stats(self) -> Dict[str, float]:
        """Host-side snapshot for bench/debug (not the metrics surface)."""
        return {
            "queue_depth": float(len(self._queue)),
            "active_slots": float(int(self._active.sum())),
            "pages_in_use": float(self._cache.pages_in_use),
            "pages_free": float(self._cache.pages_free),
            "slots_total": float(self.num_slots),
        }

    @property
    def alive(self) -> bool:
        """``False`` once the loop has crashed — the health probe's fast
        path for telling "this replica is dead" from "this replica is slow"."""
        with self._cv:
            return not self._crashed

    @property
    def draining(self) -> bool:
        """Whether admission is paused (explicit :meth:`drain` or an
        in-flight :meth:`hot_swap`)."""
        with self._cv:
            return self._draining or self._swap is not None

    # ------------------------------------------------- tier hooks (host side)

    def cancel(self, pending: _Pending) -> bool:
        """Abort a submitted request: queued — removed and resolved
        ``"aborted"`` immediately; in a slot — retired ``"aborted"`` at the
        loop's next iteration (slot and pages reclaimed).  Returns ``False``
        when the request had already finished.  This is what makes a 504 a
        *release* instead of a leak, and what makes router failover
        idempotent: once the cancelled handle resolves, this engine is
        provably no longer executing the request."""
        if pending.done():
            return False
        if self._queue.remove(pending):
            self._finish(pending, [], "aborted", 0.0)
            self._metrics["queue_depth"].set(len(self._queue))
            return True
        with self._cv:
            running = self._running
            if running:
                self._cancelled.append(pending)
                self._cv.notify_all()
        if not running and not pending.done():
            # no loop to process it (engine stopped or never started with
            # the handle outside the queue) — resolve it directly
            self._finish(pending, [], "aborted", 0.0)
        return True

    def drain(self, timeout: float = 30.0) -> bool:
        """Pause admission and wait until every occupied slot retires.
        Queued requests stay queued (they admit again after
        :meth:`resume`).  Returns ``True`` once drained; ``False`` on
        timeout (admission stays paused either way).  The wait is a
        ``serving.drain`` span, so a request that stalls behind a drain
        shows the interference on its critical path."""
        span = NOOP_SPAN
        if _truntime.enabled():
            span = _trace.span("serving.drain")
        with span:
            with self._cv:
                self._draining = True
                started = self._thread is not None
                self._cv.notify_all()
            if not started:
                return True  # no loop ⇒ nothing in flight, nothing can admit
            deadline = time.perf_counter() + timeout
            while time.perf_counter() < deadline:
                with self._cv:
                    running, acked = self._running, self._drain_ack
                if not running:
                    return True  # stopped/crashed under us — slots are clear
                if acked and not self._active.any():
                    return True
                time.sleep(0.002)
            return False

    def resume(self) -> None:
        """Reopen admission after :meth:`drain`."""
        with self._cv:
            self._draining = False
            self._drain_ack = False
            self._cv.notify_all()

    def hot_swap(self, model, params=None, timeout: float = 30.0) -> None:
        """Swap the served params in place — the checkpoint hot-swap.

        Geometry (dim/heads/head_dim/max_len/vocab/depth/ln_eps) must match
        the engine's current spec: the decode step is param-*shape*-stable,
        so the swap reuses every compiled program — no retrace, no
        recompile.  The loop applies the swap at the first iteration with
        zero active slots (admission pauses until then): in-flight requests
        finish under the old params, queued requests decode under the new,
        and nothing drops.  With a draft model, only the target swaps — the
        verify step guarantees target-distribution samples under any draft,
        so acceptance rate may dip but correctness cannot.  The blocking
        window (geometry check through drain-and-apply) is a
        ``serving.hot_swap`` span — the other interference source a
        request's critical path can surface."""
        span = NOOP_SPAN
        if _truntime.enabled():
            span = _trace.span("serving.hot_swap")
        with span:
            self._hot_swap(model, params, timeout)

    def _hot_swap(self, model, params, timeout: float) -> None:
        new = _resolve_spec(model, params)
        with self._cv:
            old = self._spec
        for f in ("dim", "heads", "head_dim", "max_len", "vocab", "ln_eps"):
            if getattr(new, f) != getattr(old, f):
                raise ValueError(
                    f"hot_swap geometry mismatch on {f}: "
                    f"{getattr(new, f)} != {getattr(old, f)}"
                )
        if len(new.blocks) != len(old.blocks):
            raise ValueError(
                f"hot_swap depth mismatch: {len(new.blocks)} blocks "
                f"!= {len(old.blocks)}"
            )
        with self._cv:
            if self._crashed:
                raise EngineCrashed("engine crashed; cannot hot_swap")
            if self._swap is not None:
                raise RuntimeError("another hot_swap is already in flight")
            if not self._running:
                # no loop ⇒ no in-flight work: swap synchronously
                self._spec = new
                self._metrics["hot_swaps"].inc()
                return
            done = threading.Event()
            self._swap = (new, done)
            self._cv.notify_all()
        if not done.wait(timeout):
            with self._cv:
                self._swap = None
            raise TimeoutError(f"hot_swap did not drain within {timeout}s")

    @property
    def prefill_buckets(self) -> Tuple[int, ...]:
        return self._buckets

    # ------------------------------------------------------------ host loop

    def _loop(self) -> None:
        while True:
            try:
                with self._cv:
                    if not self._running:
                        return
                    self._drain_ack = self._draining
                    swap_pending = self._swap is not None
                    paused = self._draining or swap_pending
                self._cancel_requested()
                if swap_pending and not self._active.any():
                    self._apply_swap()
                    with self._cv:
                        paused = self._draining
                progressed = False if paused else self._admit()
                if _chaos.enabled() and self._active.any():
                    # the kill_replica site: only busy iterations count, so
                    # a seeded kill always lands mid-decode with requests in
                    # flight (the failover path is what's under test)
                    _chaos.fault("replica")
                progressed = self._decode_once() or progressed
                if not progressed:
                    with self._cv:
                        if (self._running and self._swap is None
                                and not self._cancelled
                                and (paused or len(self._queue) == 0)):
                            self._cv.wait(timeout=0.05)
            except _chaos.ChaosKilled:
                self._crash()
                return

    def _cancel_requested(self) -> None:
        """Retire every slot whose request was cancelled (loop thread only)."""
        with self._cv:
            if not self._cancelled:
                return
            cancelled, self._cancelled = self._cancelled, []
        for pending in cancelled:
            if pending.done():
                continue
            if self._queue.remove(pending):
                self._finish(pending, [], "aborted", 0.0)
                continue
            for slot, state in enumerate(self._slots):
                if state is not None and state.pending is pending:
                    self._retire(slot, "aborted")
                    break
        self._metrics["queue_depth"].set(len(self._queue))

    def _apply_swap(self) -> None:
        """Apply a pending hot-swap (loop thread, zero active slots)."""
        with self._cv:
            if self._swap is None:
                return  # hot_swap timed out and withdrew the request
            spec, done = self._swap
            self._spec = spec
            self._swap = None
        self._metrics["hot_swaps"].inc()
        done.set()

    def _crash(self) -> None:
        # Runs ON the loop thread after a chaos kill — the in-process
        # analogue of the replica's process dying mid-decode.  Every
        # in-flight and queued request aborts (partial tokens included) and
        # the engine refuses further work; the tier's probe sees alive=False
        # and its router fails the aborted requests over.
        with self._cv:
            self._crashed = True
            self._running = False
            self._thread = None
            self._cv.notify_all()
        for slot in range(self.num_slots):
            if self._slots[slot] is not None:
                self._retire(slot, "aborted")
        while True:
            pending = self._queue.pop()
            if pending is None:
                break
            self._finish(pending, [], "aborted", 0.0)
        self._metrics["queue_depth"].set(0)

    def _admit(self) -> bool:
        """Move queued requests into free slots (prefill).  FIFO with
        head-of-line blocking: when the page pool can't fit the next
        request yet, it waits for a retirement rather than being skipped —
        no starvation of big requests."""
        admitted = False
        while True:
            free = [i for i, st in enumerate(self._slots) if st is None]
            if not free:
                break
            pending = self._queue.pop()
            if pending is None:
                break
            need = self._cache.pages_needed(
                len(pending.request.prompt) + pending.max_new
            )
            if not self._cache.can_alloc(need):
                self._queue.requeue_front(pending)
                break
            self._prefill_into(free[0], pending, need)
            admitted = True
        self._metrics["queue_depth"].set(len(self._queue))
        return admitted

    def _prefill_into(self, slot: int, pending: _Pending, need: int) -> None:
        req = pending.request
        plen = len(req.prompt)
        self._cache.alloc(slot, need)
        # smallest bucket that fits the prompt (the ladder always ends at
        # max_context and submit bounded plen, so next() can't exhaust)
        width = next(w for w in self._buckets if w >= plen)
        t0 = time.perf_counter()
        span = NOOP_SPAN
        if _truntime.enabled():
            # the loop thread serves every request, so the ids ride span
            # args (no thread-bound context here); queue wait spans the gap
            # between the admission thread's enqueue and this prefill
            _trace.record(
                "serving.queue_wait", pending.enqueue_t, t0,
                request_id=req.request_id, trace_id=req.trace_id,
                parent="serving.admit")
            attrs: Dict[str, Any] = dict(
                request_id=req.request_id, trace_id=req.trace_id,
                parent="serving.admit", slot=slot, width=width, plen=plen)
            if req.tenant:
                attrs["tenant"] = req.tenant
            span = _trace.span("serving.prefill", **attrs)
        with span:
            tokens = np.zeros((1, width), np.int32)
            tokens[0, :plen] = req.prompt
            tokens_dev = jnp.asarray(tokens)
            table = jnp.asarray(
                self._cache.tables[slot, : width // self._cache.page_size])
            kp, vp, tok, key = self._prefill_for(width)(
                self._spec.params(), self._cache.k_pages,
                self._cache.v_pages, tokens_dev, table, jnp.int32(plen),
                jax.random.PRNGKey(req.seed), jnp.float32(req.temperature),
                jnp.int32(req.top_k), jnp.float32(req.top_p),
            )
            self._cache.k_pages, self._cache.v_pages = kp, vp
            spec_on = (self._draft_spec is not None
                       and req.speculative is not False)
            if spec_on:
                dc = self._draft_cache
                dkp, dvp = self._prefill_for(width, role="draft")(
                    self._draft_spec.params(), dc.k_pages, dc.v_pages,
                    tokens_dev, table)
                dc.k_pages, dc.v_pages = dkp, dvp
                # a draft chain decorrelated from the request's target chain
                self._draft_keys[slot] = np.asarray(
                    jax.random.fold_in(jax.random.PRNGKey(req.seed), 7))
            tok0 = int(np.asarray(tok))
        now = time.perf_counter()
        self._metrics["prefill_seconds"].observe(now - t0)
        self._metrics["prefill_padded"].inc(width - plen)

        state = _SlotState(pending, plen)
        state.tokens.append(tok0)
        state.ttft_s = now - pending.enqueue_t
        state.pages = need
        state.admit_t = now
        self._metrics["ttft"].observe(state.ttft_s)
        self._metrics["tokens"].inc()
        if self._ledger is not None:
            # prompt tokens, queue wait, prefill device-seconds, and the
            # first sampled token bill at admission — all host-visible
            self._ledger.admit(
                req.tenant, prompt_tokens=plen,
                queue_wait_s=t0 - pending.enqueue_t,
                device_s=now - t0, generated=1)
        self._slots[slot] = state
        self._pos[slot] = plen
        self._last[slot] = tok0
        self._keys[slot] = np.asarray(key)
        self._temp[slot] = req.temperature
        self._topk[slot] = req.top_k
        self._topp[slot] = req.top_p
        self._active[slot] = True
        self._spec_on[slot] = spec_on
        self._refresh_gauges()

        if req.eos_id is not None and tok0 == req.eos_id:
            self._retire(slot, "eos")
        elif len(state.tokens) >= pending.max_new:
            self._retire(slot, "length")

    def _decode_once(self) -> bool:
        """One engine iteration over every active slot: a plain decode
        step, or (with a draft model) m draft steps + one verify step."""
        if not self._active.any():
            return False
        if self._draft_spec is not None:
            self._spec_once()
        else:
            self._plain_once()
        return True

    def _step_span(self):
        """A ``serving.decode_step`` span for one engine iteration.  One
        jitted step serves every active slot, so attribution is a *list* of
        request ids (``args.requests``); when a single request — or a
        single trace — is active, the scalar ``request_id``/``trace_id``
        are promoted too so per-request tooling joins without list
        handling.  NOOP when telemetry is off (no list building either)."""
        if not _truntime.enabled():
            return NOOP_SPAN
        reqs = [self._slots[i].pending.request
                for i in range(self.num_slots)
                if self._active[i] and self._slots[i] is not None]
        attrs: Dict[str, Any] = {
            "requests": [r.request_id for r in reqs],
            "n_active": len(reqs),
        }
        traces = sorted({r.trace_id for r in reqs if r.trace_id})
        if len(reqs) == 1:
            attrs["request_id"] = reqs[0].request_id
            attrs["parent"] = "serving.prefill"
        if len(traces) == 1:
            attrs["trace_id"] = traces[0]
        elif traces:
            attrs["trace_ids"] = traces
        tenants = sorted({r.tenant for r in reqs if r.tenant})
        if len(tenants) == 1:
            attrs["tenant"] = tenants[0]
        elif tenants:
            attrs["tenants"] = tenants
        return _trace.span("serving.decode_step", **attrs)

    def _plain_once(self) -> None:
        t0 = time.perf_counter()
        with self._step_span():
            kp, vp, tok, keys = self._decode(
                self._spec.params(), self._cache.k_pages, self._cache.v_pages,
                jnp.asarray(self._cache.tables), jnp.asarray(self._pos),
                jnp.asarray(self._last), jnp.asarray(self._keys),
                jnp.asarray(self._temp), jnp.asarray(self._topk),
                jnp.asarray(self._topp), jnp.asarray(self._active),
            )
            self._cache.k_pages, self._cache.v_pages = kp, vp
            toks = np.asarray(tok)      # device sync: the step is done here
        self._keys = np.array(keys)     # np.array: keep the host copy writable
        dt = time.perf_counter() - t0
        self._metrics["token_latency"].observe(dt)
        self._metrics["decode_steps"].inc()
        ledger = self._ledger
        # device-seconds estimate: the step's wall time split evenly over
        # the slots it decoded for (captured before retirements mutate it)
        share = dt / max(1, int(self._active.sum()))

        for slot in range(self.num_slots):
            state = self._slots[slot]
            if state is None or not self._active[slot]:
                continue
            t = int(toks[slot])
            state.tokens.append(t)
            self._metrics["tokens"].inc()
            if ledger is not None:
                ledger.decode(state.pending.request.tenant,
                              tokens=1, device_s=share)
            self._pos[slot] += 1
            self._last[slot] = t
            eos = state.pending.request.eos_id
            if eos is not None and t == eos:
                self._retire(slot, "eos")
            elif len(state.tokens) >= state.pending.max_new:
                self._retire(slot, "length")

    def _spec_once(self) -> None:
        """One speculative iteration: chain m draft steps (device arrays
        flow straight between dispatches — no host syncs), verify the
        window in one target step, then emit each slot's accepted prefix."""
        t0 = time.perf_counter()
        m = self._spec_tokens
        with self._step_span():
            tables = jnp.asarray(self._cache.tables)
            temp = jnp.asarray(self._temp)
            topk = jnp.asarray(self._topk)
            topp = jnp.asarray(self._topp)
            active = jnp.asarray(self._active)
            base_pos = self._pos
            last = jnp.asarray(self._last)
            dkeys = jnp.asarray(self._draft_keys)
            dc = self._draft_cache
            dparams = self._draft_spec.params()
            drafts, qprobs = [], []
            for i in range(m):
                dc.k_pages, dc.v_pages, tok, qp, dkeys = self._draft_step(
                    dparams, dc.k_pages, dc.v_pages, tables,
                    jnp.asarray(base_pos + i), last, dkeys, temp, topk, topp,
                    active)
                drafts.append(tok)
                qprobs.append(qp)
                last = tok
            kp, vp, out, count, accepted, keys = self._verify(
                self._spec.params(), self._cache.k_pages, self._cache.v_pages,
                tables, jnp.asarray(base_pos), jnp.asarray(self._last),
                tuple(drafts), tuple(qprobs), jnp.asarray(self._keys),
                temp, topk, topp, active, jnp.asarray(self._spec_on))
            self._cache.k_pages, self._cache.v_pages = kp, vp
            out = np.asarray(out)       # device sync: the iteration is done
            counts = np.asarray(count)
            acc = np.asarray(accepted)
        self._keys = np.array(keys)
        self._draft_keys = np.array(dkeys)
        dt = time.perf_counter() - t0
        self._metrics["token_latency"].observe(dt)
        self._metrics["decode_steps"].inc()
        spec_slots = self._active & self._spec_on
        n_spec = int(spec_slots.sum())
        if n_spec:
            self._metrics["spec_proposed"].inc(m * n_spec)
            self._metrics["spec_accepted"].inc(int(acc[spec_slots].sum()))
        ledger = self._ledger
        share = dt / max(1, int(self._active.sum()))

        for slot in range(self.num_slots):
            state = self._slots[slot]
            if state is None or not self._active[slot]:
                continue
            req = state.pending.request
            if ledger is not None and spec_slots[slot]:
                # accepted + rejected = m per spec slot, so the tenant sums
                # conserve against serving_spec_{proposed,accepted}_total
                accepted = int(acc[slot])
                ledger.speculative(req.tenant, accepted=accepted,
                                   rejected=m - accepted)
            retired = False
            emitted = 0
            for j in range(int(counts[slot])):
                t = int(out[slot, j])
                state.tokens.append(t)
                emitted += 1
                self._metrics["tokens"].inc()
                if req.eos_id is not None and t == req.eos_id:
                    self._retire(slot, "eos")
                    retired = True
                    break
                if len(state.tokens) >= state.pending.max_new:
                    self._retire(slot, "length")
                    retired = True
                    break
            if ledger is not None:
                ledger.decode(req.tenant, tokens=emitted, device_s=share)
            if not retired:
                self._pos[slot] += emitted
                self._last[slot] = int(out[slot, emitted - 1])

    def _retire(self, slot: int, reason: str) -> None:
        state = self._slots[slot]
        if self._ledger is not None:
            # page-seconds sample at slot free: pages held x wall time
            self._ledger.release(
                state.pending.request.tenant, pages=state.pages,
                held_s=time.perf_counter() - state.admit_t)
        self._cache.free(slot)
        self._slots[slot] = None
        self._active[slot] = False
        self._spec_on[slot] = False
        self._pos[slot] = 0
        self._last[slot] = 0
        self._temp[slot] = 0.0
        self._topk[slot] = 0
        self._topp[slot] = 1.0
        self._finish(state.pending, state.tokens, reason, state.ttft_s)
        self._refresh_gauges()

    def _finish(self, pending: _Pending, tokens: List[int], reason: str,
                ttft_s: float) -> None:
        self._metrics["requests"].inc()
        pending._resolve(GenerateResult(
            request_id=pending.request.request_id,
            prompt=list(pending.request.prompt),
            tokens=list(tokens),
            finish_reason=reason,
            ttft_s=ttft_s,
            latency_s=time.perf_counter() - pending.enqueue_t,
            trace_id=pending.request.trace_id,
        ))

    def _refresh_gauges(self) -> None:
        self._metrics["active_slots"].set(int(self._active.sum()))
        self._metrics["pages_in_use"].set(self._cache.pages_in_use)
