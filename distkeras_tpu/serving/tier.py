"""Fault-tolerant serving tier: a health-gated router over N replicas.

The reference's serving story ended at one engine behind one ``/generate``
endpoint — a replica crash dropped every in-flight request and took the
service down.  This module is the serving-side twin of the PR-11 fleet
work: the same lease discipline (:class:`~distkeras_tpu.fleet.
FleetMembership` underneath), applied to inference replicas.

* **Health state machine** per replica, driven by probes (flightdeck
  ``/healthz`` + the live queue/slot gauges for HTTP replicas, the
  engine's own ``alive``/``stats()`` for in-process ones)::

      starting ──probe ok──▶ healthy ◀──probe ok── degraded
                                │  probe failed ▲      │ lease expired
                                ▼───────────────┘      ▼
      draining (explicit, during a roll)             dead

  ``dead`` is reversible for a replica that was merely wedged (a later
  successful probe resurrects it, epoch-bumped like a fleet rejoin), and
  immediate for a provably crashed one (:class:`ReplicaDead` from the
  probe: engine crashed, serve-job Popen dead).

* **Least-loaded dispatch** — ``queue_depth + active_slots`` from the
  last probe plus the router's own in-flight count, healthy replicas
  preferred over degraded ones.

* **Failover retry** — a request whose replica died mid-flight is re-run
  on another replica.  Safe because generation is a pure function of
  (params, prompt, knobs, seed): the retried request yields bit-equal
  tokens.  Attempts are capped with jittered exponential backoff, and an
  idempotency discipline guarantees a retry never *double-executes* on a
  slow-but-alive replica: in-process replicas confirm cancellation before
  the retry dispatches (``engine.cancel`` + wait for the handle to
  resolve), HTTP replicas receive the hop budget as ``timeout_s`` so
  their own handler 504s — and self-cancels — no later than the router
  gives up on them.

* **Deadline propagation** — one budget per request, decremented per hop
  and forwarded as ``timeout_s``; when it runs out the router answers 504
  itself instead of stacking N independent timeouts.

* **Load shedding** — when every dispatchable replica is saturated the
  router sheds (503 + ``Retry-After``) instead of queueing unbounded.

* **Rolling checkpoint hot-swap** — :meth:`ServingTier.watch_checkpoints`
  polls the ``CheckpointManager`` directory (manifest commit records, no
  cross-process flush), re-verifies each candidate step's digests at swap
  time (a corrupt one is rejected — ``serving_checkpoint_rejected_total``
  — and the fleet keeps its params), and :meth:`ServingTier.roll` swaps
  the fleet one replica at a time: drain → param swap (shape-stable, zero
  recompiles, zero dropped requests) → wait until the replica probes
  healthy again — so ≥1 replica stays dispatchable throughout.

Everything is observable: ``serving_tier_*`` counters (failovers, hedges,
sheds, hot swaps), a per-replica health gauge, and router-level SLO
histograms (end-to-end latency, attempts per request).
"""

from __future__ import annotations

import dataclasses
import json
import random
import threading
import time
import urllib.error
import urllib.request
import uuid
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from distkeras_tpu import chaos as _chaos
from distkeras_tpu.fleet import FleetMembership
from distkeras_tpu.sanitizer import lockwatch
from distkeras_tpu.serving.engine import EngineCrashed
from distkeras_tpu.serving.frontend import (
    GenerateRequest,
    GenerateResult,
    QueueFull,
)
from distkeras_tpu.telemetry import accounting as _accounting
from distkeras_tpu.telemetry import runtime as _truntime
from distkeras_tpu.telemetry.trace import (
    NOOP_SPAN,
    new_trace_id,
    trace as _trace,
)


def _span_note(span, **kv) -> None:
    """Annotate a live span's args in place (no-op for the disabled-path
    NOOP span) — how an attempt's *outcome* lands on a span that had to
    open before the outcome was known."""
    attrs = getattr(span, "attrs", None)
    if attrs is not None:
        attrs.update(kv)


__all__ = [
    "HttpReplica",
    "LocalReplica",
    "REPLICA_STATES",
    "ReplicaDead",
    "ServingTier",
    "TierDeadline",
    "TierError",
    "TierExhausted",
    "TierSaturated",
    "install_tier_endpoint",
    "tier_metrics",
    "watch_and_swap",
]

#: health states, in gauge-ordinal order
REPLICA_STATES = ("starting", "healthy", "degraded", "draining", "dead")


class ReplicaDead(ConnectionError):
    """A probe's *fatal* verdict: the replica is provably gone (engine
    crashed, serve-job process dead), not merely slow — the router evicts
    it immediately instead of waiting out the lease."""


class TierError(RuntimeError):
    """Base for router-level request failures."""


class TierDeadline(TierError):
    """The request's deadline budget ran out at the router (HTTP 504)."""


class TierSaturated(TierError):
    """Every dispatchable replica is saturated or unavailable — the
    router sheds the request (HTTP 503 + ``Retry-After``)."""


class TierExhausted(TierError):
    """The failover attempt cap was reached without a completed
    generation (HTTP 502)."""


def _ckpt_rejected_counter(registry=None):
    """The swap-time verification rejection counter — shared between the
    router's :meth:`ServingTier.watch_checkpoints` and the replica-side
    :func:`watch_and_swap` so both publication paths count into one name."""
    if registry is None:
        from distkeras_tpu.telemetry.metrics import metrics as registry
    return registry.counter(
        "serving_checkpoint_rejected_total",
        help="checkpoint steps that failed re-verification at swap time "
             "(replicas kept the old params)",
    )


def tier_metrics(registry=None) -> dict:
    """Get-or-create the router's instruments (default: process-global
    registry).  One canonical home for names/help so the router, the
    golden test, and the CI chaos smoke assert the same schema."""
    if registry is None:
        from distkeras_tpu.telemetry.metrics import metrics as registry
    return {
        "ckpt_rejected": _ckpt_rejected_counter(registry),
        "requests": registry.counter(
            "serving_tier_routed_total",
            help="requests completed successfully through the router",
        ),
        "failovers": registry.counter(
            "serving_tier_failovers_total",
            help="request retries after a replica died mid-flight",
        ),
        "hedges": registry.counter(
            "serving_tier_hedges_total",
            help="request retries after a per-hop deadline on a "
                 "slow-but-alive replica (cancellation confirmed first)",
        ),
        "sheds": registry.counter(
            "serving_tier_sheds_total",
            help="requests shed because every replica was saturated",
        ),
        "hot_swaps": registry.counter(
            "serving_tier_hot_swaps_total",
            help="per-replica checkpoint hot-swaps applied by rolls",
        ),
        "roll_failures": registry.counter(
            "serving_tier_roll_failures_total",
            help="checkpoint rolls that failed (load error or drain timeout)",
        ),
        "deadline_expired": registry.counter(
            "serving_tier_deadline_expired_total",
            help="requests 504ed at the router when their budget ran out",
        ),
        "replicas_healthy": registry.gauge(
            "serving_tier_replicas_healthy",
            help="replicas currently in the healthy state",
        ),
        "latency": registry.histogram(
            "serving_tier_latency_seconds",
            help="end-to-end router latency (admission to final result, "
                 "failovers included)",
        ),
        "attempts": registry.histogram(
            "serving_tier_request_attempts",
            help="dispatch attempts per completed request (1 = no failover)",
            buckets=(1, 2, 3, 4, 5, 8),
        ),
    }


# ---------------------------------------------------------------- replicas


class LocalReplica:
    """An in-process :class:`~distkeras_tpu.serving.engine.ServingEngine`
    behind the replica interface — what tests, bench, and the CI chaos
    smoke route over (deterministic, no sockets)."""

    def __init__(self, engine, name: str = ""):
        self.engine = engine
        self.name = name or f"local-{id(engine):x}"

    def probe(self, timeout: float = 1.0) -> Dict[str, float]:
        """Health + load snapshot; raises :class:`ReplicaDead` for a
        crashed engine, ``TimeoutError`` when the probe itself exceeds
        ``timeout`` (the chaos ``stall_http`` site lands here — a wedged
        ``/healthz`` must degrade the replica, not wedge the prober)."""
        t0 = time.perf_counter()
        if _chaos.enabled():
            _chaos.fault("http")
        if not self.engine.alive:
            raise ReplicaDead(f"replica {self.name}: engine crashed")
        if time.perf_counter() - t0 > timeout:
            raise TimeoutError(
                f"replica {self.name}: health probe exceeded {timeout}s")
        return self.engine.stats()

    def submit(self, request: GenerateRequest):
        return self.engine.submit(request)

    def cancel(self, handle) -> bool:
        """Cancel and *confirm*: returns ``True`` only once the handle has
        resolved — i.e. the engine provably stopped executing the request
        — which is what licenses an idempotent retry elsewhere."""
        self.engine.cancel(handle)
        return handle.result(timeout=5.0) is not None

    def hot_swap(self, model, params=None, timeout: float = 30.0) -> None:
        self.engine.hot_swap(model, params, timeout=timeout)

    def close(self) -> None:
        self.engine.stop()


class _HttpPending:
    """One in-flight HTTP generate call, result()-compatible with the
    engine's pending handle."""

    def __init__(self, url: str, payload: dict, timeout_s: Optional[float]):
        self._url = url
        self._payload = payload
        # socket deadline trails the propagated budget so the replica's own
        # 504 (its self-cancel acknowledgement) arrives before we give up
        self._timeout = (timeout_s + 2.0) if timeout_s else 30.0
        # trace context rides the hop as headers too, so even a replica
        # frontend that drops unknown body fields keeps the correlation;
        # X-DK-Parent-Span names the router-side span the replica's
        # serving.http_request span nests under in the merged trace
        self._headers = {"Content-Type": "application/json"}
        if payload.get("request_id"):
            self._headers["X-DK-Request-Id"] = payload["request_id"]
        if payload.get("trace_id"):
            self._headers["X-DK-Trace-Id"] = payload["trace_id"]
            self._headers["X-DK-Parent-Span"] = "tier.attempt"
        self._event = threading.Event()
        self._result: Optional[GenerateResult] = None
        self._error: Optional[Exception] = None
        self.got_504 = False
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self) -> None:
        try:
            if _chaos.enabled():
                _chaos.fault("http")  # stall_http: wedge the outbound hop
            data = json.dumps(self._payload).encode("utf-8")
            req = urllib.request.Request(
                self._url, data=data, headers=self._headers)
            with urllib.request.urlopen(req, timeout=self._timeout) as resp:
                body = resp.read().decode("utf-8", "replace")
            self._result = GenerateResult(**json.loads(body))
        except urllib.error.HTTPError as e:
            body = e.read().decode("utf-8", "replace")
            try:
                payload = json.loads(body)
            except ValueError:
                payload = {}
            if e.code == 504:
                # the replica hit its propagated deadline and self-cancelled
                self.got_504 = True
                self._error = TimeoutError(payload.get("error") or "hop 504")
            elif e.code == 503 and "finish_reason" in payload:
                self._result = GenerateResult(**payload)  # engine aborted
            elif e.code == 503:
                self._error = QueueFull(payload.get("error") or "replica 503")
            elif e.code == 400:
                self._error = ValueError(payload.get("error") or body)
            else:
                self._error = ConnectionError(f"HTTP {e.code}: {body[:200]}")
        except (OSError, ValueError, TypeError) as e:
            self._error = ConnectionError(f"{type(e).__name__}: {e}")
        finally:
            self._event.set()

    def result(self, timeout: Optional[float] = None):
        """The :class:`GenerateResult`, ``None`` on timeout *or* a
        replica-side 504 (both mean "no result, replica not executing it
        past its budget"); raises the transport error otherwise."""
        if not self._event.wait(timeout):
            return None
        if self._error is not None:
            if self.got_504:
                return None
            raise self._error
        return self._result


class HttpReplica:
    """A serving replica behind ``http://<address>/generate`` (its own
    flightdeck exporter).  When ``job`` (a daemon
    :class:`~distkeras_tpu.job_deployment.Job` handle) is given, the probe
    consults the daemon first — a dead serve-job Popen (status
    ``failed``/``finished``) is :class:`ReplicaDead` *immediately*, no
    waiting for ``/healthz`` timeouts to burn the lease."""

    def __init__(self, address: str, name: str = "", job=None,
                 path: str = "/generate"):
        self.address = address
        self.name = name or address
        self.job = job
        self.path = path

    def probe(self, timeout: float = 1.0) -> Dict[str, float]:
        if self.job is not None:
            status = (self.job.status() or {}).get("status")
            if status in ("failed", "finished", "stopped"):
                raise ReplicaDead(
                    f"replica {self.name}: serve job is {status}")
        with urllib.request.urlopen(
                f"http://{self.address}/healthz", timeout=timeout) as resp:
            json.loads(resp.read().decode("utf-8", "replace"))
        with urllib.request.urlopen(
                f"http://{self.address}/vars", timeout=timeout) as resp:
            snap = json.loads(
                resp.read().decode("utf-8", "replace")).get("metrics", {})

        def _gauge(metric: str) -> float:
            return float((snap.get(metric) or {}).get("value") or 0.0)

        return {
            "queue_depth": _gauge("serving_queue_depth"),
            "active_slots": _gauge("serving_active_slots"),
        }

    def submit(self, request: GenerateRequest) -> _HttpPending:
        payload = dataclasses.asdict(request)
        return _HttpPending(
            f"http://{self.address}{self.path}", payload, request.timeout_s)

    def cancel(self, handle: _HttpPending) -> bool:
        """There is no out-of-band abort over HTTP; idempotency rides the
        propagated deadline instead — only a replica-side 504 (it already
        self-cancelled) confirms the replica stopped executing."""
        return handle.got_504

    def hot_swap(self, model, params=None, timeout: float = 30.0) -> None:
        raise NotImplementedError(
            "HTTP replicas hot-swap autonomously via watch_and_swap() in "
            "their serve script, not through the router")

    def close(self) -> None:
        pass


class _Entry:
    """Router-side record for one replica."""

    __slots__ = ("replica", "name", "index", "wid", "state", "failures",
                 "stats", "inflight", "last_error")

    def __init__(self, replica, index: int):
        self.replica = replica
        self.name = replica.name
        self.index = index
        self.wid = f"{index}:{self.name}"
        self.state = "starting"
        self.failures = 0
        self.stats: Dict[str, float] = {}
        self.inflight = 0
        self.last_error: Optional[str] = None

    def load(self) -> float:
        return (float(self.stats.get("queue_depth") or 0.0)
                + float(self.stats.get("active_slots") or 0.0)
                + float(self.inflight))


# ------------------------------------------------------------------ router


class ServingTier:
    """The request router.  ``replicas`` may mix :class:`LocalReplica`,
    :class:`HttpReplica`, and raw ``ServingEngine`` instances (wrapped
    automatically).  Probing runs from a daemon thread after
    :meth:`start`; without it, the first dispatch runs one synchronous
    probe round so a freshly built tier is usable in tests."""

    def __init__(self, replicas: Sequence, *,
                 probe_interval: float = 0.2,
                 probe_timeout: float = 1.0,
                 probe_misses: int = 3,
                 max_attempts: int = 3,
                 default_deadline_s: float = 30.0,
                 hop_timeout_s: Optional[float] = None,
                 backoff_s: float = 0.02,
                 backoff_cap_s: float = 0.25,
                 registry=None,
                 slo_objectives: Optional[Sequence] = None,
                 traffic_log=None,
                 clock: Callable[[], float] = time.monotonic):
        if not replicas:
            raise ValueError("a serving tier needs at least one replica")
        wrapped = []
        for i, rep in enumerate(replicas):
            if not hasattr(rep, "probe"):
                rep = LocalReplica(rep, name=f"replica-{i}")
            wrapped.append(rep)
        self._entries = [_Entry(rep, i) for i, rep in enumerate(wrapped)]
        self.probe_interval = float(probe_interval)
        self.probe_timeout = float(probe_timeout)
        self.max_attempts = int(max_attempts)
        self.default_deadline_s = float(default_deadline_s)
        self.hop_timeout_s = hop_timeout_s
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self._clock = clock
        self._metrics = tier_metrics(registry)
        self._registry = registry
        # per-tenant accounting (None when DISTKERAS_ACCOUNTING is off):
        # the router bills each request exactly once at completion —
        # failed failover attempts fold into that one bill, never counted
        # per attempt
        self._acct = _accounting.maybe_ledger(registry)
        # router-level online capture (satellite of the accounting plane):
        # the tenant is resolved once here and inherited by capture and
        # accounting alike, so a replica frontend no longer has to carry
        # its own hook to close the serve->train loop
        self._traffic_log = traffic_log
        # replica liveness rides the fleet lease machinery: a successful
        # probe is a heartbeat; a replica that misses probe_misses probes'
        # worth of lease is swept exactly like a preempted trainer
        self._membership = FleetMembership(
            lease=self.probe_interval + self.probe_timeout,
            miss_tolerance=int(probe_misses), clock=clock)
        self._cv = lockwatch.maybe_wrap(
            threading.Condition(), "serving.tier")
        self._probed = False
        self._stop_evt: Optional[threading.Event] = None
        self._probe_thread: Optional[threading.Thread] = None
        self._watchers: List[Tuple[threading.Event, threading.Thread]] = []
        # SLO evaluation rides the probe loop; None until start() and only
        # ever non-None when telemetry + DISTKERAS_ROLLUP are on, so the
        # flag-off dispatch/probe path is untouched.
        self._slo_objectives = slo_objectives
        self._slo = None

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Run one synchronous probe round (the tier is dispatchable on
        return), then keep probing from a daemon thread."""
        self.probe_once()
        with self._cv:
            if self._probe_thread is not None:
                return
            self._stop_evt = threading.Event()
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="serving-tier-probe",
                daemon=True)
            self._probe_thread.start()
        if self._slo is None:
            from distkeras_tpu.telemetry import slo as _slo

            objectives = self._slo_objectives
            if objectives is None:
                objectives = _slo.default_serving_objectives()
            self._slo = _slo.maybe_engine(
                objectives, source="serving_tier", registry=self._registry)

    def stop(self, close_replicas: bool = False) -> None:
        """Stop the prober and any checkpoint watchers; optionally stop
        the replicas themselves (in-process engines)."""
        with self._cv:
            stop_evt, self._stop_evt = self._stop_evt, None
            thread, self._probe_thread = self._probe_thread, None
            watchers, self._watchers = list(self._watchers), []
        if stop_evt is not None:
            stop_evt.set()
        for evt, _t in watchers:
            evt.set()
        if thread is not None:
            thread.join(timeout=5)
        for _evt, t in watchers:
            t.join(timeout=5)
        if close_replicas:
            for entry in self._entries:
                entry.replica.close()

    def _probe_loop(self) -> None:
        stop = self._stop_evt
        while stop is not None and not stop.wait(self.probe_interval):
            try:
                self.probe_once()
                if self._slo is not None:
                    self._slo.evaluate()
            except Exception:  # noqa: BLE001 — a failed sweep/export must
                # not kill the supervisor; the next round retries it
                continue

    # ------------------------------------------------------------- probing

    def probe_once(self) -> None:
        """One probe round over every replica + a lease sweep."""
        for entry in self._entries:
            self._probe_entry(entry)
        with self._cv:
            evicted = set(self._membership.sweep())
            for entry in self._entries:
                if entry.wid in evicted and entry.state != "dead":
                    entry.state = "dead"
            self._probed = True
        self._export_health()

    def _probe_entry(self, entry: _Entry) -> None:
        try:
            info = entry.replica.probe(timeout=self.probe_timeout)
        except ReplicaDead as e:
            with self._cv:
                entry.failures += 1
                entry.last_error = str(e)
                if entry.state != "dead":
                    entry.state = "dead"
                    self._membership.deregister(entry.wid)
            return
        except Exception as e:  # noqa: BLE001 — any probe failure degrades
            with self._cv:
                entry.failures += 1
                entry.last_error = str(e)
                if entry.state == "healthy":
                    entry.state = "degraded"
                # no heartbeat: the lease keeps draining toward eviction
            return
        with self._cv:
            entry.failures = 0
            entry.stats = dict(info or {})
            entry.last_error = None
            if not self._membership.heartbeat(entry.wid):
                # first probe, or a rejoin after eviction (epoch bumps)
                self._membership.register(
                    entry.wid, host=entry.name,
                    meta={"role": "serving", "index": entry.index})
            if entry.state in ("starting", "degraded", "dead"):
                entry.state = "healthy"

    def _export_health(self) -> None:
        if self._registry is None:
            from distkeras_tpu.telemetry.metrics import metrics as registry
        else:
            registry = self._registry
        with self._cv:
            states = [(e.index, e.state) for e in self._entries]
        healthy = sum(1 for _i, s in states if s == "healthy")
        self._metrics["replicas_healthy"].set(healthy)
        for index, state in states:
            registry.gauge(
                f"serving_tier_replica_health_{index}",
                help="replica health ordinal (0=starting 1=healthy "
                     "2=degraded 3=draining 4=dead)",
            ).set(REPLICA_STATES.index(state))

    def _mark_dead(self, entry: _Entry, why: str) -> None:
        with self._cv:
            entry.last_error = why
            if entry.state != "dead":
                entry.state = "dead"
                self._membership.deregister(entry.wid)
        self._export_health()

    # ------------------------------------------------------------ dispatch

    def _pick(self, exclude: Dict[str, str]) -> Optional[_Entry]:
        with self._cv:
            probed = self._probed
        if not probed:
            self.probe_once()
        with self._cv:
            pools: Dict[str, List[_Entry]] = {"healthy": [], "degraded": []}
            for entry in self._entries:
                if entry.name in exclude or entry.state not in pools:
                    continue
                pools[entry.state].append(entry)
            for state in ("healthy", "degraded"):
                if pools[state]:
                    return min(pools[state],
                               key=lambda e: (e.load(), e.index))
        return None

    def _backoff(self, attempt: int, deadline: float) -> None:
        delay = min(self.backoff_cap_s,
                    self.backoff_s * (2 ** max(0, attempt - 1)))
        delay *= 0.5 + 0.5 * random.random()  # jitter against retry storms
        delay = min(delay, max(0.0, deadline - self._clock()))
        if delay > 0:
            time.sleep(delay)

    def generate(self, prompt=None, request: Optional[GenerateRequest] = None,
                 deadline_s: Optional[float] = None,
                 **knobs) -> GenerateResult:
        """Route one request.  Pass a ``prompt`` (+ sampling ``knobs``) or
        a prebuilt ``request``.  Raises :class:`TierDeadline` (budget ran
        out), :class:`TierSaturated` (shed), or :class:`TierExhausted`
        (attempt cap)."""
        if request is None:
            if prompt is None:
                raise ValueError("need a prompt or a GenerateRequest")
            request = GenerateRequest(
                prompt=[int(t) for t in prompt], **knobs)
        return self.dispatch(request, deadline_s=deadline_s)

    def dispatch(self, request: GenerateRequest,
                 deadline_s: Optional[float] = None) -> GenerateResult:
        budget = (deadline_s if deadline_s is not None
                  else (request.timeout_s or self.default_deadline_s))
        deadline = self._clock() + float(budget)
        if not request.request_id:
            # the idempotency key: every hop of this request carries the
            # same id, so replica-side logs/metrics can correlate retries
            request = dataclasses.replace(
                request, request_id=uuid.uuid4().hex)
        if not request.trace_id:
            # the correlation key: unlike request_id it is never used for
            # idempotency decisions, only to join spans across processes
            request = dataclasses.replace(request, trace_id=new_trace_id())
        root = NOOP_SPAN
        if _truntime.enabled():
            attrs = dict(request_id=request.request_id,
                         trace_id=request.trace_id,
                         budget_s=round(float(budget), 3))
            if request.tenant:
                attrs["tenant"] = request.tenant
            root = _trace.span("tier.request", **attrs)
        with _trace.bind(trace_id=request.trace_id,
                         request_id=request.request_id), root:
            try:
                result = self._dispatch(request, budget, deadline)
            except TierError as e:
                _span_note(root, outcome=type(e).__name__)
                raise
            _span_note(root, outcome="ok")
            return result

    def _dispatch(self, request: GenerateRequest, budget: float,
                  deadline: float) -> GenerateResult:
        t0 = time.perf_counter()
        attempts = 0
        # replicas excluded for the rest of THIS request: saturated, or
        # possibly still executing an uncancelled earlier hop
        exclude: Dict[str, str] = {}
        while True:
            remaining = deadline - self._clock()
            if remaining <= 0:
                self._metrics["deadline_expired"].inc()
                raise TierDeadline(
                    f"deadline ({budget}s) exhausted after "
                    f"{attempts} attempt(s)")
            if attempts >= self.max_attempts:
                raise TierExhausted(
                    f"request failed after {attempts} attempts "
                    f"(cap {self.max_attempts})")
            entry = self._pick(exclude)
            if entry is None:
                self._metrics["sheds"].inc()
                raise TierSaturated(
                    "no dispatchable replica (all saturated, excluded, "
                    "or unhealthy)")
            hop = (remaining if self.hop_timeout_s is None
                   else min(remaining, self.hop_timeout_s))
            # deadline propagation: the replica gets the hop budget, not
            # its own independent timeout — over HTTP its handler 504s
            # (and self-cancels) exactly when the router stops waiting
            hop_request = dataclasses.replace(request, timeout_s=hop)
            attempts += 1
            # the attempt span stack-nests under tier.request (same
            # thread); its outcome arg is what dktrace critical-path
            # renders as the per-attempt verdict
            aspan = NOOP_SPAN
            if _truntime.enabled():
                aspan = _trace.span(
                    "tier.attempt", attempt=attempts, replica=entry.name,
                    hop_s=round(float(hop), 3))
            with aspan:
                try:
                    handle = entry.replica.submit(hop_request)
                except QueueFull:
                    _span_note(aspan, outcome="saturated")
                    exclude[entry.name] = "saturated"
                    attempts -= 1  # saturation is a shed decision, not a hop
                    continue
                except (EngineCrashed, ReplicaDead, ConnectionError,
                        OSError) as e:
                    _span_note(aspan, outcome="dead_on_submit")
                    self._mark_dead(entry, f"submit failed: {e}")
                    self._metrics["failovers"].inc()
                    self._backoff(attempts, deadline)
                    continue
                with self._cv:
                    entry.inflight += 1
                try:
                    try:
                        result = handle.result(timeout=hop)
                    except QueueFull:  # HTTP replicas surface 503 at result
                        _span_note(aspan, outcome="saturated")
                        exclude[entry.name] = "saturated"
                        attempts -= 1
                        continue
                    except (ConnectionError, OSError) as e:
                        _span_note(aspan, outcome="transport_error")
                        self._probe_entry(entry)  # dead or flaky? decide now
                        self._export_health()
                        self._metrics["failovers"].inc()
                        entry.last_error = str(e)
                        self._backoff(attempts, deadline)
                        continue
                finally:
                    with self._cv:
                        entry.inflight -= 1
                if result is None:
                    # slow hop: hedge — but only once the replica provably
                    # stopped executing (confirmed cancel / replica-side 504)
                    confirmed = entry.replica.cancel(handle)
                    if confirmed:
                        late = handle.result(timeout=0)
                        if late is not None and late.finish_reason != "aborted":
                            result = late  # finished inside the cancel window
                        else:
                            _span_note(aspan, outcome="hedge")
                            self._metrics["hedges"].inc()
                            self._backoff(attempts, deadline)
                            continue
                    else:
                        _span_note(aspan, outcome="hedge_uncancelled")
                        exclude[entry.name] = "uncancelled"
                        self._metrics["hedges"].inc()
                        self._backoff(attempts, deadline)
                        continue
                if result.finish_reason == "aborted":
                    # the replica stopped/crashed with the request in flight
                    # — THE failover case; re-probe so routing reacts now
                    _span_note(aspan, outcome="aborted_failover")
                    self._probe_entry(entry)
                    self._export_health()
                    self._metrics["failovers"].inc()
                    self._backoff(attempts, deadline)
                    continue
                _span_note(aspan, outcome="ok")
                latency = time.perf_counter() - t0
                self._metrics["latency"].observe(latency)
                self._metrics["attempts"].observe(attempts)
                self._metrics["requests"].inc()
                if self._acct is not None:
                    self._acct.request(request.tenant, attempts=attempts,
                                       latency_s=latency)
                self._offer_capture(request, result)
                return result

    # ------------------------------------------------------ online capture

    def attach_traffic_log(self, traffic_log) -> None:
        """Attach (or replace) the router-level capture hook after
        construction — what :func:`install_tier_endpoint` uses when handed
        a ``traffic_log``."""
        self._traffic_log = traffic_log

    def _offer_capture(self, request: GenerateRequest, result) -> None:
        """Offer a completed generation to the capture ring.  Strictly
        best-effort: a capture fault is counted and swallowed, never
        surfaced to the caller — routing must not fail because capture
        did (same contract as the frontend hook)."""
        log = self._traffic_log
        if log is None:
            return
        try:
            log.record(request, result)
        except Exception:  # noqa: BLE001 — capture is best-effort
            from distkeras_tpu import telemetry

            if telemetry.enabled():
                from distkeras_tpu.online.capture import online_metrics

                online_metrics()["capture_errors"].inc()

    # ----------------------------------------------------- rolling hot-swap

    def roll(self, model, params=None, *, timeout: float = 60.0) -> int:
        """Hot-swap every live replica to ``(model, params)``, strictly one
        at a time: mark it draining (the router stops dispatching to it),
        let the engine drain its slots and swap in place (zero dropped
        requests), then wait until it probes healthy again before touching
        the next — so ≥1 replica stays dispatchable throughout.  Returns
        the number of replicas swapped."""
        swapped = 0
        for entry in self._entries:
            with self._cv:
                if entry.state == "dead":
                    continue
                entry.state = "draining"
            self._export_health()
            try:
                entry.replica.hot_swap(model, params, timeout=timeout)
            except Exception as e:
                self._metrics["roll_failures"].inc()
                with self._cv:
                    entry.state = "starting"
                raise TierError(
                    f"roll failed at replica {entry.name}: {e}") from e
            self._metrics["hot_swaps"].inc()
            with self._cv:
                entry.state = "starting"
            if not self._await_healthy(entry, timeout):
                self._metrics["roll_failures"].inc()
                raise TierError(
                    f"replica {entry.name} did not return to healthy "
                    f"within {timeout}s after its swap")
            swapped += 1
        return swapped

    def _await_healthy(self, entry: _Entry, timeout: float) -> bool:
        deadline = self._clock() + timeout
        while True:
            self._probe_entry(entry)
            self._export_health()
            with self._cv:
                if entry.state == "healthy":
                    return True
            if self._clock() >= deadline:
                return False
            time.sleep(0.01)

    def watch_checkpoints(self, directory: str, loader,
                          poll_interval: float = 0.25) -> threading.Thread:
        """Roll the fleet whenever a newer checkpoint commits in
        ``directory``.  ``loader(step) -> (model, params)`` materializes
        the params (e.g. ``restore_center``).  The watcher only surfaces
        published steps that pass a fast size check, and each surfaced
        step is re-verified against its manifest digests *at swap time* —
        a step whose bytes rotted between publish and swap is rejected
        (``serving_checkpoint_rejected_total``) with the fleet untouched:
        old params keep serving, no request is dropped.  Watching stops
        with :meth:`stop`."""
        from distkeras_tpu.checkpoint import CheckpointWatcher, verify_failure

        watcher = CheckpointWatcher(directory)
        stop = threading.Event()

        def _watch():
            while not stop.wait(poll_interval):
                try:
                    step = watcher.poll()
                    if step is None:
                        continue
                    if verify_failure(directory, step, "full") is not None:
                        self._metrics["ckpt_rejected"].inc()
                        continue
                    try:
                        model, params = loader(step)
                        self.roll(model, params)
                    except Exception:  # noqa: BLE001 — a bad checkpoint
                        # must not kill the watcher; counted separately
                        self._metrics["roll_failures"].inc()
                except Exception:  # noqa: BLE001 — a transient poll/verify
                    # error (fs flake, torn manifest) must not kill the
                    # watcher either; the next round re-polls
                    continue

        thread = threading.Thread(
            target=_watch, name="serving-tier-ckpt-watch", daemon=True)
        thread.start()
        with self._cv:
            self._watchers.append((stop, thread))
        return thread

    # ---------------------------------------------------------- inspection

    def states(self) -> Dict[str, str]:
        with self._cv:
            return {e.name: e.state for e in self._entries}

    def snapshot(self) -> dict:
        """JSON-safe health/load view (the ``/tier`` endpoint and the
        daemon's ``tier_status`` verb)."""
        with self._cv:
            membership = self._membership.snapshot()
            replicas = [{
                "name": e.name,
                "index": e.index,
                "state": e.state,
                "load": e.load(),
                "queue_depth": float(e.stats.get("queue_depth") or 0.0),
                "active_slots": float(e.stats.get("active_slots") or 0.0),
                "inflight": e.inflight,
                "failures": e.failures,
                "last_error": e.last_error,
            } for e in self._entries]
        return {
            "replicas": replicas,
            "healthy": sum(1 for r in replicas if r["state"] == "healthy"),
            "epoch": membership["epoch"],
            "evictions": membership["evictions"],
        }


# --------------------------------------------------- replica-side hot-swap


def watch_and_swap(engine, directory: str, loader,
                   poll_interval: float = 0.25):
    """Autonomous per-replica hot-swap: poll ``directory`` for newly
    published checkpoints and ``engine.hot_swap`` to each — how an HTTP
    replica's serve script tracks the trainer without router involvement
    (the router only gates health around the swap's drain).  Each step is
    re-verified against its manifest digests right before the swap; a
    failing one is rejected (``serving_checkpoint_rejected_total``) and
    the engine keeps its current params.  Returns a zero-arg stopper."""
    from distkeras_tpu.checkpoint import CheckpointWatcher, verify_failure

    watcher = CheckpointWatcher(directory)
    stop = threading.Event()

    def _watch():
        while not stop.wait(poll_interval):
            try:
                step = watcher.poll()
                if step is None:
                    continue
                if verify_failure(directory, step, "full") is not None:
                    _ckpt_rejected_counter().inc()
                    continue
                model, params = loader(step)
                engine.hot_swap(model, params)
            except Exception:  # noqa: BLE001 — keep watching; a transient
                # poll/verify error is retried next round
                continue

    thread = threading.Thread(
        target=_watch, name="serving-replica-ckpt-watch", daemon=True)
    thread.start()

    def stopper():
        stop.set()
        thread.join(timeout=5)

    return stopper


# ---------------------------------------------------------------- endpoint


def install_tier_endpoint(tier: ServingTier, path: str = "/generate",
                          status_path: str = "/tier",
                          traffic_log=None) -> str:
    """Mount the router on the flightdeck exporter: ``path`` routes
    requests across the tier (maps :class:`TierSaturated` → 503 +
    ``Retry-After``, :class:`TierDeadline` → 504, :class:`TierExhausted`
    → 502), ``status_path`` serves the health snapshot.  ``traffic_log``
    attaches router-level online capture — the preferred hook point, so
    tenant resolution, accounting, and capture all happen once at the
    router instead of per replica frontend.  Returns the mounted path."""
    from distkeras_tpu.serving.frontend import _parse_request
    from distkeras_tpu.telemetry.flightdeck import server as _server

    if traffic_log is not None:
        tier.attach_traffic_log(traffic_log)

    def handle(request):
        try:
            req = _parse_request(request)
        except (ValueError, KeyError, json.JSONDecodeError) as e:
            body = json.dumps({"error": f"{type(e).__name__}: {e}"})
            return ("application/json", body, 400)
        try:
            result = tier.dispatch(req)
        except TierSaturated as e:
            return ("application/json", json.dumps({"error": str(e)}), 503,
                    {"Retry-After": "1"})
        except TierDeadline as e:
            return ("application/json", json.dumps({"error": str(e)}), 504)
        except TierExhausted as e:
            return ("application/json", json.dumps({"error": str(e)}), 502)
        except ValueError as e:
            return ("application/json", json.dumps({"error": str(e)}), 400)
        return ("application/json", result.to_json(), 200)

    _server.add_endpoint(path, handle)
    _server.add_endpoint(
        status_path,
        lambda: ("application/json", json.dumps(tier.snapshot())))
    return path
