"""Admission layer for the serving engine: requests, queueing, HTTP.

Three pieces, all host-side:

* :class:`GenerateRequest` / :class:`GenerateResult` — the wire-shaped
  request/response dataclasses (sampling knobs, per-request seed, EOS id).
* :class:`RequestQueue` — a bounded queue with **backpressure rejection**:
  ``put`` raises :class:`QueueFull` instead of blocking, so an overloaded
  engine sheds load at admission (the HTTP layer maps it to 503) rather
  than stacking unbounded latency.
* :func:`install_http_endpoint` — mounts ``/generate`` on the flightdeck
  exporter via :func:`telemetry.flightdeck.add_endpoint`, accepting GET
  query parameters or a POST JSON body.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from collections import deque
from typing import List, Optional
from urllib.parse import parse_qs

__all__ = [
    "GenerateRequest",
    "GenerateResult",
    "QueueFull",
    "RequestQueue",
    "install_http_endpoint",
    "serve_flags",
]


class QueueFull(Exception):
    """Raised by :meth:`RequestQueue.put` when the queue is at capacity —
    the backpressure signal (HTTP layer: 503)."""


@dataclasses.dataclass
class GenerateRequest:
    """One generation request.

    ``temperature <= 0`` (default) means greedy decode; ``seed`` fixes the
    sampling RNG chain so a request's tokens are deterministic regardless
    of what else shares the batch; ``eos_id`` retires the request early
    when that token is emitted.  ``speculative`` opts a single request in
    (True) or out (False) of the engine's draft-model fast path; None
    (default) follows the engine — speculative whenever it has a draft.
    ``timeout_s`` is the caller's *remaining* deadline budget: the serving
    tier decrements it per hop so a replica's HTTP handler times out (and
    self-cancels) no later than the router's own 504 — one deadline,
    propagated, instead of stacked independent timeouts.  ``trace_id``
    correlates every span the request produces across router, replica, and
    engine (minted at the first hop that sees the request, carried over the
    HTTP hop in the body and as ``X-DK-Trace-Id``); ``request_id`` stays
    the idempotency key.  Both ride trace-span args, never metric labels
    (dklint DK117).  ``tenant`` names the client on whose behalf the
    request runs — the accounting key: the per-tenant usage ledger
    (:mod:`distkeras_tpu.telemetry.accounting`) bills tokens, queue wait,
    KV page-seconds, and device-seconds to it, and the online capture
    layer's per-tenant quotas/rates meter on it
    (:mod:`distkeras_tpu.online`).  Resolved once at the outermost hop
    that sees the request (router or frontend, from the body or the
    ``x-dk-tenant`` header) and inherited unchanged by every inner hop;
    empty means untagged (all untagged traffic shares one
    ``__untagged__`` bucket).  Like the ids it rides trace-span args and
    the ledger's bounded table, never raw metric labels (DK117).
    """

    prompt: List[int]
    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    eos_id: Optional[int] = None
    request_id: str = ""
    speculative: Optional[bool] = None
    timeout_s: Optional[float] = None
    trace_id: str = ""
    tenant: str = ""

    def validate(self) -> None:
        if not self.prompt:
            raise ValueError("prompt must be non-empty")
        if any(int(t) < 0 for t in self.prompt):
            raise ValueError("prompt token ids must be >= 0")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not (0.0 <= self.top_p <= 1.0):
            raise ValueError(f"top_p must be in [0, 1], got {self.top_p}")
        if self.timeout_s is not None and self.timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {self.timeout_s}")


@dataclasses.dataclass
class GenerateResult:
    """Engine output for one request.  ``tokens`` excludes the prompt;
    ``finish_reason`` is ``"eos"``, ``"length"``, or ``"aborted"`` (engine
    stopped with the request in flight)."""

    request_id: str
    prompt: List[int]
    tokens: List[int]
    finish_reason: str
    ttft_s: float = 0.0
    latency_s: float = 0.0
    trace_id: str = ""

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))


class RequestQueue:
    """Bounded FIFO with reject-on-full semantics.

    The engine's admission loop is the single consumer; any thread may
    produce.  ``put`` never blocks — a full queue is an *error* the caller
    must surface (backpressure), not a wait."""

    def __init__(self, maxsize: int = 64):
        if maxsize < 1:
            raise ValueError("maxsize must be >= 1")
        self.maxsize = int(maxsize)
        self._items: deque = deque()
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._items)

    def put(self, item) -> None:
        with self._lock:
            if len(self._items) >= self.maxsize:
                raise QueueFull(
                    f"serving queue at capacity ({self.maxsize}); retry later"
                )
            self._items.append(item)

    def pop(self):
        """Next item or ``None`` when empty (engine loop polls between
        decode steps; it never blocks on the queue)."""
        with self._lock:
            if not self._items:
                return None
            return self._items.popleft()

    def remove(self, item) -> bool:
        """Remove a queued item (identity match) before the engine admits
        it; ``False`` if it is no longer queued.  The cancellation fast
        path: a request that never reached a slot frees nothing."""
        with self._lock:
            for i, queued in enumerate(self._items):
                if queued is item:
                    del self._items[i]
                    return True
            return False

    def requeue_front(self, item) -> None:
        """Put a popped item back at the head — the engine's head-of-line
        blocking when the page pool can't fit it yet.  May transiently
        exceed ``maxsize`` by the one in-flight item; that's the popped
        item returning, not new admission."""
        with self._lock:
            self._items.appendleft(item)


# ---------------------------------------------------------------- HTTP


def _parse_tristate(value) -> Optional[bool]:
    """``speculative`` over the wire: absent/empty -> None (engine default),
    otherwise the usual JSON/query truthy spellings."""
    if value in (None, "", "None", "null"):
        return None
    if isinstance(value, bool):
        return value
    return str(value).strip().lower() in ("1", "true", "yes", "on")


def serve_flags() -> dict:
    """Engine construction knobs passed down by the job daemon's ``serve``
    verb (``Job.serve(flags=...)``) as the ``DISTKERAS_SERVE_FLAGS`` JSON
    env var — e.g. ``{"spec_tokens": 4, "num_slots": 8}``.  Serve scripts
    splat this into the engine: ``ServingEngine(model, params,
    **serve_flags())``.  Returns ``{}`` when unset or unparseable (a broken
    deploy flag should degrade to defaults, not kill the serving job)."""
    import os

    try:
        flags = json.loads(os.environ.get("DISTKERAS_SERVE_FLAGS") or "{}")
    except ValueError:
        return {}
    return flags if isinstance(flags, dict) else {}


def _parse_request(request: dict) -> GenerateRequest:
    """Build a :class:`GenerateRequest` from the flightdeck request dict
    (``method``/``query``/``body``/``headers``).  GET:
    ``prompt=1,2,3&max_new_tokens=8``; POST: the same fields as a JSON
    object with ``prompt`` a list.  ``request_id``/``trace_id`` fall back
    to the ``X-DK-Request-Id``/``X-DK-Trace-Id`` headers the router's HTTP
    hop sets, so trace context survives even a body that omits them."""
    if request.get("method") == "POST":
        payload = json.loads(request.get("body") or "{}")
    else:
        qs = parse_qs(request.get("query") or "")
        payload = {k: v[-1] for k, v in qs.items()}
        if "prompt" in payload:
            payload["prompt"] = [
                int(t) for t in str(payload["prompt"]).split(",") if t != ""
            ]
    req = GenerateRequest(
        prompt=[int(t) for t in payload.get("prompt", [])],
        max_new_tokens=int(payload.get("max_new_tokens", 16)),
        temperature=float(payload.get("temperature", 0.0)),
        top_k=int(payload.get("top_k", 0)),
        top_p=float(payload.get("top_p", 1.0)),
        seed=int(payload.get("seed", 0)),
        eos_id=(None if payload.get("eos_id") in (None, "", "None")
                else int(payload["eos_id"])),
        request_id=str(payload.get("request_id", "")),
        speculative=_parse_tristate(payload.get("speculative")),
        timeout_s=(None if payload.get("timeout_s") in (None, "", "None")
                   else float(payload["timeout_s"])),
        trace_id=str(payload.get("trace_id", "")),
        tenant=str(payload.get("tenant", "")),
    )
    headers = request.get("headers") or {}
    if not req.request_id:
        req.request_id = str(headers.get("x-dk-request-id", ""))
    if not req.trace_id:
        req.trace_id = str(headers.get("x-dk-trace-id", ""))
    if not req.tenant:
        req.tenant = str(headers.get("x-dk-tenant", ""))
    req.validate()
    return req


def install_http_endpoint(engine, path: str = "/generate",
                          timeout: Optional[float] = None,
                          traffic_log=None) -> str:
    """Mount a ``/generate`` endpoint for ``engine`` on the flightdeck
    exporter.  Blocking request/response: the handler thread (flightdeck's
    ``ThreadingHTTPServer`` runs one per connection) submits and waits for
    the result.  A request carrying ``timeout_s`` (the router's propagated
    deadline budget) bounds its own wait to that remainder.  On timeout the
    pending request is cancelled so the engine reclaims its slot/pages —
    the 504 is a *release*, not a leak — which is also what makes router
    failover idempotent over HTTP: by the time the retry lands elsewhere,
    this replica is provably no longer executing the request.  Returns the
    mounted path.

    The handler is also the frontend's trace-context mint: a request that
    arrives without ``trace_id``/``request_id`` (a direct client, not a
    router hop) gets fresh ids here, and the whole handler runs inside a
    ``serving.http_request`` span bound to them — when the router sent the
    request, ``X-DK-Parent-Span`` names the router-side span this one
    logically nests under, stitching the cross-process trace.

    ``traffic_log`` (a :class:`distkeras_tpu.online.TrafficLog`) closes the
    serve→train loop: every *successful* generation is offered back to the
    capture ring after its 200 is decided (sampling/quota admission happens
    inside the log).  Capture is strictly best-effort here — a capture
    fault is counted (``online_capture_errors_total``) and swallowed, never
    surfaced to the client; serving must not fail because capture did."""
    import uuid as _uuid

    from distkeras_tpu.telemetry.flightdeck import server as _server
    from distkeras_tpu.telemetry.trace import new_trace_id, trace as _trace

    def handle(request):
        try:
            req = _parse_request(request)
        except (ValueError, KeyError, json.JSONDecodeError) as e:
            body = json.dumps({"error": f"{type(e).__name__}: {e}"})
            return ("application/json", body, 400)
        if not req.request_id:
            req.request_id = _uuid.uuid4().hex
        if not req.trace_id:
            req.trace_id = new_trace_id()
        span_attrs = {"request_id": req.request_id, "trace_id": req.trace_id}
        if req.tenant:
            span_attrs["tenant"] = req.tenant
        parent = (request.get("headers") or {}).get("x-dk-parent-span")
        if parent:
            span_attrs["parent"] = str(parent)
        with _trace.bind(trace_id=req.trace_id, request_id=req.request_id), \
                _trace.span("serving.http_request", **span_attrs):
            try:
                pending = engine.submit(req)
            except QueueFull as e:
                return ("application/json", json.dumps({"error": str(e)}),
                        503, {"Retry-After": "1"})
            budget = timeout
            if req.timeout_s is not None:
                budget = req.timeout_s if budget is None else min(
                    budget, req.timeout_s)
            result = pending.result(timeout=budget)
            if result is None:
                engine.cancel(pending)
                body = json.dumps({"error": "generation timed out"})
                return ("application/json", body, 504)
            if result.finish_reason == "aborted":
                # engine stopped/crashed with the request in flight — a
                # retryable server condition, not a successful generation
                return ("application/json", result.to_json(), 503,
                        {"Retry-After": "1"})
            if traffic_log is not None:
                try:
                    traffic_log.record(req, result)
                except Exception:  # noqa: BLE001 — capture is best-effort
                    from distkeras_tpu import telemetry

                    if telemetry.enabled():
                        from distkeras_tpu.online.capture import online_metrics

                        online_metrics()["capture_errors"].inc()
            return ("application/json", result.to_json(), 200)

    _server.add_endpoint(path, handle)
    return path
