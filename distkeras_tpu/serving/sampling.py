"""Sampling beyond greedy: temperature / top-k / top-p, per-request seeds.

Every knob is a **traced scalar**, not a Python value: the serving engine
runs one jitted decode step for every request mix, so "this request samples
at temperature 0.8 with top_k 40, that one is greedy" must be data, never a
recompile (dklint DK102).  Greedy is the ``temperature <= 0`` limit and is
computed as an exact ``argmax`` — not a low-temperature softmax — so greedy
requests through the engine are token-identical to ``greedy_generate``.

Conventions (matching the common HF/vLLM semantics):

* ``temperature <= 0`` — greedy (argmax); the other knobs are ignored.
* ``top_k <= 0`` or ``>= vocab`` — no top-k truncation.
* ``top_p >= 1`` — no nucleus truncation; the smallest prefix of
  probability-sorted tokens with cumulative mass ``>= top_p`` is kept
  (the token that crosses the threshold is always kept).

Speculative decoding (:func:`speculative_verify`) builds on the same
filtered distributions: the acceptance test and the rejection-resample both
use the **modified** distribution (after temperature/top-k/top-p), which is
what makes draft-then-verify sampling exact for the filtered target
distribution (Leviathan et al., arXiv:2211.17192, applied per-knob).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "filtered_logits",
    "modified_probs",
    "sample_one",
    "sample_tokens",
    "speculative_verify",
    "speculative_verify_tokens",
]


def filtered_logits(logits, temperature, top_k, top_p):
    """Temperature-scaled logits with the top-k / top-p mask applied
    (masked-out entries are ``-inf``).  ``logits [vocab]``; knobs are traced
    scalars.  This is the distribution-shaping half of :func:`sample_one`,
    shared with the speculative accept/resample path."""
    vocab = logits.shape[-1]

    # temperature-scaled working copy (guard the traced divide-by-zero even
    # though the greedy branch wins the final where)
    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_t

    desc = jnp.sort(scaled)[::-1]  # [vocab], descending

    # top-k: keep logits >= the k-th largest; k<=0 or k>=vocab disables
    k = jnp.clip(top_k, 1, vocab)
    kth = desc[k - 1]
    use_k = (top_k > 0) & (top_k < vocab)
    k_mask = jnp.where(use_k, scaled >= kth, True)

    # top-p over the sorted softmax: keep the smallest prefix with
    # cumulative mass >= top_p; (cum - p) < top_p keeps the crossing token
    probs = jax.nn.softmax(desc)
    cum = jnp.cumsum(probs)
    keep_sorted = (cum - probs) < top_p  # [vocab] in sorted order
    # map back by value: the threshold is the smallest kept sorted logit
    n_keep = jnp.sum(keep_sorted)
    p_thresh = desc[jnp.clip(n_keep - 1, 0, vocab - 1)]
    use_p = top_p < 1.0
    p_mask = jnp.where(use_p, scaled >= p_thresh, True)

    return jnp.where(k_mask & p_mask, scaled, -jnp.inf)


def modified_probs(logits, temperature, top_k, top_p):
    """The *modified* distribution the sampler actually draws from:
    ``softmax(filtered_logits(...))``.  The speculative acceptance test
    compares draft and target under their modified distributions."""
    return jax.nn.softmax(filtered_logits(logits, temperature, top_k, top_p))


def sample_one(logits, key, temperature, top_k, top_p):
    """Sample one token id from ``logits [vocab]``; every argument after
    ``logits`` is a traced scalar.  Returns an int32 scalar."""
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    masked = filtered_logits(logits, temperature, top_k, top_p)
    sampled = jax.random.categorical(key, masked).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy_tok)


def sample_tokens(logits, keys, temperature, top_k, top_p):
    """Vmapped :func:`sample_one` over a slot batch: ``logits [slots,
    vocab]``, ``keys [slots]`` PRNG keys, per-slot scalar knob arrays."""
    return jax.vmap(sample_one)(logits, keys, temperature, top_k, top_p)


def speculative_verify(logits, drafts, draft_probs, key, temperature, top_k,
                       top_p, speculate):
    """Judge one slot's ``m``-token speculative window.

    ``logits [m, vocab]`` are the target's logits where row ``i`` predicts
    the position ``drafts[i]`` was proposed for; ``draft_probs [m, vocab]``
    are the draft's *modified* distributions at those positions (same
    temperature/top-k/top-p filtering).  ``speculate`` is a traced bool —
    False collapses to the plain single-token path (sample row 0 exactly as
    the non-speculative decode step would), so opted-out slots ride the same
    program without semantic drift.

    Returns ``(tokens [m], count, accepted, new_key)``: emit
    ``tokens[:count]``; ``accepted`` counts kept draft tokens (the
    proposed/accepted telemetry).  There is deliberately **no bonus token**:
    on an all-accept window the emitted suffix is ``drafts`` itself, so the
    draft model's own cache — which already holds K/V for every proposed
    token — never develops a hole and needs no catch-up feeds.

    Semantics per mode:

    * greedy (``temperature <= 0``): accept while the draft matches the
      target argmax; every emitted token is a target argmax row, so the
      emitted stream is bitwise the non-speculative greedy stream.
    * stochastic: Leviathan et al. acceptance-rejection — accept ``d_i``
      with probability ``min(1, p(d_i)/q(d_i))``; on first rejection,
      resample from ``normalize(max(p - q, 0))``.
    """
    m = logits.shape[0]
    targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    greedy_ok = drafts == targets

    # Opted-out slots consume the same (key -> next_key, subkey) chain as
    # the non-speculative engine, so a request's sampled tokens don't depend
    # on its neighbours' opt-in.  The speculative keys derive from the fresh
    # subkey `next_plain` — never from `key` itself: under partitionable
    # threefry (the default in newer JAX), split(key, n)[:2] == split(key),
    # so re-splitting the parent would make the first accept-uniform reuse
    # the plain sampling key exactly (correlated accept/resample streams —
    # the DK111 lineage rule pins this).
    next_plain, sub_plain = jax.random.split(key)
    spec_keys = jax.random.split(next_plain, 2 * m + 1)  # [next, m accepts, m resamples]

    p = jax.vmap(modified_probs, in_axes=(0, None, None, None))(
        logits, temperature, top_k, top_p)  # [m, vocab]
    p_d = jnp.take_along_axis(p, drafts[:, None], axis=1)[:, 0]
    q_d = jnp.take_along_axis(draft_probs, drafts[:, None], axis=1)[:, 0]
    u = jax.vmap(lambda k: jax.random.uniform(k))(spec_keys[1:m + 1])
    # u < p/q, written mult-form so q(d)=0 (never proposed, but numerically
    # possible) accepts iff p(d) > 0 instead of dividing by zero
    stoch_ok = u * q_d < p_d

    residual = jnp.maximum(p - draft_probs, 0.0)
    total = residual.sum(axis=-1, keepdims=True)
    # p == q makes the residual empty — but then rejection has probability
    # ~0; fall back to p so the categorical below stays well-defined
    residual = jnp.where(total > 0, residual / total, p)
    resampled = jax.vmap(
        lambda k, pr: jax.random.categorical(k, jnp.log(pr))
    )(spec_keys[m + 1:], residual).astype(jnp.int32)

    ok = jnp.where(temperature > 0, stoch_ok, greedy_ok)
    lead = jnp.sum(jnp.cumprod(ok.astype(jnp.int32)))  # leading accepts
    count = jnp.minimum(lead + 1, m)  # +1 = the correction/final token
    accepted = jnp.minimum(lead, count)
    out = jnp.where(temperature > 0, jnp.where(ok, drafts, resampled), targets)

    plain = sample_one(logits[0], sub_plain, temperature, top_k, top_p)
    out = jnp.where(speculate, out, out.at[0].set(plain))
    count = jnp.where(speculate, count, 1).astype(jnp.int32)
    accepted = jnp.where(speculate, accepted, 0).astype(jnp.int32)
    new_key = jnp.where(speculate, spec_keys[0], next_plain)
    return out, count, accepted, new_key


def speculative_verify_tokens(logits, drafts, draft_probs, keys, temperature,
                              top_k, top_p, speculate):
    """Vmapped :func:`speculative_verify` over the slot batch: ``logits
    [slots, m, vocab]``, ``drafts [slots, m]``, ``draft_probs [slots, m,
    vocab]``, per-slot keys/knobs/opt-in."""
    return jax.vmap(speculative_verify)(
        logits, drafts, draft_probs, keys, temperature, top_k, top_p,
        speculate)
