"""Sampling beyond greedy: temperature / top-k / top-p, per-request seeds.

Every knob is a **traced scalar**, not a Python value: the serving engine
runs one jitted decode step for every request mix, so "this request samples
at temperature 0.8 with top_k 40, that one is greedy" must be data, never a
recompile (dklint DK102).  Greedy is the ``temperature <= 0`` limit and is
computed as an exact ``argmax`` — not a low-temperature softmax — so greedy
requests through the engine are token-identical to ``greedy_generate``.

Conventions (matching the common HF/vLLM semantics):

* ``temperature <= 0`` — greedy (argmax); the other knobs are ignored.
* ``top_k <= 0`` or ``>= vocab`` — no top-k truncation.
* ``top_p >= 1`` — no nucleus truncation; the smallest prefix of
  probability-sorted tokens with cumulative mass ``>= top_p`` is kept
  (the token that crosses the threshold is always kept).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["sample_one", "sample_tokens"]


def sample_one(logits, key, temperature, top_k, top_p):
    """Sample one token id from ``logits [vocab]``; every argument after
    ``logits`` is a traced scalar.  Returns an int32 scalar."""
    vocab = logits.shape[-1]
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # temperature-scaled working copy (guard the traced divide-by-zero even
    # though the greedy branch wins the final where)
    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_t

    desc = jnp.sort(scaled)[::-1]  # [vocab], descending

    # top-k: keep logits >= the k-th largest; k<=0 or k>=vocab disables
    k = jnp.clip(top_k, 1, vocab)
    kth = desc[k - 1]
    use_k = (top_k > 0) & (top_k < vocab)
    k_mask = jnp.where(use_k, scaled >= kth, True)

    # top-p over the sorted softmax: keep the smallest prefix with
    # cumulative mass >= top_p; (cum - p) < top_p keeps the crossing token
    probs = jax.nn.softmax(desc)
    cum = jnp.cumsum(probs)
    keep_sorted = (cum - probs) < top_p  # [vocab] in sorted order
    # map back by value: the threshold is the smallest kept sorted logit
    n_keep = jnp.sum(keep_sorted)
    p_thresh = desc[jnp.clip(n_keep - 1, 0, vocab - 1)]
    use_p = top_p < 1.0
    p_mask = jnp.where(use_p, scaled >= p_thresh, True)

    masked = jnp.where(k_mask & p_mask, scaled, -jnp.inf)
    sampled = jax.random.categorical(key, masked).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy_tok)


def sample_tokens(logits, keys, temperature, top_k, top_p):
    """Vmapped :func:`sample_one` over a slot batch: ``logits [slots,
    vocab]``, ``keys [slots]`` PRNG keys, per-slot scalar knob arrays."""
    return jax.vmap(sample_one)(logits, keys, temperature, top_k, top_p)
