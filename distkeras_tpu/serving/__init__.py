"""Online inference: continuous batching, paged KV cache, SLO metrics.

The request-level serving layer ROADMAP item 1 calls for — everything the
training side can only do call-at-a-time (``greedy_generate``) reshaped for
a service that admits requests whenever they arrive:

* :class:`~distkeras_tpu.serving.engine.ServingEngine` — the decode loop
  (fixed slot ring, ONE jitted step, prefill-on-admission / retire-on-EOS);
* :mod:`~distkeras_tpu.serving.cache` — paged KV cache (slot page tables
  over shared K/V pools);
* :mod:`~distkeras_tpu.serving.sampling` — temperature / top-k / top-p
  with per-request seeds, all traced (no recompiles);
* :mod:`~distkeras_tpu.serving.frontend` — request/response dataclasses,
  bounded queue with backpressure, the flightdeck ``/generate`` endpoint;
* :mod:`~distkeras_tpu.serving.tier` — the fault-tolerant router over N
  replicas: health-gated least-loaded dispatch, failover retry, deadline
  propagation, load shedding, rolling checkpoint hot-swap.

Serve over HTTP (flightdeck exporter carries the endpoint)::

    from distkeras_tpu import serving
    engine = serving.ServingEngine(trained_model)
    serving.install_http_endpoint(engine)      # POST/GET /generate
    # SLO histograms (serving_ttft_seconds, serving_token_latency_seconds,
    # serving_queue_depth, ...) appear on the same server's /metrics.

or as a daemon job: ``PunchcardServer``'s ``serve`` verb
(:mod:`distkeras_tpu.job_deployment`), which forwards engine knobs via
``Job.serve(flags=...)`` -> :func:`serve_flags`.

Fast paths (all optional engine kwargs): ``prefill_buckets`` — power-of-two
prefill width ladder; ``draft_model``/``spec_tokens`` — speculative
decoding with exact accept/resample semantics; ``mesh`` — tensor-parallel
decode over the local devices.
"""

from distkeras_tpu.serving.cache import PagedKVCache, append_rows, rollback_rows
from distkeras_tpu.serving.engine import EngineCrashed, ServingEngine, serving_metrics
from distkeras_tpu.serving.frontend import (
    GenerateRequest,
    GenerateResult,
    QueueFull,
    RequestQueue,
    install_http_endpoint,
    serve_flags,
)
from distkeras_tpu.serving.sampling import (
    modified_probs,
    sample_one,
    sample_tokens,
    speculative_verify,
)
from distkeras_tpu.serving.tier import (
    HttpReplica,
    LocalReplica,
    ReplicaDead,
    ServingTier,
    TierDeadline,
    TierError,
    TierExhausted,
    TierSaturated,
    install_tier_endpoint,
    tier_metrics,
    watch_and_swap,
)

__all__ = [
    "EngineCrashed",
    "GenerateRequest",
    "GenerateResult",
    "HttpReplica",
    "LocalReplica",
    "PagedKVCache",
    "QueueFull",
    "ReplicaDead",
    "RequestQueue",
    "ServingEngine",
    "ServingTier",
    "TierDeadline",
    "TierError",
    "TierExhausted",
    "TierSaturated",
    "append_rows",
    "install_http_endpoint",
    "install_tier_endpoint",
    "modified_probs",
    "rollback_rows",
    "sample_one",
    "sample_tokens",
    "serve_flags",
    "serving_metrics",
    "speculative_verify",
    "tier_metrics",
    "watch_and_swap",
]
