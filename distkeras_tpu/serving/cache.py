"""Paged KV cache for the serving engine.

The training/generation caches (``TransformerLM``'s per-block
``cached_key``/``cached_value`` buffers, ``StagedLM.init_cache``) are
*request-shaped*: one contiguous ``[batch, max_len, heads, head_dim]``
buffer per request batch, allocated for the worst case and thrown away when
the generate call returns.  A serving engine admitting and retiring requests
mid-flight needs the vLLM formulation instead: K/V live in fixed **pools of
pages** shared by every slot, and each slot owns a small *page table* mapping
its logical context chunks to physical pages.  Admission allocates pages,
retirement frees them — the pools themselves never change shape, so the
jitted decode step compiles exactly once.

Layout::

    k_pages, v_pages : [num_layers, num_pages, page_size, heads, head_dim]
    tables           : [num_slots, pages_per_slot] int32 (host, numpy)

Physical page 0 is a reserved **scratch page**: unallocated table entries
and inactive slots point at it, so masked-off lanes of the decode step write
garbage there instead of corrupting live pages.  Attention masks by position
(``key_pos <= pos``), so scratch garbage is never read.

The pools are plain jax arrays owned by the engine (donated through its jit
step and reassigned from its outputs); this class owns the *bookkeeping*:
free-list, per-slot tables, alloc/free.  Host-side only — nothing here is
traced.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = ["PagedKVCache", "append_rows", "rollback_rows"]


# ------------------------------------------------------- traced pool writes
#
# The two functions below are the *traced* companions to the host-side
# bookkeeping: they scatter token rows into (or out of) the pools through a
# slot's page table.  ``append_rows`` generalises the decode step's one-row
# write to the ``m``-row window a speculative verify feeds; ``rollback_rows``
# erases the rejected suffix of that window so the pools only ever hold
# accepted-token K/V between engine iterations.


def append_rows(pool, layer, tables, pos, rows):
    """Scatter ``rows [slots, m, heads, head_dim]`` into ``pool`` at logical
    positions ``pos + 0 .. pos + m-1`` of each slot, through ``tables
    [slots, pages_per_slot]``.  Positions at or past a slot's capacity
    (``pages_per_slot * page_size``) are redirected to the scratch page, so
    a speculative window overhanging the end of context can never clobber
    another slot's pages — ``max_context`` stays honest."""
    page_size = pool.shape[2]
    pages_per_slot = tables.shape[1]
    m = rows.shape[1]
    logical = pos[:, None] + jnp.arange(m)[None, :]  # [slots, m]
    page_ix = jnp.clip(logical // page_size, 0, pages_per_slot - 1)
    phys = jnp.take_along_axis(tables, page_ix, axis=1)
    phys = jnp.where(logical < pages_per_slot * page_size, phys, 0)
    return pool.at[layer, phys, logical % page_size].set(rows)


def rollback_rows(pool, layer, tables, pos, count, m):
    """Zero the rejected suffix of an ``m``-row verify window: rows
    ``pos + count .. pos + m-1`` of each slot.  Kept rows (and overhang past
    capacity) are redirected to the scratch page, where the zero-write is
    harmless.  Defensive hygiene more than correctness: attention masks
    ``key_pos <= pos`` and every future write window starts at the live
    position, so stale rows would be overwritten before they could ever be
    attended — but zeroing them keeps the pools' invariant ("only accepted
    tokens between iterations") checkable."""
    page_size = pool.shape[2]
    pages_per_slot = tables.shape[1]
    offs = jnp.arange(m)[None, :]
    logical = pos[:, None] + offs  # [slots, m]
    rejected = (offs >= count[:, None]) & (logical < pages_per_slot * page_size)
    page_ix = jnp.clip(logical // page_size, 0, pages_per_slot - 1)
    phys = jnp.take_along_axis(tables, page_ix, axis=1)
    phys = jnp.where(rejected, phys, 0)
    zeros = jnp.zeros((pos.shape[0], m) + pool.shape[3:], pool.dtype)
    return pool.at[layer, phys, logical % page_size].set(zeros)


class PagedKVCache:
    """Page-table bookkeeping plus the pooled K/V buffers.

    ``pages_per_slot`` rows of the table bound each slot's context to
    ``pages_per_slot * page_size`` tokens; ``num_pages`` bounds the fleet of
    pages (default: enough for every slot at full context, plus the scratch
    page — i.e. no over-subscription unless the caller asks for it).
    """

    def __init__(self, *, num_layers, num_slots, page_size, pages_per_slot,
                 heads, head_dim, num_pages=None, dtype=jnp.float32):
        if page_size < 1 or pages_per_slot < 1 or num_slots < 1:
            raise ValueError("page_size, pages_per_slot, num_slots must be >= 1")
        self.num_layers = int(num_layers)
        self.num_slots = int(num_slots)
        self.page_size = int(page_size)
        self.pages_per_slot = int(pages_per_slot)
        if num_pages is None:
            num_pages = num_slots * pages_per_slot + 1  # +1 scratch
        if num_pages < 2:
            raise ValueError("need at least one real page beyond scratch")
        self.num_pages = int(num_pages)
        shape = (self.num_layers, self.num_pages, self.page_size,
                 int(heads), int(head_dim))
        self.k_pages = jnp.zeros(shape, dtype)
        self.v_pages = jnp.zeros(shape, dtype)
        # host-side: table rows point at scratch (page 0) until allocated
        self.tables = np.zeros((self.num_slots, self.pages_per_slot), np.int32)
        # LIFO free list over physical pages 1..num_pages-1 (0 = scratch)
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._owned = {s: [] for s in range(self.num_slots)}

    # ------------------------------------------------------------- queries

    def pages_needed(self, length: int) -> int:
        """Pages required to hold ``length`` tokens of context."""
        return -(-int(length) // self.page_size)  # ceil div

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    def max_context(self) -> int:
        """Tokens a single slot can hold: its table rows times page size."""
        return self.pages_per_slot * self.page_size

    # ------------------------------------------------------- alloc / free

    def alloc(self, slot: int, n: int) -> None:
        """Give ``slot`` ``n`` physical pages (admission).  Raises when the
        pool is dry or the slot's table would overflow — the engine checks
        :meth:`can_alloc` first, so hitting either is a bookkeeping bug."""
        owned = self._owned[slot]
        if len(owned) + n > self.pages_per_slot:
            raise ValueError(
                f"slot {slot}: {len(owned)}+{n} pages exceeds table size "
                f"{self.pages_per_slot}"
            )
        if n > len(self._free):
            raise ValueError(f"page pool dry: want {n}, have {len(self._free)}")
        for _ in range(n):
            page = self._free.pop()
            self.tables[slot, len(owned)] = page
            owned.append(page)

    def free(self, slot: int) -> int:
        """Return every page ``slot`` owns to the pool (retirement); the
        slot's table rows point back at scratch.  Returns the count freed."""
        owned = self._owned[slot]
        n = len(owned)
        while owned:
            self._free.append(owned.pop())
        self.tables[slot, :] = 0
        return n
