"""Parameter servers — the center-variable abstraction, TPU-native.

In the reference these are driver-side TCP daemons
(``distkeras/parameter_servers.py``): ``SocketParameterServer.run`` accepts
worker connections and dispatches 1-byte action codes (``p``=pull sends the
pickled center weights, ``c``=commit applies a delta), with subclasses
defining the commit rule (``DeltaParameterServer``: ``center += delta``;
``DynSGDParameterServer``: staleness-scaled).

On TPU the center variable does not live on a host behind a socket — it is a
*replicated pytree on the device mesh*, and commits are ``psum`` collectives
inside the compiled program (see :mod:`distkeras_tpu.algorithms` for the
update rules and :mod:`distkeras_tpu.parallel.engine` for the execution).
These classes keep the reference's PS lifecycle/observability API
(``start``/``stop``/``get_model``/``num_updates``) as a facade over that
on-device state, so user code written against the reference keeps working.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from distkeras_tpu.algorithms import Adag, Downpour, DynSGD, UpdateRule

__all__ = [
    "ParameterServer",
    "SocketParameterServer",
    "DeltaParameterServer",
    "ADAGParameterServer",
    "DynSGDParameterServer",
]


class ParameterServer:
    """Facade over the on-device replicated center variable."""

    #: update rule applied at commit boundaries (subclass responsibility).
    rule_class = Downpour

    def __init__(self, model: Any = None, master_port: int = 5000):
        self.model = model
        self.master_port = master_port  # kept for API compat; no socket is opened
        self.center_params: Any = None
        self.center_model_state: Any = None
        self._num_updates: int = 0
        self._live_updates: Any = None  # device-side counter copy mid-fit
        self.running = False

    # -- lifecycle (reference parity: initialize/start/run/stop) ------------
    def initialize(self) -> None:
        """Reference parity: bound a listening socket.  Here: nothing to do —
        the center variable is materialised on-device by the engine."""

    def start(self) -> None:
        self.running = True

    def run(self) -> None:  # pragma: no cover - compat shim
        self.running = True

    def stop(self) -> None:
        self.running = False

    # -- state --------------------------------------------------------------
    def attach(self, center_params, center_rule_state, center_model_state=None) -> None:
        """Called by the trainer after training: adopt the final on-device
        center state (the equivalent of the PS holding the trained model)."""
        self.center_params = center_params
        self.center_model_state = center_model_state
        num = center_rule_state.get("num_updates") if isinstance(center_rule_state, dict) else None
        if num is not None:
            self._num_updates = int(np.asarray(num))
        self._live_updates = None  # final count wins over the mid-fit copy

    def track(self, center_rule_state) -> None:
        """Called by the trainer at every epoch boundary *while training
        runs*: snapshot the on-device commit counter so :attr:`num_updates`
        is pollable live (reference parity — the socket PS could be asked
        mid-train).  The epoch state is donated into the next epoch's
        dispatch, so the facade keeps its own ``jnp.copy`` of the counter;
        the copy is dispatched here (before the donation) and only
        materialised if someone reads the property."""
        num = center_rule_state.get("num_updates") if isinstance(center_rule_state, dict) else None
        if num is not None:
            self._live_updates = jnp.copy(num)

    @property
    def num_updates(self) -> int:
        """Total commits applied to the center variable (reference parity:
        ``ParameterServer.num_updates``).  Live during a fit — epoch
        boundaries refresh it via :meth:`track`."""
        if self._live_updates is not None:
            return int(np.asarray(self._live_updates))
        return self._num_updates

    def get_model(self):
        """The trained center model (reference parity: ``get_model``)."""
        return self.model


class SocketParameterServer(ParameterServer):
    """Name-parity alias: the reference's TCP accept-loop server.  All
    transport concerns are gone — commits arrive as XLA collectives."""


class DeltaParameterServer(SocketParameterServer):
    """``center += delta`` (DOWNPOUR / AEASGD / EAMSGD commits)."""

    rule_class = Downpour


class ADAGParameterServer(SocketParameterServer):
    """Window-normalised delta (``center += delta / window``)."""

    rule_class = Adag


class DynSGDParameterServer(SocketParameterServer):
    """Staleness-aware: ``center += delta / (staleness + 1)`` with per-worker
    update clocks (see :class:`distkeras_tpu.algorithms.DynSGD`)."""

    rule_class = DynSGD
