"""Distributed inference — reference parity for ``distkeras/predictors.py``.

``ModelPredictor.predict(df)`` appends a ``prediction`` column.  The reference
deserialises the Keras model once per Spark partition and loops rows in
Python; here inference is one jitted, batched forward pass, sharded over the
device mesh when more than one chip is visible (batch data parallelism via
positional sharding — the TPU-native ``mapPartitions``).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np

from distkeras_tpu.frame import DataFrame
from distkeras_tpu.models.adapter import ModelAdapter, TrainedModel, as_adapter
from distkeras_tpu.parallel.mesh import make_mesh, worker_sharding

__all__ = ["Predictor", "ModelPredictor"]


class Predictor:
    def predict(self, dataframe: DataFrame) -> DataFrame:
        raise NotImplementedError


class ModelPredictor(Predictor):
    """Append model outputs as a ``prediction`` column.

    Accepts what trainers return: a Keras model, a :class:`TrainedModel`, or
    (adapter, params, state).
    """

    def __init__(
        self,
        keras_model: Any,
        features_col: str = "features",
        output_col: str = "prediction",
        batch_size: int = 512,
        params: Any = None,
        state: Any = None,
    ):
        self.features_col = features_col
        self.output_col = output_col
        self.batch_size = int(batch_size)
        if isinstance(keras_model, TrainedModel):
            self.adapter = keras_model.adapter
            self.params = keras_model.params
            self.state = keras_model.state
        else:
            self.adapter = as_adapter(keras_model)
            if params is None:
                self.params, self.state = self.adapter.init(
                    jax.random.key(0), np.zeros((1, 1), np.float32)
                ) if not hasattr(self.adapter, "model") else self._keras_vars()
            else:
                self.params, self.state = params, state or {}
        self._jit_apply = jax.jit(
            lambda p, s, x: self.adapter.apply(p, s, x, training=False)[0]
        )

    def _keras_vars(self):
        m = self.adapter.model
        return (
            [v.value for v in m.trainable_variables],
            {"ntv": [v.value for v in m.non_trainable_variables]},
        )

    def predict(self, dataframe: DataFrame) -> DataFrame:
        col = dataframe.column(self.features_col)
        feats = dataframe.matrix(
            self.features_col,
            dtype=np.int32 if (col.dtype != object and np.issubdtype(col.dtype, np.integer)) else np.float32,
        )
        n = len(feats)
        outs = []
        bs = self.batch_size
        for i in range(0, n, bs):
            chunk = feats[i : i + bs]
            pad = bs - len(chunk)
            if pad:  # static shapes: pad the tail batch, slice the output
                chunk = np.concatenate([chunk, np.repeat(chunk[-1:], pad, axis=0)])
            out = np.asarray(self._jit_apply(self.params, self.state, chunk))
            outs.append(out[: bs - pad] if pad else out)
        preds = np.concatenate(outs) if outs else np.zeros((0,))
        if self.adapter.outputs_logits and preds.ndim > 1 and preds.shape[-1] > 1:
            preds = np.asarray(jax.nn.softmax(preds, axis=-1))
        return dataframe.with_column(self.output_col, preds)

    # Spark-ML style alias used in the reference notebooks.
    transform = predict
