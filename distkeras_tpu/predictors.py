"""Distributed inference — reference parity for ``distkeras/predictors.py``.

``ModelPredictor.predict(df)`` appends a ``prediction`` column.  The reference
deserialises the Keras model once per Spark partition and loops rows in
Python (``distkeras/predictors.py :: ModelPredictor._predict``); here
inference is a jitted, batched forward pass.  When more than one device is
visible and the frame is at least ``distribute_threshold`` rows, each global
batch is sharded over the ``workers`` mesh axis (params replicated, batch
axis split — the TPU-native ``mapPartitions``) and every chip runs its shard
in the same XLA program; smaller frames take the single-device path, where
sharding overhead would dominate.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np

from distkeras_tpu import telemetry
from distkeras_tpu.frame import DataFrame
from distkeras_tpu.models.adapter import ModelAdapter, TrainedModel, as_adapter
from distkeras_tpu.parallel.mesh import make_mesh, replicated_sharding, worker_sharding

__all__ = ["Predictor", "ModelPredictor"]


class Predictor:
    def predict(self, dataframe: DataFrame) -> DataFrame:
        raise NotImplementedError


class ModelPredictor(Predictor):
    """Append model outputs as a ``prediction`` column.

    Accepts what trainers return: a Keras model, a :class:`TrainedModel`, or
    (adapter, params, state).  A bare flax module without ``params`` is
    initialised lazily from the first predicted batch (the input shape is
    only knowable from real data — init-time dummy shapes broke conv models).
    """

    def __init__(
        self,
        keras_model: Any = None,
        features_col: str = "features",
        output_col: str = "prediction",
        batch_size: int = 512,
        params: Any = None,
        state: Any = None,
        num_devices: Optional[int] = None,
        distribute_threshold: Optional[int] = None,
        engine: Any = None,
        max_new_tokens: int = 16,
    ):
        self.features_col = features_col
        self.output_col = output_col
        self.batch_size = int(batch_size)
        # Route rows through a serving.ServingEngine instead of the batched
        # forward pass: each row is a token-id prompt, the prediction column
        # holds the generated continuation.  The engine carries the model,
        # so no adapter/mesh setup happens on this path.
        self.engine = engine
        self.max_new_tokens = int(max_new_tokens)
        if engine is not None:
            self.adapter = None
            self.params = self.state = None
            self.last_mode = None
            return
        if keras_model is None:
            raise TypeError("ModelPredictor needs a model (or an engine=)")
        if isinstance(keras_model, TrainedModel):
            self.adapter = keras_model.adapter
            self.params = keras_model.params
            self.state = keras_model.state
        else:
            self.adapter = as_adapter(keras_model)
            if params is not None:
                self.params, self.state = params, state or {}
            elif hasattr(self.adapter, "model"):
                self.params, self.state = self._keras_vars()
            else:
                self.params = None  # lazy: init from the first real batch
                self.state = {}
        # LOCAL devices only: prediction is per-process data parallel (each
        # process holds its own frame rows, like the reference's
        # mapPartitions executors).  A global mesh would hand device_put
        # non-addressable shardings and make the output un-gatherable on
        # multi-host runs.
        self.mesh = make_mesh(num_devices, devices=jax.local_devices())
        self.n_dev = int(self.mesh.devices.size)
        # Below this many rows the mesh path isn't worth the put/gather —
        # scaled by the device count so distribution kicks in only when
        # every chip gets a meaningful slice of work (a bare batch_size
        # would widen one batch n_dev-fold and pad it with duplicates).
        self.distribute_threshold = (
            int(distribute_threshold) if distribute_threshold is not None
            else self.batch_size * self.n_dev
        )
        self._rep = replicated_sharding(self.mesh)
        self._shard = worker_sharding(self.mesh)
        fwd = lambda p, s, x: self.adapter.apply(p, s, x, training=False)[0]
        self._jit_apply = jax.jit(fwd)
        self._jit_apply_sharded = jax.jit(
            fwd,
            in_shardings=(self._rep, self._rep, self._shard),
            out_shardings=self._shard,
        )
        #: how the last ``predict`` ran: None | "single" | "distributed"
        self.last_mode = None

    def _keras_vars(self):
        m = self.adapter.model
        return (
            [v.value for v in m.trainable_variables],
            {"ntv": [v.value for v in m.non_trainable_variables]},
        )

    def _ensure_params(self, sample: np.ndarray):
        if self.params is None:
            self.params, self.state = self.adapter.init(jax.random.key(0), sample)

    def _shard_batch(self, chunk: np.ndarray):
        """Device-put one global batch split over the workers mesh axis."""
        return jax.device_put(chunk, self._shard)

    def _predict_via_engine(self, dataframe: DataFrame) -> DataFrame:
        """Generation-shaped prediction: every row's features are a token-id
        prompt submitted to the serving engine.  Submission is windowed —
        on backpressure (QueueFull) the oldest in-flight request is drained
        first, so the predictor never overruns the engine's queue and never
        deadlocks on its own submissions."""
        from collections import deque

        from distkeras_tpu.serving.frontend import GenerateRequest, QueueFull

        col = dataframe.column(self.features_col)
        if col.dtype == object:
            prompts = [[int(t) for t in np.ravel(row)] for row in col]
        else:
            prompts = [[int(t) for t in row] for row in np.atleast_2d(
                dataframe.matrix(self.features_col, dtype=np.int32))]
        n = len(prompts)
        out = np.empty(n, dtype=object)
        in_flight: deque = deque()

        def drain_one():
            idx, pending = in_flight.popleft()
            result = pending.result(timeout=300.0)
            if result is None:
                raise TimeoutError(f"engine never finished row {idx}")
            out[idx] = result.tokens

        with telemetry.trace.span("predict", rows=int(n), mode="engine"):
            for idx, prompt in enumerate(prompts):
                req = GenerateRequest(prompt=prompt,
                                      max_new_tokens=self.max_new_tokens)
                while True:
                    try:
                        in_flight.append((idx, self.engine.submit(req)))
                        break
                    except QueueFull:
                        drain_one()
            while in_flight:
                drain_one()
        self.last_mode = "engine"
        return dataframe.with_column(self.output_col, out)

    def predict(self, dataframe: DataFrame) -> DataFrame:
        if self.engine is not None:
            return self._predict_via_engine(dataframe)
        col = dataframe.column(self.features_col)
        feats = dataframe.matrix(
            self.features_col,
            dtype=np.int32 if (col.dtype != object and np.issubdtype(col.dtype, np.integer)) else np.float32,
        )
        n = len(feats)
        self._ensure_params(feats[:1])
        distributed = self.n_dev > 1 and n >= self.distribute_threshold
        self.last_mode = "distributed" if distributed else "single"
        # One compiled shape: pad the tail batch, slice the output.  The
        # distributed path widens the batch so every chip gets batch_size rows.
        bs = self.batch_size * (self.n_dev if distributed else 1)
        outs = []
        with telemetry.trace.span("predict", rows=int(n), mode=self.last_mode):
            for i in range(0, n, bs):
                chunk = feats[i : i + bs]
                pad = bs - len(chunk)
                if pad:
                    chunk = np.concatenate([chunk, np.repeat(chunk[-1:], pad, axis=0)])
                # np.asarray already blocks on the device result, so the
                # per-batch span needs no extra sync
                with telemetry.trace.span("predict_batch", phase="infer",
                                          batch=len(chunk)):
                    if distributed:
                        with self.mesh:
                            out = self._jit_apply_sharded(
                                self.params, self.state, self._shard_batch(chunk)
                            )
                        out = np.asarray(out)
                    else:
                        out = np.asarray(self._jit_apply(self.params, self.state, chunk))
                outs.append(out[: bs - pad] if pad else out)
        preds = np.concatenate(outs) if outs else np.zeros((0,))
        if self.adapter.outputs_logits and preds.ndim > 1 and preds.shape[-1] > 1:
            preds = np.asarray(jax.nn.softmax(preds, axis=-1))
        return dataframe.with_column(self.output_col, preds)

    # Spark-ML style alias used in the reference notebooks.
    transform = predict
