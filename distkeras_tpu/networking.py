"""Networking — host discovery, multi-host initialisation, wire helpers.

Reference parity: ``distkeras/networking.py`` provided
``determine_host_address`` plus length-prefixed pickled-TCP ``send_data`` /
``recv_data`` — the transport of the star-topology parameter server.  On TPU
the training-path transport is gone: gradients/deltas ride XLA collectives
over ICI/DCN, wired up by ``jax.distributed`` (the coordination service
replaces the reference's master host:port handshake).  What remains here:

* :func:`determine_host_address` — unchanged role;
* :func:`initialize` / :func:`shutdown` — multi-host process bootstrap
  (``jax.distributed``), the reference's ``master_host``/``master_port``
  analogue.  On Cloud TPU pods ``initialize()`` with no args auto-detects;
* ``send_data`` / ``recv_data`` — the control-plane wire helpers, retained
  for the job-deployment daemon (L7).  Payloads are length-prefixed; the
  default codec is a restricted numpy/JSON container format, NOT pickle —
  the reference's pickled transport is an arbitrary-code-execution surface
  we chose not to reproduce.
"""

from __future__ import annotations

import io
import json
import socket
import struct
from typing import Any, Optional

import numpy as np

__all__ = [
    "determine_host_address",
    "initialize",
    "shutdown",
    "connect",
    "send_data",
    "recv_data",
]

_MAGIC = b"DKT1"
_MAX_MESSAGE = 1 << 31


def determine_host_address() -> str:
    """Best-effort routable address of this host (reference parity:
    ``networking.py :: determine_host_address``)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        # UDP connect sends no packet and cannot block on a peer
        s.connect(("8.8.8.8", 80))  # dklint: disable=DK115
        return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        s.close()


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host training job (the reference's master handshake).

    On Cloud TPU pods call with no arguments: the runtime auto-detects the
    coordinator and process topology.  Elsewhere pass
    ``coordinator_address='host:port'`` plus ``num_processes``/``process_id``.
    After this, ``jax.devices()`` spans every host and
    :func:`distkeras_tpu.parallel.mesh.make_mesh` builds a global mesh whose
    collectives ride ICI within a slice and DCN across slices.
    """
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def shutdown() -> None:
    import jax

    jax.distributed.shutdown()


# -- control-plane wire helpers (job deployment) ---------------------------

def connect(host: str, port: int, timeout: float = 30.0) -> socket.socket:
    """TCP connect with NODELAY (reference parity: ``networking.py :: connect``).
    The timeout stays applied on the returned socket — callers inherit a
    deadline on every subsequent send/recv unless they override it."""
    from distkeras_tpu import chaos

    if chaos.enabled():
        chaos.fault("connect")  # seeded ConnectionRefusedError injection
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def _encode(obj: Any) -> bytes:
    """Restricted container codec: JSON tree with out-of-band numpy arrays."""
    arrays: list[np.ndarray] = []

    def visit(x):
        if isinstance(x, np.ndarray):
            arrays.append(x)
            return {"__nd__": len(arrays) - 1}
        if isinstance(x, (np.integer, np.floating)):
            return x.item()
        if isinstance(x, dict):
            return {k: visit(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return [visit(v) for v in x]
        if isinstance(x, bytes):
            arrays.append(np.frombuffer(x, dtype=np.uint8))
            return {"__bytes__": len(arrays) - 1}
        return x

    tree = json.dumps(visit(obj)).encode()
    buf = io.BytesIO()
    np.savez(buf, **{f"a{i}": a for i, a in enumerate(arrays)})
    blob = buf.getvalue()
    return struct.pack("!II", len(tree), len(blob)) + tree + blob


def _decode(payload: bytes) -> Any:
    tree_len, blob_len = struct.unpack("!II", payload[:8])
    tree = json.loads(payload[8 : 8 + tree_len].decode())
    blob = payload[8 + tree_len : 8 + tree_len + blob_len]
    arrays = np.load(io.BytesIO(blob), allow_pickle=False) if blob_len else {}

    def visit(x):
        if isinstance(x, dict):
            if "__nd__" in x and len(x) == 1:
                return arrays[f"a{x['__nd__']}"]
            if "__bytes__" in x and len(x) == 1:
                return arrays[f"a{x['__bytes__']}"].tobytes()
            return {k: visit(v) for k, v in x.items()}
        if isinstance(x, list):
            return [visit(v) for v in x]
        return x

    return visit(tree)


def send_data(sock: socket.socket, obj: Any) -> None:
    """Length-prefixed message send (reference parity: ``send_data``)."""
    from distkeras_tpu.sanitizer import lockwatch

    payload = _encode(obj)
    frame = _MAGIC + struct.pack("!Q", len(payload)) + payload
    # one frame must hit the wire atomically per socket: the sanitizer's
    # exclusivity guard flags concurrent sends from two threads, which
    # would interleave length-prefixed frames and tear the stream
    with lockwatch.exclusive(sock, "send_data on one socket"):
        from distkeras_tpu import chaos

        if chaos.enabled():
            # tear check first (it consumes the site counter only when it
            # fires); the delay fault below is skipped for a torn frame
            torn = chaos.tear_bytes("send", len(frame))
            if torn is not None:
                sock.sendall(frame[:torn])
                raise ConnectionError(
                    f"chaos: frame torn after {torn}/{len(frame)} bytes")
            chaos.fault("send")
        sock.sendall(frame)


def _recvall(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n > 0:
        # timeout is the caller's contract: connect() applies one and the
        # daemon sets conn.settimeout() before recv_data
        chunk = sock.recv(min(n, 1 << 20))  # dklint: disable=DK115
        if not chunk:
            raise ConnectionError("socket closed mid-message")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_data(sock: socket.socket) -> Any:
    """Length-prefixed message receive (reference parity: ``recv_data``)."""
    from distkeras_tpu import chaos
    from distkeras_tpu.sanitizer import lockwatch

    if chaos.enabled():
        chaos.fault("recv")  # seeded ConnectionError before the read
    with lockwatch.exclusive(sock, "recv_data on one socket"):
        header = _recvall(sock, 12)
        if header[:4] != _MAGIC:
            raise ValueError("bad message magic")
        (length,) = struct.unpack("!Q", header[4:])
        if length > _MAX_MESSAGE:
            raise ValueError(f"message too large: {length}")
        payload = _recvall(sock, length)
    return _decode(payload)
