"""Telemetry on/off switch and output-directory resolution.

The whole subsystem is opt-in: it activates only when ``DISTKERAS_TELEMETRY``
is set to a non-empty value other than ``0``.  A value of ``1``/``true``
enables with the default output directory; any other value enables AND names
the output directory (``DISTKERAS_TELEMETRY=/tmp/run1``), with
``DISTKERAS_TELEMETRY_DIR`` as the explicit override.

``enabled()`` is the fast path consulted by every instrumentation site, so it
must cost no more than a module-global read plus an ``is None`` check once
the cached value is warm.  Tests flip the switch with ``configure()`` instead
of mutating ``os.environ``.
"""

from __future__ import annotations

import os

__all__ = ["configure", "enabled", "out_dir"]

_FALSEY = ("", "0", "false", "no")

# None = not yet resolved from the environment; True/False once resolved or
# forced via configure().
_ENABLED = None


def enabled() -> bool:
    """True when telemetry recording is on.  Cached after first read."""
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = os.environ.get("DISTKERAS_TELEMETRY", "").lower() not in _FALSEY
    return _ENABLED


def configure(on=None) -> None:
    """Force telemetry on/off (``True``/``False``) or reset to env-driven
    (``None``, re-read lazily on the next ``enabled()`` call)."""
    global _ENABLED
    _ENABLED = on


def out_dir() -> str:
    """Directory where ``flush()`` writes trace/metrics files.

    Priority: ``DISTKERAS_TELEMETRY_DIR``, then a path-valued
    ``DISTKERAS_TELEMETRY``, then ``./distkeras_telemetry``.
    """
    explicit = os.environ.get("DISTKERAS_TELEMETRY_DIR")
    if explicit:
        return explicit
    v = os.environ.get("DISTKERAS_TELEMETRY", "")
    if v.lower() not in _FALSEY + ("1", "true", "yes"):
        return v
    return "distkeras_telemetry"
