"""Training-dynamics observability: in-graph health stats + watchdog.

The async trainers (DOWNPOUR/AEASGD/EAMSGD/ADAG/DynSGD) live or die by
quantities the host normally cannot see without breaking the async
pipeline: gradient magnitude, worker<->center drift, update size, and
effective staleness.  This module provides

* ``DynamicsConfig`` — env-driven switch (``DISTKERAS_DYNAMICS``) plus
  watchdog knobs (``DISTKERAS_DYNAMICS_WATCHDOG``,
  ``DISTKERAS_DYNAMICS_FACTOR``).  Like ``runtime.enabled()`` the config
  is resolved once and cached so the engines' trace-time branches are
  stable for the life of their cached epoch programs.
* in-graph helpers (``tree_sq_norm`` / ``tree_sq_dist`` /
  ``tree_nonfinite_count``) used by ``parallel/engine.py`` and
  ``parallel/gspmd.py`` to compute the extra stats leaves *inside* the
  jitted epoch program, so they ride the existing stats device->host
  gather — zero new host-sync sites.
* host-side ``summarize``/``record`` that turn the per-epoch dynamics
  arrays into telemetry gauges and a JSONL series, and
  ``DivergenceWatchdog`` with ``warn | halt | rollback`` policies.

Import cost is stdlib-only; jax is touched lazily inside the in-graph
helpers (mirrors the telemetry package contract).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
import warnings
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, Optional

from distkeras_tpu.telemetry import runtime as _runtime
from distkeras_tpu.telemetry import metrics as _metrics_mod
from distkeras_tpu.telemetry.flightdeck.recorder import recorder as _flight_recorder

_FALSEY = ("", "0", "false", "no")

#: Watchdog policies, in escalation order.
WATCHDOG_POLICIES = ("off", "warn", "halt", "rollback")


class TrainingDiverged(RuntimeError):
    """Raised by the watchdog under the ``halt`` policy (or when a
    ``rollback`` cannot proceed) to stop a diverging run."""


@dataclass(frozen=True)
class DynamicsConfig:
    """Resolved training-dynamics settings.

    ``enabled`` gates the in-graph stats; the remaining fields configure
    the host-side :class:`DivergenceWatchdog` built from them.
    """

    enabled: bool = False
    watchdog: str = "warn"
    divergence_factor: float = 10.0
    history: int = 32
    min_history: int = 3
    max_rollbacks: int = 2

    def __post_init__(self) -> None:
        if self.watchdog not in WATCHDOG_POLICIES:
            raise ValueError(
                f"watchdog policy must be one of {WATCHDOG_POLICIES}, "
                f"got {self.watchdog!r}")
        if self.divergence_factor <= 1.0:
            raise ValueError("divergence_factor must be > 1")

    @classmethod
    def from_env(cls) -> "DynamicsConfig":
        enabled = os.environ.get("DISTKERAS_DYNAMICS", "").lower() not in _FALSEY
        policy = os.environ.get("DISTKERAS_DYNAMICS_WATCHDOG", "warn").lower()
        factor = float(os.environ.get("DISTKERAS_DYNAMICS_FACTOR", "10.0"))
        return cls(enabled=enabled, watchdog=policy, divergence_factor=factor)


_CONFIG: Optional[DynamicsConfig] = None
_CONFIG_LOCK = threading.Lock()


def config() -> DynamicsConfig:
    """The cached config; resolved from the environment on first use."""
    global _CONFIG
    if _CONFIG is None:
        with _CONFIG_LOCK:
            if _CONFIG is None:
                _CONFIG = DynamicsConfig.from_env()
    return _CONFIG


def configure(cfg: Optional[DynamicsConfig] = None, **overrides: Any) -> DynamicsConfig:
    """Override the cached config (tests / programmatic use).

    ``configure()`` with no arguments re-reads the environment.  Keyword
    overrides are applied on top of ``cfg`` (or the env config).
    """
    global _CONFIG
    with _CONFIG_LOCK:
        base = cfg if cfg is not None else DynamicsConfig.from_env()
        if overrides:
            base = DynamicsConfig(**{**base.__dict__, **overrides})
        _CONFIG = base
    return _CONFIG


def enabled() -> bool:
    return config().enabled


# ---------------------------------------------------------------------------
# In-graph helpers.  Called at trace time inside the jitted epoch/window
# programs; jax is imported lazily so the telemetry package stays
# stdlib-only at import.
# ---------------------------------------------------------------------------


def _float_leaves(tree: Any):
    import jax
    import jax.numpy as jnp

    return [x for x in jax.tree.leaves(tree)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)]


def tree_sq_norm(tree: Any):
    """Sum of squares over every floating leaf, as a float32 scalar."""
    import jax.numpy as jnp

    acc = jnp.zeros((), jnp.float32)
    for x in _float_leaves(tree):
        acc = acc + jnp.sum(jnp.square(x.astype(jnp.float32)))
    return acc


def tree_sq_dist(a: Any, b: Any):
    """Squared L2 distance between two same-structure trees (float leaves)."""
    import jax
    import jax.numpy as jnp

    acc = jnp.zeros((), jnp.float32)
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            d = x.astype(jnp.float32) - y.astype(jnp.float32)
            acc = acc + jnp.sum(jnp.square(d))
    return acc


def tree_nonfinite_count(tree: Any):
    """Number of non-finite elements across floating leaves (float32 scalar,
    so the engines can psum it alongside the other dynamics leaves)."""
    import jax.numpy as jnp

    acc = jnp.zeros((), jnp.float32)
    for x in _float_leaves(tree):
        acc = acc + jnp.sum(~jnp.isfinite(x)).astype(jnp.float32)
    return acc


# ---------------------------------------------------------------------------
# Host side: per-epoch summaries, gauges, and the JSONL series.
# ---------------------------------------------------------------------------

#: Keys of per-window global leaves ([n_windows] arrays) in the stats dict.
GLOBAL_KEYS = ("grad_norm", "update_norm", "nonfinite_grads", "nonfinite_params")


def summarize(dyn: Dict[str, Any], loss: Any = None) -> Dict[str, float]:
    """Collapse one epoch's dynamics arrays to scalar gauges.

    1-D leaves (per-window globals) yield ``<k>`` (last window) and
    ``<k>_max``; 2-D leaves (per-window x per-worker) additionally yield
    ``<k>_mean`` over workers at the last window.  ``loss`` (if given)
    contributes ``loss_nonfinite`` — the count of non-finite loss values,
    which catches divergence even when the dynamics leaves saturate.
    """
    import numpy as np

    out: Dict[str, float] = {}
    for k in sorted(dyn):
        v = np.asarray(dyn[k], np.float64)
        if v.size == 0:
            continue
        with np.errstate(invalid="ignore"):
            if v.ndim >= 2:
                out[f"{k}_max"] = float(np.max(v))
                out[f"{k}_mean"] = float(np.mean(v[-1]))
            else:
                out[k] = float(v[-1])
                out[f"{k}_max"] = float(np.max(v))
    if loss is not None:
        larr = np.asarray(loss, np.float64)
        out["loss_nonfinite"] = float(np.size(larr) - np.sum(np.isfinite(larr)))
    return out


# Most recent recorded epoch summary — the /vars scrape and blackbox dumps
# read it so a crash report always carries the last known training health.
_LAST_SUMMARY: Optional[Dict[str, Any]] = None


def last_summary() -> Optional[Dict[str, Any]]:
    """``{"epoch", "summary", "unix"}`` of the latest :func:`record` call,
    or ``None`` before the first one (non-finite values stringified)."""
    return _LAST_SUMMARY


def record(epoch: int, dyn: Dict[str, Any], summary: Dict[str, float],
           directory: Optional[str] = None) -> None:
    """Publish one epoch of dynamics: gauges into the process registry and
    one JSON line (full per-window/per-worker series) into the metrics
    JSONL.  No-op when telemetry is disabled."""
    if not _runtime.enabled():
        return
    global _LAST_SUMMARY
    _LAST_SUMMARY = {
        "epoch": int(epoch),
        "summary": {k: (v if math.isfinite(v) else repr(v))
                    for k, v in sorted(summary.items())},
        "unix": time.time(),
    }
    record_gauges(summary)
    append_series(epoch, dyn, summary, directory=directory)


def record_gauges(summary: Dict[str, float], prefix: str = "dynamics_") -> None:
    """Set ``dynamics_<k>`` gauges for each summary scalar."""
    if not _runtime.enabled():
        return
    for k, v in summary.items():
        if math.isfinite(v):
            _metrics_mod.metrics.gauge(
                prefix + k, help="training-dynamics health stat").set(v)
        else:
            # a NaN gauge would poison max/mean fleet merges; surface the
            # event as a counter instead
            _metrics_mod.metrics.counter(
                prefix + "nonfinite_summaries_total",
                help="dynamics summary values that were non-finite").inc()


def append_series(epoch: int, dyn: Dict[str, Any], summary: Dict[str, float],
                  directory: Optional[str] = None) -> None:
    """Append the epoch's full dynamics series to ``metrics_<pid>.jsonl``."""
    if not _runtime.enabled():
        return
    import numpy as np

    directory = directory or _runtime.out_dir()
    os.makedirs(directory, exist_ok=True)
    pid = os.getpid()
    path = os.path.join(directory, f"metrics_{pid}.jsonl")

    def _tolist(v: Any):
        arr = np.asarray(v, np.float64)
        # JSON has no NaN/Inf literal; stringify non-finite entries
        flat = [x if math.isfinite(x) else repr(x) for x in arr.reshape(-1).tolist()]
        return {"shape": list(arr.shape), "values": flat}

    line = {
        "type": "dynamics",
        "pid": pid,
        "epoch": int(epoch),
        "series": {k: _tolist(v) for k, v in sorted(dyn.items())},
        "summary": {k: (v if math.isfinite(v) else repr(v))
                    for k, v in sorted(summary.items())},
    }
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps(line) + "\n")


# ---------------------------------------------------------------------------
# Watchdog.
# ---------------------------------------------------------------------------


class DivergenceWatchdog:
    """Epoch-granularity health check over dynamics summaries.

    Trips on (a) any non-finite gradient/parameter/loss value, or (b) the
    per-epoch max divergence exceeding ``divergence_factor`` times the
    running median of recent healthy epochs.  Policies:

    * ``warn`` — emit a ``RuntimeWarning`` and keep training.
    * ``halt`` — raise :class:`TrainingDiverged`.
    * ``rollback`` — request a checkpoint restore from the trainer
      (``pending_rollback`` holds the reason); after ``max_rollbacks``
      restores the policy escalates to ``halt``.

    The check runs on host numpy arrays *after* the epoch's stats have been
    fetched — never inside the step loop (see dklint rule DK107).
    """

    def __init__(self, policy: str = "warn", divergence_factor: float = 10.0,
                 history: int = 32, min_history: int = 3,
                 max_rollbacks: int = 2) -> None:
        if policy not in WATCHDOG_POLICIES or policy == "off":
            raise ValueError(f"bad watchdog policy {policy!r}")
        self.policy = policy
        self.divergence_factor = float(divergence_factor)
        self.min_history = int(min_history)
        self.max_rollbacks = int(max_rollbacks)
        self._history: deque = deque(maxlen=int(history))
        self._rollbacks = 0
        self._pending: Optional[str] = None
        self.trips = 0

    @classmethod
    def from_config(cls, cfg: Optional[DynamicsConfig] = None
                    ) -> Optional["DivergenceWatchdog"]:
        cfg = cfg if cfg is not None else config()
        if not cfg.enabled or cfg.watchdog == "off":
            return None
        return cls(policy=cfg.watchdog,
                   divergence_factor=cfg.divergence_factor,
                   history=cfg.history, min_history=cfg.min_history,
                   max_rollbacks=cfg.max_rollbacks)

    @property
    def pending_rollback(self) -> Optional[str]:
        return self._pending

    @property
    def rollbacks(self) -> int:
        return self._rollbacks

    def rolled_back(self) -> None:
        """Trainer callback: the requested restore happened."""
        self._pending = None
        self._rollbacks += 1
        self._history.clear()

    def _diagnose(self, epoch: int, summary: Dict[str, float]) -> Optional[str]:
        nonfinite = (summary.get("nonfinite_grads_max", 0.0)
                     + summary.get("nonfinite_params_max", 0.0)
                     + summary.get("loss_nonfinite", 0.0))
        if nonfinite > 0:
            return (f"epoch {epoch}: {nonfinite:g} non-finite "
                    "gradient/parameter/loss values")
        div = summary.get("divergence_max")
        if div is None:
            return None
        if not math.isfinite(div):
            return f"epoch {epoch}: worker<->center divergence is {div!r}"
        if len(self._history) >= self.min_history:
            hist = sorted(self._history)
            median = hist[len(hist) // 2]
            if median > 0.0 and div > self.divergence_factor * median:
                return (f"epoch {epoch}: divergence {div:.3g} exceeds "
                        f"{self.divergence_factor:g}x running median "
                        f"{median:.3g}")
        return None

    def observe(self, epoch: int, summary: Dict[str, float]) -> Optional[str]:
        """Inspect one epoch summary.  Returns the action taken
        (``"warn"`` / ``"rollback"``) or ``None`` when healthy.  Raises
        :class:`TrainingDiverged` under the ``halt`` policy."""
        reason = self._diagnose(epoch, summary)
        if reason is None:
            div = summary.get("divergence_max")
            if div is not None and math.isfinite(div):
                self._history.append(div)
            self._note(epoch, "ok", None)
            return None
        self.trips += 1
        if _runtime.enabled():
            _metrics_mod.metrics.counter(
                "dynamics_watchdog_trips_total",
                help="divergence watchdog activations").inc()
        if self.policy == "warn":
            self._note(epoch, "warn", reason)
            warnings.warn(f"divergence watchdog: {reason}", RuntimeWarning,
                          stacklevel=2)
            return "warn"
        if self.policy == "rollback" and self._rollbacks < self.max_rollbacks:
            self._pending = reason
            self._note(epoch, "rollback", reason)
            return "rollback"
        suffix = ("" if self.policy == "halt"
                  else f" (rollback budget of {self.max_rollbacks} exhausted)")
        self._note(epoch, "halt", reason + suffix)
        raise TrainingDiverged(reason + suffix)

    def _note(self, epoch: int, action: str, reason: Optional[str]) -> None:
        # Feed the flight-recorder ring so a blackbox dump shows the
        # watchdog's view of the final epochs, not just the raised error.
        if not _runtime.enabled():
            return
        _flight_recorder.record_watchdog({
            "epoch": int(epoch),
            "action": action,
            "reason": reason,
            "policy": self.policy,
            "trips": self.trips,
            "rollbacks": self._rollbacks,
        })
