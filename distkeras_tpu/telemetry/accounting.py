"""dkcost — per-tenant resource accounting and fair-share attribution.

The serving stack mints trace ids and evaluates SLO burn rates, but until
this module nothing attributed *resources* to the clients consuming them:
``GenerateRequest.tenant`` is the accounting key, and this is the ledger it
keys.  Every request is metered from already-host-visible bookkeeping (zero
new device syncs) and rolled up per tenant:

* **prefill tokens** — prompt tokens consumed at admission;
* **decode tokens** — generated tokens (first sampled token included), so
  the tenant-summed count equals ``serving_tokens_total`` *exactly* — the
  conservation invariant tests pin;
* **speculative accept/reject tokens** — the draft-token split, conserving
  against ``serving_spec_{proposed,accepted}_total``;
* **queue-wait seconds** — enqueue to prefill dispatch, on a fixed bucket
  ladder per tenant so fleet merges and p99s are exact;
* **KV page-seconds** — pages held × wall seconds, sampled at slot free;
* **estimated device-seconds** split by phase — prefill wall time, plus an
  even share of each decode step's wall time across the active slots.

Cardinality is **bounded by construction** (DK117-safe): the ledger tracks
the top-K tenants by rolling usage (exponentially-decayed token mass) plus
one ``__other__`` overflow bucket; admitting tenant K+1 folds the
smallest-usage entry into ``__other__`` — totals conserve across eviction,
and the series count never exceeds K+1.  Per-tenant breakdowns are served
as JSON (the flightdeck ``/ledger`` endpoint, the daemon's
``ledger_status`` verb, ``dkmon top``); only *aggregate* ``accounting_*``
instruments enter the metrics registry, so rollups, SLOs, and the fleet
merge see fixed names.

Flag discipline matches telemetry/rollup: ``DISTKERAS_ACCOUNTING=0``
disables the ledger entirely — :func:`maybe_ledger` returns ``None``, the
serving hot paths keep a single ``is None`` check, and lowering is
byte-identical (the ledger never enters traced code).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
import weakref
from typing import Dict, List, Optional

from distkeras_tpu.telemetry import runtime as _truntime

__all__ = [
    "DEFAULT_CAPACITY",
    "OTHER_TENANT",
    "QUEUE_WAIT_BUCKETS",
    "TenantLedger",
    "UNTAGGED_TENANT",
    "accounting_metrics",
    "configure",
    "enabled",
    "ledger_for",
    "ledger_payload",
    "ledger_view",
    "maybe_ledger",
    "merge_ledgers",
    "reset",
]

#: overflow bucket evicted tenants fold into — the "+1" of top-K+1
OTHER_TENANT = "__other__"

#: requests that arrive without a tenant key
UNTAGGED_TENANT = "__untagged__"

#: tracked tenants before eviction into ``__other__`` begins
DEFAULT_CAPACITY = 8

#: rolling-usage decay constant (seconds) — the window "tokens/sec" means
DEFAULT_TAU_S = 30.0

#: fixed per-tenant queue-wait ladder (coarse subset of the registry's
#: DEFAULT_BUCKETS).  Shared by every ledger so cross-process merges sum
#: bucket-exact and the merged p99 stays honest.
QUEUE_WAIT_BUCKETS = (0.001, 0.005, 0.025, 0.1, 0.5, 2.5, 10.0)

_FALSEY = ("", "0", "false", "no")

# None = not yet resolved from the environment; True/False once resolved
# or forced via configure().  Accounting defaults ON when telemetry is on.
_ENABLED = None


def _flag() -> bool:
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = os.environ.get(
            "DISTKERAS_ACCOUNTING", "1").lower() not in _FALSEY
    return _ENABLED


def enabled() -> bool:
    """True when per-tenant accounting is on: telemetry enabled AND
    ``DISTKERAS_ACCOUNTING`` not falsey (unset counts as on)."""
    return _truntime.enabled() and _flag()


def configure(on=None) -> None:
    """Force accounting on/off (``True``/``False``) or reset to env-driven
    (``None``) — same contract as :func:`telemetry.runtime.configure`.
    Telemetry itself must still be enabled for :func:`enabled` to be true."""
    global _ENABLED
    _ENABLED = on


def accounting_metrics(registry=None) -> dict:
    """Get-or-create the *aggregate* accounting instruments (default: the
    process-global registry).  One canonical home for names/help so the
    ledger, the golden test, and the CI smoke assert the same schema.
    Per-tenant breakdowns deliberately never enter the registry — they live
    in the ledger's bounded table, served as JSON (DK117)."""
    if registry is None:
        from distkeras_tpu.telemetry.metrics import metrics as registry
    return {
        "requests": registry.counter(
            "accounting_requests_total",
            help="requests billed to a tenant at the router (one per "
                 "request; failed failover attempts fold into the same "
                 "request, never billed twice)",
        ),
        "failover_attempts": registry.counter(
            "accounting_failover_attempts_total",
            help="extra dispatch attempts beyond the first, billed once "
                 "to the owning request at completion",
        ),
        "prefill_tokens": registry.counter(
            "accounting_prefill_tokens_total",
            help="prompt tokens prefilled, summed over tenants",
        ),
        "decode_tokens": registry.counter(
            "accounting_decode_tokens_total",
            help="generated tokens billed to tenants (tenant-summed this "
                 "equals serving_tokens_total exactly — conservation)",
        ),
        "spec_accepted": registry.counter(
            "accounting_spec_accepted_tokens_total",
            help="speculative draft tokens accepted, billed per tenant",
        ),
        "spec_rejected": registry.counter(
            "accounting_spec_rejected_tokens_total",
            help="speculative draft tokens rejected, billed per tenant",
        ),
        "queue_wait": registry.histogram(
            "accounting_queue_wait_seconds",
            help="per-request admission-queue wait billed to tenants",
        ),
        "page_seconds": registry.counter(
            "accounting_kv_page_seconds_total",
            help="KV page-seconds (pages held x wall seconds, sampled at "
                 "slot free)",
        ),
        "prefill_device_seconds": registry.counter(
            "accounting_prefill_device_seconds_total",
            help="estimated device-seconds spent in prefill, billed to "
                 "the admitted tenant",
        ),
        "decode_device_seconds": registry.counter(
            "accounting_decode_device_seconds_total",
            help="estimated device-seconds spent in decode (each step's "
                 "wall time split evenly across its active slots)",
        ),
        "tenants_tracked": registry.gauge(
            "accounting_tenants_tracked",
            help="tenants currently holding a ledger row (bounded top-K; "
                 "__other__ excluded)",
        ),
        "evictions": registry.counter(
            "accounting_tenant_evictions_total",
            help="ledger rows folded into __other__ to keep cardinality "
                 "fixed",
        ),
    }


class _TenantEntry:
    """One tenant's cumulative usage plus its decayed rolling-rate state."""

    __slots__ = (
        "tenant", "requests", "failover_attempts", "prefill_tokens",
        "decode_tokens", "spec_accepted", "spec_rejected", "queue_wait_s",
        "queue_counts", "page_seconds", "prefill_device_s",
        "decode_device_s", "rate_tokens", "rate_requests", "rate_t",
    )

    def __init__(self, tenant: str, now: float):
        self.tenant = tenant
        self.requests = 0
        self.failover_attempts = 0
        self.prefill_tokens = 0
        self.decode_tokens = 0
        self.spec_accepted = 0
        self.spec_rejected = 0
        self.queue_wait_s = 0.0
        self.queue_counts = [0] * (len(QUEUE_WAIT_BUCKETS) + 1)
        self.page_seconds = 0.0
        self.prefill_device_s = 0.0
        self.decode_device_s = 0.0
        # exponentially-decayed mass: rate = mass / tau
        self.rate_tokens = 0.0
        self.rate_requests = 0.0
        self.rate_t = now

    def decay(self, now: float, tau: float) -> None:
        dt = now - self.rate_t
        if dt > 0.0:
            f = math.exp(-dt / tau)
            self.rate_tokens *= f
            self.rate_requests *= f
            self.rate_t = now


class TenantLedger:
    """Bounded per-tenant usage table: top-``capacity`` tenants by rolling
    usage plus the ``__other__`` overflow bucket.  Thread-safe — the
    engine's loop thread, the router's dispatch threads, and HTTP scrapes
    all meter/read concurrently.  Every billing call also feeds the aggregate
    ``accounting_*`` instruments on ``registry``, so the fleet-mergeable
    totals and the per-tenant table can never drift apart."""

    def __init__(self, registry=None, *, capacity: int = DEFAULT_CAPACITY,
                 tau_s: float = DEFAULT_TAU_S, clock=time.monotonic):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.tau_s = float(tau_s)
        self._clock = clock
        self._metrics = accounting_metrics(registry)
        # re-entrant: billing sites hold it across _entry/_fold_into_other,
        # which also lock themselves — every write provably guarded (DK105)
        self._lock = threading.RLock()
        self._tenants: Dict[str, _TenantEntry] = {}
        self._evictions = 0

    # ------------------------------------------------------------ internals

    def _entry(self, tenant: str, now: float) -> _TenantEntry:
        name = str(tenant or "") or UNTAGGED_TENANT
        with self._lock:
            entry = self._tenants.get(name)
            if entry is not None:
                return entry
            if name != OTHER_TENANT:
                live = [e for n, e in self._tenants.items()
                        if n != OTHER_TENANT]
                if len(live) >= self.capacity:
                    # fold the coldest row into __other__: the newcomer gets
                    # a row (a late-arriving hot tenant must become visible),
                    # the evicted tail keeps its totals — conservation holds
                    for e in live:
                        e.decay(now, self.tau_s)
                    victim = min(live,
                                 key=lambda e: (e.rate_tokens, e.tenant))
                    self._fold_into_other(victim, now)
            entry = _TenantEntry(name, now)
            self._tenants[name] = entry
            tracked = sum(1 for n in self._tenants if n != OTHER_TENANT)
        self._metrics["tenants_tracked"].set(tracked)
        return entry

    def _fold_into_other(self, victim: _TenantEntry, now: float) -> None:
        with self._lock:
            other = self._tenants.get(OTHER_TENANT)
            if other is None:
                other = _TenantEntry(OTHER_TENANT, now)
                self._tenants[OTHER_TENANT] = other
            other.decay(now, self.tau_s)
            victim.decay(now, self.tau_s)
            other.requests += victim.requests
            other.failover_attempts += victim.failover_attempts
            other.prefill_tokens += victim.prefill_tokens
            other.decode_tokens += victim.decode_tokens
            other.spec_accepted += victim.spec_accepted
            other.spec_rejected += victim.spec_rejected
            other.queue_wait_s += victim.queue_wait_s
            for i, n in enumerate(victim.queue_counts):
                other.queue_counts[i] += n
            other.page_seconds += victim.page_seconds
            other.prefill_device_s += victim.prefill_device_s
            other.decode_device_s += victim.decode_device_s
            other.rate_tokens += victim.rate_tokens
            other.rate_requests += victim.rate_requests
            del self._tenants[victim.tenant]
            self._evictions += 1
        self._metrics["evictions"].inc()

    def _observe_queue(self, entry: _TenantEntry, seconds: float) -> None:
        entry.queue_wait_s += seconds
        for i, bound in enumerate(QUEUE_WAIT_BUCKETS):
            if seconds <= bound:
                entry.queue_counts[i] += 1
                return
        entry.queue_counts[-1] += 1

    # -------------------------------------------------------- billing sites

    def admit(self, tenant: str, *, prompt_tokens: int, queue_wait_s: float,
              device_s: float, generated: int = 1) -> None:
        """Bill one admission (the engine's prefill site): prompt tokens,
        queue wait, prefill device-seconds, and the first sampled token."""
        now = self._clock()
        with self._lock:
            entry = self._entry(tenant, now)
            entry.decay(now, self.tau_s)
            entry.prefill_tokens += int(prompt_tokens)
            entry.decode_tokens += int(generated)
            self._observe_queue(entry, float(queue_wait_s))
            entry.prefill_device_s += float(device_s)
            entry.rate_tokens += float(prompt_tokens + generated)
        self._metrics["prefill_tokens"].inc(int(prompt_tokens))
        if generated:
            self._metrics["decode_tokens"].inc(int(generated))
        self._metrics["queue_wait"].observe(float(queue_wait_s))
        self._metrics["prefill_device_seconds"].inc(float(device_s))

    def decode(self, tenant: str, *, tokens: int, device_s: float) -> None:
        """Bill one slot's share of a decode step: emitted tokens plus an
        even split of the step's wall time."""
        now = self._clock()
        with self._lock:
            entry = self._entry(tenant, now)
            entry.decay(now, self.tau_s)
            entry.decode_tokens += int(tokens)
            entry.decode_device_s += float(device_s)
            entry.rate_tokens += float(tokens)
        if tokens:
            self._metrics["decode_tokens"].inc(int(tokens))
        self._metrics["decode_device_seconds"].inc(float(device_s))

    def speculative(self, tenant: str, *, accepted: int,
                    rejected: int) -> None:
        """Bill one slot's speculative verify verdict."""
        now = self._clock()
        with self._lock:
            entry = self._entry(tenant, now)
            entry.spec_accepted += int(accepted)
            entry.spec_rejected += int(rejected)
        self._metrics["spec_accepted"].inc(int(accepted))
        self._metrics["spec_rejected"].inc(int(rejected))

    def release(self, tenant: str, *, pages: int, held_s: float) -> None:
        """Sample page-seconds at slot free: pages held × wall seconds."""
        page_s = float(pages) * max(0.0, float(held_s))
        now = self._clock()
        with self._lock:
            entry = self._entry(tenant, now)
            entry.page_seconds += page_s
        self._metrics["page_seconds"].inc(page_s)

    def request(self, tenant: str, *, attempts: int = 1,
                latency_s: float = 0.0) -> None:
        """Router-level attribution, called exactly once per completed
        request: failed failover attempts bill here as ``attempts - 1``,
        never per attempt."""
        del latency_s  # router latency already has a registry histogram
        extra = max(0, int(attempts) - 1)
        now = self._clock()
        with self._lock:
            entry = self._entry(tenant, now)
            entry.decay(now, self.tau_s)
            entry.requests += 1
            entry.failover_attempts += extra
            entry.rate_requests += 1.0
        self._metrics["requests"].inc()
        if extra:
            self._metrics["failover_attempts"].inc(extra)

    # ------------------------------------------------------------ inspection

    def rolling_rate(self, tenant: str, unit: str = "tokens") -> float:
        """The tenant's decayed usage rate in ``unit``/sec (``"tokens"`` or
        ``"requests"``); 0.0 for an unknown tenant.  This is the signal the
        online :class:`~distkeras_tpu.online.capture.SamplingPolicy` rate
        policy keys off, and the ranking evictions use."""
        if unit not in ("tokens", "requests"):
            raise ValueError(f"unit must be 'tokens' or 'requests', got {unit!r}")
        name = str(tenant or "") or UNTAGGED_TENANT
        now = self._clock()
        with self._lock:
            entry = self._tenants.get(name)
            if entry is None:
                return 0.0
            entry.decay(now, self.tau_s)
            mass = (entry.rate_tokens if unit == "tokens"
                    else entry.rate_requests)
        return mass / self.tau_s

    def snapshot(self) -> dict:
        """JSON-safe per-tenant table (the ``/ledger`` endpoint body and
        ``dkmon top``'s input), sorted by total tokens descending.  Bucket
        counts ride along so :func:`merge_ledgers` merges exactly."""
        now = self._clock()
        with self._lock:
            rows = []
            for entry in self._tenants.values():
                entry.decay(now, self.tau_s)
                rows.append({
                    "tenant": entry.tenant,
                    "requests": entry.requests,
                    "failover_attempts": entry.failover_attempts,
                    "prefill_tokens": entry.prefill_tokens,
                    "decode_tokens": entry.decode_tokens,
                    "spec_accepted": entry.spec_accepted,
                    "spec_rejected": entry.spec_rejected,
                    "queue_wait_s": entry.queue_wait_s,
                    "queue_buckets": _cumulative_buckets(entry.queue_counts),
                    "page_seconds": entry.page_seconds,
                    "device_seconds": {
                        "prefill": entry.prefill_device_s,
                        "decode": entry.decode_device_s,
                    },
                    "tokens_per_s": entry.rate_tokens / self.tau_s,
                    "requests_per_s": entry.rate_requests / self.tau_s,
                })
            evictions = self._evictions
        return _finish_payload(rows, evictions, capacity=self.capacity)


def _cumulative_buckets(counts: List[int]) -> Dict[str, int]:
    out, cum = {}, 0
    for bound, n in zip(QUEUE_WAIT_BUCKETS, counts):
        cum += n
        out[repr(float(bound))] = cum
    out["+Inf"] = cum + counts[-1]
    return out


def _finish_payload(rows: List[dict], evictions: int,
                    capacity: Optional[int] = None) -> dict:
    """Sort rows, stamp share-of-fleet and queue p99, and total up."""
    from distkeras_tpu.telemetry.flightdeck.rollup import (
        quantile_from_cumulative,
    )

    total_tokens = sum(r["prefill_tokens"] + r["decode_tokens"] for r in rows)
    for row in rows:
        mine = row["prefill_tokens"] + row["decode_tokens"]
        row["share"] = (mine / total_tokens) if total_tokens else 0.0
        row["queue_p99_s"] = quantile_from_cumulative(
            row["queue_buckets"], 0.99)
    rows.sort(key=lambda r: (-(r["prefill_tokens"] + r["decode_tokens"]),
                             r["tenant"]))
    payload = {
        "enabled": True,
        "tenants": rows,
        "evictions": int(evictions),
        "totals": {
            "tokens": total_tokens,
            "requests": sum(r["requests"] for r in rows),
            "page_seconds": sum(r["page_seconds"] for r in rows),
        },
    }
    if capacity is not None:
        payload["capacity"] = int(capacity)
    return payload


def merge_ledgers(payloads: List[dict]) -> dict:
    """Fleet-merge ledger snapshots tenant-wise by name: counters and
    page/device/queue sums add, rolling rates add (fleet tokens/sec is
    additive), bucket counts add per bound so the merged p99 is as exact
    as any single ladder.  Share is recomputed over the merged totals."""
    merged: Dict[str, dict] = {}
    evictions = 0
    for payload in payloads:
        if not payload:
            continue
        evictions += int(payload.get("evictions") or 0)
        for row in payload.get("tenants") or ():
            name = row["tenant"]
            into = merged.get(name)
            if into is None:
                into = {
                    "tenant": name, "requests": 0, "failover_attempts": 0,
                    "prefill_tokens": 0, "decode_tokens": 0,
                    "spec_accepted": 0, "spec_rejected": 0,
                    "queue_wait_s": 0.0, "queue_buckets": {},
                    "page_seconds": 0.0,
                    "device_seconds": {"prefill": 0.0, "decode": 0.0},
                    "tokens_per_s": 0.0, "requests_per_s": 0.0,
                }
                merged[name] = into
            for key in ("requests", "failover_attempts", "prefill_tokens",
                        "decode_tokens", "spec_accepted", "spec_rejected"):
                into[key] += int(row.get(key) or 0)
            for key in ("queue_wait_s", "page_seconds", "tokens_per_s",
                        "requests_per_s"):
                into[key] += float(row.get(key) or 0.0)
            for phase in ("prefill", "decode"):
                into["device_seconds"][phase] += float(
                    (row.get("device_seconds") or {}).get(phase) or 0.0)
            for le, cum in (row.get("queue_buckets") or {}).items():
                into["queue_buckets"][le] = (
                    into["queue_buckets"].get(le, 0) + int(cum))
    return _finish_payload(list(merged.values()), evictions)


# ------------------------------------------------------- per-registry wiring

_LEDGERS = weakref.WeakKeyDictionary()
_LEDGER_LOCK = threading.Lock()
_GLOBAL_KEY = None


def ledger_for(registry=None) -> TenantLedger:
    """Get-or-create the ledger bound to ``registry`` (default: the
    process-global one) — same get-or-create discipline as the metric
    helpers, so an engine and its router share one table per registry."""
    global _GLOBAL_KEY
    if registry is None:
        from distkeras_tpu.telemetry.metrics import metrics as registry
        _GLOBAL_KEY = registry
    with _LEDGER_LOCK:
        ledger = _LEDGERS.get(registry)
        if ledger is None:
            ledger = TenantLedger(registry)
            _LEDGERS[registry] = ledger
        return ledger


def maybe_ledger(registry=None) -> Optional[TenantLedger]:
    """The serving hot-path hook: the registry's ledger when accounting is
    enabled, else ``None`` (callers keep a single ``is None`` check)."""
    if not enabled():
        return None
    return ledger_for(registry)


def reset() -> None:
    """Drop every cached ledger (tests; pairs with ``metrics.reset()``)."""
    with _LEDGER_LOCK:
        _LEDGERS.clear()


def ledger_payload() -> dict:
    """The process-global ledger's snapshot, or the disabled shape — what
    the daemon's ``ledger_status`` verb reports for its own process."""
    if not enabled():
        return {"enabled": False, "tenants": []}
    return ledger_for().snapshot()


def ledger_view(request: Optional[dict] = None):
    """``/ledger`` flightdeck endpoint body: the process-global ledger as
    JSON (disabled-shaped when accounting is off, so scrapers can tell
    "off" from "idle")."""
    del request
    return ("application/json", json.dumps(ledger_payload()), 200)
