"""Metrics registry: counters, gauges, bounded-bucket histograms.

Instruments are get-or-create through a process-global :data:`metrics`
registry, so call sites never need to coordinate construction:

    telemetry.metrics.counter("checkpoints_saved_total").inc()
    telemetry.metrics.histogram("phase_step_seconds").observe(dt)

Exporters: Prometheus text exposition (served by the ``job_deployment``
daemon's ``metrics`` verb), JSONL snapshots, and a bridge into the existing
``utils.tb.ScalarLogger``.  ``install_jax_hooks()`` wires ``jax.monitoring``
listeners so retraces/compiles show up as ``jax_compiles_total`` without any
polling of jit internals.

Histograms are bounded by construction: a fixed bucket ladder plus one
overflow slot, so a runaway workload can never grow memory.  All mutation is
behind a per-instrument lock; reads of a single float/int are atomic in
CPython and done off-lock.
"""

from __future__ import annotations

import bisect
import json
import math
import threading

from distkeras_tpu.telemetry import runtime
from distkeras_tpu.telemetry.flightdeck.recorder import recorder as _flight_recorder

__all__ = [
    "Counter",
    "DEFAULT_BUCKETS",
    "Gauge",
    "Histogram",
    "PHASES",
    "Registry",
    "install_jax_hooks",
    "merge_snapshots",
    "metrics",
    "prometheus_from_snapshot",
]

# Exponential seconds ladder: 100µs .. 60s covers everything from a single
# h2d transfer to a full-epoch dispatch; beyond that lands in +Inf.
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# Canonical phase names for the bench breakdown ("where did the step time
# go?").  Spans opened with phase=<name> feed phase_<name>_seconds.
PHASES = ("data", "h2d", "step", "commit")


class Counter:
    """Monotonically increasing float counter."""

    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount=1.0):
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount
            value = self._value
        if runtime.enabled():
            _flight_recorder.record_metric(self.name, value)

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins float gauge."""

    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value):
        with self._lock:
            self._value = float(value)
        if runtime.enabled():
            _flight_recorder.record_metric(self.name, float(value))

    @property
    def value(self):
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram (cumulative ``le`` semantics on export)."""

    __slots__ = ("name", "help", "buckets", "_lock", "_counts", "_sum", "_count")

    def __init__(self, name, help="", buckets=DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one finite bucket")
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)  # +1 = +Inf overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, value):
        value = float(value)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    @property
    def sum(self):
        with self._lock:
            return self._sum

    @property
    def count(self):
        with self._lock:
            return self._count

    def cumulative(self):
        """[(upper_bound_label, cumulative_count), ...] ending with +Inf."""
        with self._lock:
            counts = list(self._counts)
        out, running = [], 0
        for bound, n in zip(self.buckets, counts):
            running += n
            out.append((_fmt_float(bound), running))
        out.append(("+Inf", running + counts[-1]))
        return out


def _fmt_float(v):
    """Prometheus-friendly number rendering: 0.005, 1, 10 — no 1e-05."""
    s = f"{v:.10f}".rstrip("0").rstrip(".")
    return s if s else "0"


def _label_suffix(labels, first=None):
    """``{le="0.5",run_id="abc"}`` — ``first`` (a ``(k, v)`` pair) leads so
    histogram ``le`` keeps its customary position; the rest sort by key.
    Empty string when there is nothing to render (keeps unlabelled output —
    and its goldens — byte-identical)."""
    pairs = []
    if first is not None:
        pairs.append(first)
    if labels:
        pairs.extend(sorted(labels.items()))
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in pairs) + "}"


class Registry:
    """Get-or-create home for named instruments."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments = {}

    def _get_or_create(self, cls, name, help, **kwargs):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, help=help, **kwargs)
                self._instruments[name] = inst
        if not isinstance(inst, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(inst).__name__}, requested {cls.__name__}"
            )
        return inst

    def counter(self, name, help="") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name, help="") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name, help="", buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def reset(self):
        with self._lock:
            self._instruments.clear()

    # ------------------------------------------------------------ exporters

    def snapshot(self) -> dict:
        """JSON-safe dict of every instrument's current state."""
        with self._lock:
            items = list(self._instruments.items())
        out = {}
        for name, inst in sorted(items):
            if isinstance(inst, Counter):
                out[name] = {"type": "counter", "value": inst.value}
            elif isinstance(inst, Gauge):
                out[name] = {"type": "gauge", "value": inst.value}
            else:
                out[name] = {
                    "type": "histogram",
                    "sum": inst.sum,
                    "count": inst.count,
                    "buckets": {le: n for le, n in inst.cumulative()},
                }
        return out

    def to_prometheus(self, labels=None) -> str:
        """Prometheus text exposition format (v0.0.4).

        ``labels`` (a flat dict) is stamped onto every sample — the live
        scrape passes ``{"run_id": ...}`` so fleet dashboards can join
        processes; ``None`` keeps the output byte-identical to before.
        """
        with self._lock:
            items = list(self._instruments.items())
        sfx = _label_suffix(labels)
        lines = []
        for name, inst in sorted(items):
            kind = ("counter" if isinstance(inst, Counter)
                    else "gauge" if isinstance(inst, Gauge)
                    else "histogram")
            if inst.help:
                lines.append(f"# HELP {name} {inst.help}")
            lines.append(f"# TYPE {name} {kind}")
            if kind == "histogram":
                for le, n in inst.cumulative():
                    lines.append(
                        f"{name}_bucket{_label_suffix(labels, ('le', le))} {n}"
                    )
                lines.append(f"{name}_sum{sfx} {_fmt_float(inst.sum)}")
                lines.append(f"{name}_count{sfx} {inst.count}")
            else:
                lines.append(f"{name}{sfx} {_fmt_float(inst.value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def write_jsonl(self, path, extra=None) -> str:
        """Append one snapshot line to ``path``; returns the path."""
        record = dict(extra or {})
        record["metrics"] = self.snapshot()
        with open(path, "a") as fh:
            fh.write(json.dumps(record) + "\n")
        return path

    def to_scalar_logger(self, logger, step) -> None:
        """Bridge into ``utils.tb.ScalarLogger``: counters/gauges as-is,
        histograms as ``<name>_sum``/``<name>_count``."""
        scalars = {}
        for name, payload in self.snapshot().items():
            if payload["type"] == "histogram":
                scalars[f"{name}_sum"] = payload["sum"]
                scalars[f"{name}_count"] = payload["count"]
            else:
                scalars[name] = payload["value"]
        if scalars:
            logger.log(step, **scalars)

    def phase_breakdown(self) -> dict:
        """Seconds spent per phase, from the ``phase_*_seconds`` histograms
        that span exits feed.  Always contains the canonical four keys."""
        with self._lock:
            items = list(self._instruments.items())
        out = {p: 0.0 for p in PHASES}
        for name, inst in items:
            if (isinstance(inst, Histogram) and name.startswith("phase_")
                    and name.endswith("_seconds")):
                out[name[len("phase_"):-len("_seconds")]] = inst.sum
        return out


# -------------------------------------------------- fleet-level aggregation


def _le_key(le):
    return math.inf if le == "+Inf" else float(le)


def _le_label(le):
    return "+Inf" if _le_key(le) == math.inf else _fmt_float(float(le))


def _merge_histograms(payloads) -> dict:
    """Merge histogram snapshots on their cumulative bounded-bucket form.

    The merged ladder is the union of the inputs' ``le`` labels.  A snapshot
    missing a label contributes its cumulative count at its largest bound
    <= that label (carry-forward) — exact for cumulative distributions, so
    merging loses nothing as long as jobs share a ladder, and degrades
    conservatively (counts attributed to the next coarser bound) when they
    don't.  Sums and counts add."""
    per_snap = []
    labels = set()
    for p in payloads:
        bounds = sorted(((_le_key(le), n) for le, n in p["buckets"].items()))
        per_snap.append(bounds)
        labels.update(_le_key(le) for le in p["buckets"])
    merged = {}
    for le_val in sorted(labels):
        total = 0
        for bounds in per_snap:
            idx = bisect.bisect_right([b for b, _ in bounds], le_val) - 1
            total += bounds[idx][1] if idx >= 0 else 0
        merged[_le_label(le_val)] = total
    return {
        "type": "histogram",
        "sum": sum(p["sum"] for p in payloads),
        "count": sum(p["count"] for p in payloads),
        "buckets": merged,
    }


def merge_snapshots(snapshots) -> dict:
    """Merge per-job :meth:`Registry.snapshot` dicts into one fleet view.

    Counters sum (fleet totals); gauges keep the **max** as their value —
    for health stats the worst worker is the signal — and carry the fleet
    ``mean`` alongside; histograms merge exactly via
    :func:`_merge_histograms`.  Raises on a name registered with different
    types across jobs."""
    merged: dict = {}
    grouped: dict = {}
    for snap in snapshots:
        for name, payload in snap.items():
            grouped.setdefault(name, []).append(payload)
    for name, payloads in sorted(grouped.items()):
        kinds = {p["type"] for p in payloads}
        if len(kinds) > 1:
            raise ValueError(
                f"metric {name!r} has conflicting types across jobs: "
                f"{sorted(kinds)}"
            )
        kind = kinds.pop()
        if kind == "counter":
            merged[name] = {
                "type": "counter",
                "value": sum(p["value"] for p in payloads),
            }
        elif kind == "gauge":
            values = [p["value"] for p in payloads]
            merged[name] = {
                "type": "gauge",
                "value": max(values),
                "mean": sum(values) / len(values),
            }
        else:
            merged[name] = _merge_histograms(payloads)
    return merged


def prometheus_from_snapshot(snapshot, help_map=None, labels=None) -> str:
    """Prometheus text exposition for a snapshot dict (per-job or merged).

    Merged gauges (carrying a ``mean``) export two labelled samples,
    ``{agg="max"}`` and ``{agg="mean"}``; everything else renders exactly
    like :meth:`Registry.to_prometheus`.  ``labels`` stamps every sample
    (the fleet scrape passes the run_id) and composes with ``le``/``agg``."""
    sfx = _label_suffix(labels)
    lines = []
    for name, payload in sorted(snapshot.items()):
        kind = payload["type"]
        help_text = (help_map or {}).get(name)
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        if kind == "histogram":
            for le, n in payload["buckets"].items():
                lines.append(
                    f"{name}_bucket{_label_suffix(labels, ('le', le))} {n}"
                )
            lines.append(f"{name}_sum{sfx} {_fmt_float(payload['sum'])}")
            lines.append(f"{name}_count{sfx} {payload['count']}")
        elif kind == "gauge" and "mean" in payload:
            max_sfx = _label_suffix(labels, ("agg", "max"))
            mean_sfx = _label_suffix(labels, ("agg", "mean"))
            lines.append(f"{name}{max_sfx} {_fmt_float(payload['value'])}")
            lines.append(f"{name}{mean_sfx} {_fmt_float(payload['mean'])}")
        else:
            lines.append(f"{name}{sfx} {_fmt_float(payload['value'])}")
    return "\n".join(lines) + ("\n" if lines else "")


# Process-global registry: one scrape surface per process, like the tracer.
metrics = Registry()

_JAX_HOOKS_INSTALLED = False


def install_jax_hooks(registry=None) -> bool:
    """Register ``jax.monitoring`` listeners that count compiles/retraces.

    Idempotent; returns False when jax (or its monitoring module) is absent.
    Listeners are permanent per jax's API, so they consult ``enabled()`` at
    event time rather than registration time.
    """
    global _JAX_HOOKS_INSTALLED
    if _JAX_HOOKS_INSTALLED:
        return True
    try:
        from jax import monitoring
    except Exception:
        return False
    reg = registry if registry is not None else metrics

    def _on_event(event, **kw):
        if not runtime.enabled():
            return
        if "compil" in event or "trace" in event:
            reg.counter(
                "jax_compiles_total",
                help="jax.monitoring compile/trace events observed",
            ).inc()

    def _on_duration(event, duration=0.0, **kw):
        if not runtime.enabled():
            return
        if "compil" in event or "trace" in event:
            reg.histogram(
                "jax_compile_seconds",
                help="duration of jax compile/trace events",
            ).observe(duration)

    monitoring.register_event_listener(_on_event)
    monitoring.register_event_duration_secs_listener(_on_duration)
    _JAX_HOOKS_INSTALLED = True
    return True
