"""Step-windowed ``jax.profiler`` capture.

Opt-in via ``DISTKERAS_PROFILE=<dir>`` (plus optional
``DISTKERAS_PROFILE_STEPS=<start>:<stop>``, default ``1:2`` — skip epoch 0
so compile noise stays out of the capture).  The trainer calls
``on_step(epoch)`` at the top of each epoch and ``close()`` when done; the
hook starts/stops ``jax.profiler`` exactly once over the half-open window
``[start, stop)``.

jax is imported lazily so this module stays importable (and testable by
monkeypatching ``_start``/``_stop``) on hosts without a backend.
"""

from __future__ import annotations

import os

__all__ = ["ProfilerHook"]


class ProfilerHook:
    """Start/stop ``jax.profiler`` over a step (epoch) range."""

    def __init__(self, logdir, start_step=1, stop_step=None):
        self.logdir = logdir
        self.start_step = int(start_step)
        self.stop_step = int(stop_step) if stop_step is not None else self.start_step + 1
        if self.stop_step <= self.start_step:
            raise ValueError("stop_step must be > start_step")
        self.active = False
        self.done = False

    @classmethod
    def from_env(cls):
        """Build from ``DISTKERAS_PROFILE`` / ``DISTKERAS_PROFILE_STEPS``;
        None when profiling is not requested."""
        logdir = os.environ.get("DISTKERAS_PROFILE")
        if not logdir:
            return None
        steps = os.environ.get("DISTKERAS_PROFILE_STEPS", "1:2")
        try:
            lo, _, hi = steps.partition(":")
            start, stop = int(lo), int(hi) if hi else int(lo) + 1
        except ValueError:
            raise ValueError(
                f"DISTKERAS_PROFILE_STEPS must be 'start:stop', got {steps!r}"
            ) from None
        return cls(logdir, start, stop)

    # Separated so tests can monkeypatch without a real profiler session.
    def _start(self):
        import jax

        os.makedirs(self.logdir, exist_ok=True)
        jax.profiler.start_trace(self.logdir)

    def _stop(self):
        import jax

        jax.profiler.stop_trace()

    def on_step(self, step) -> None:
        """Call at the top of each step/epoch with its index."""
        if self.active and step >= self.stop_step:
            self._stop()
            self.active = False
            self.done = True
        if (not self.active and not self.done
                and self.start_step <= step < self.stop_step):
            self._start()
            self.active = True

    def close(self) -> None:
        """Stop the capture if the run ended inside the window."""
        if self.active:
            self._stop()
            self.active = False
            self.done = True
