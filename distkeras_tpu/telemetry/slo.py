"""SLO engine: declarative objectives, burn-rate alerting, incident log.

The rollup ring (:mod:`.flightdeck.rollup`) holds windowed history; this
module turns it into *decisions*.  An :class:`SLOConfig` states an objective
over one of three signal shapes:

* ``"quantile"`` — a latency histogram must keep ``target`` of its
  observations under ``threshold`` (``serving_ttft_seconds p99 < 250ms`` is
  ``quantile=0.99, threshold=0.25, target=0.99``);
* ``"gauge"`` — a gauge must stay on the right side of ``threshold``
  (``online_window_lag_seconds < 2×window``, or ``op="lt"`` for
  ``serving_tier_replicas_healthy >= 1``);
* ``"ratio"`` — a bad-event counter must stay under ``1 - target`` of a
  total-event counter (shed ratio, error ratio).

Each objective is evaluated as a **burn rate**: the observed bad fraction
divided by the error budget (``1 - target``).  Burn 1.0 means the budget is
being spent exactly as fast as it accrues; burn 10 means ten times too
fast.  Alerts use the Prometheus multi-window recipe — fire only when BOTH
a fast window (reactive, noisy) and a slow window (confirming, stable)
burn at or above ``burn_threshold``; resolve when the fast window drops
back under it.  Fire/resolve transitions append one JSON line each to an
**incident log** (single ``O_APPEND`` write per record, so concurrent
engines interleave whole lines), stamped with the fleet ``run_id`` and the
worst-offending ``trace_id``s still in the flight-recorder ring — the
operator jumps straight from the page to ``dktrace critical-path``.

Evaluation is wired into loops that already exist (the serving tier's probe
loop, the window scheduler's poll loop) via :func:`maybe_engine`, which
returns ``None`` unless telemetry *and* ``DISTKERAS_ROLLUP`` are on — the
flag-off path stays byte-identical.  ``tools.dkmon`` and the daemon's
``slo_status`` verb consume the ``/slo`` endpoint this module installs.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Union

from distkeras_tpu.telemetry import runtime as _runtime
from distkeras_tpu.telemetry.flightdeck import correlate as _correlate
from distkeras_tpu.telemetry.flightdeck import rollup as _rollup
from distkeras_tpu.telemetry.flightdeck.recorder import recorder as _recorder

__all__ = [
    "SLOConfig",
    "SLOEngine",
    "breach_fraction_from_cumulative",
    "default_online_objectives",
    "default_serving_objectives",
    "engines",
    "incident_path",
    "install_slo_endpoint",
    "maybe_engine",
    "reset_engines",
    "slo_metrics",
    "slo_view",
    "worst_trace_ids",
]

KINDS = ("quantile", "gauge", "ratio")


@dataclass(frozen=True)
class SLOConfig:
    """One declarative objective; see module docstring for the kinds."""

    name: str
    kind: str
    metric: str = ""
    quantile: float = 0.99
    threshold: float = 0.0
    op: str = "gt"
    bad_metric: str = ""
    total_metric: Union[str, Sequence[str]] = ""
    target: float = 0.99
    fast_window_s: float = 30.0
    slow_window_s: float = 120.0
    burn_threshold: float = 2.0
    description: str = ""

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"kind must be one of {KINDS}, got {self.kind!r}")
        if not 0.0 < self.target < 1.0:
            raise ValueError(f"target must be in (0, 1), got {self.target}")
        if self.kind in ("quantile", "gauge") and not self.metric:
            raise ValueError(f"objective {self.name!r} needs a metric")
        if self.kind == "ratio" and not (self.bad_metric and self.total_metric):
            raise ValueError(
                f"objective {self.name!r} needs bad_metric and total_metric")
        if self.fast_window_s >= self.slow_window_s:
            raise ValueError(
                f"objective {self.name!r}: fast window must be shorter "
                f"than slow window")

    @property
    def budget(self) -> float:
        return 1.0 - self.target


def breach_fraction_from_cumulative(buckets: Dict[str, float],
                                    threshold: float) -> float:
    """Fraction of observations above ``threshold``, from cumulative
    ``{le: count}`` buckets.  Exact when the threshold sits on a bucket
    boundary; linear within a bucket otherwise.  Observations in the +Inf
    overflow count as breaching any finite threshold at or above the top
    finite bound (the conservative reading of a bounded ladder)."""
    from distkeras_tpu.telemetry.metrics import _le_key

    ladder = sorted(((_le_key(le), n) for le, n in buckets.items()))
    total = ladder[-1][1] if ladder else 0
    if total <= 0:
        return 0.0
    prev_bound, prev_cum = 0.0, 0
    cum_at = None
    for bound, cum in ladder:
        if math.isinf(bound):
            continue
        if threshold <= bound:
            if threshold == bound:
                cum_at = cum
            elif bound == prev_bound:
                cum_at = cum
            else:
                frac = max(0.0, (threshold - prev_bound) / (bound - prev_bound))
                cum_at = prev_cum + frac * (cum - prev_cum)
            break
        prev_bound, prev_cum = bound, cum
    if cum_at is None:
        # Threshold above the top finite bound: only +Inf overflow breaches.
        cum_at = prev_cum
    return max(0.0, 1.0 - cum_at / total)


def worst_trace_ids(limit: int = 3) -> List[str]:
    """Trace ids of the longest spans still in the flight-recorder ring —
    the "worst offenders" stamped into incident records."""
    best: Dict[str, float] = {}
    for e in _recorder.events():
        if e.get("kind") != "span":
            continue
        event = e.get("event") or {}
        args = event.get("args") or {}
        dur = float(event.get("dur") or 0.0)
        tids = []
        if args.get("trace_id"):
            tids.append(args["trace_id"])
        tids.extend(args.get("trace_ids") or ())
        for tid in tids:
            if dur >= best.get(tid, -1.0):
                best[tid] = dur
    ranked = sorted(best.items(), key=lambda kv: kv[1], reverse=True)
    return [tid for tid, _ in ranked[:limit]]


def incident_path() -> str:
    """Where incident records land: ``DISTKERAS_SLO_INCIDENTS`` when set,
    else ``incidents_<run_id>.jsonl`` in the telemetry directory."""
    explicit = os.environ.get("DISTKERAS_SLO_INCIDENTS")
    if explicit:
        return explicit
    rid = _correlate.current() or f"pid{os.getpid()}"
    return os.path.join(_runtime.out_dir(), f"incidents_{rid}.jsonl")


def slo_metrics(registry=None) -> dict:
    """Get-or-create the engine's instruments (default: process-global
    registry).  One canonical home for names/help so the engine, the golden
    test, and the CI dkmon smoke assert the same schema."""
    if registry is None:
        from distkeras_tpu.telemetry.metrics import metrics as registry
    return {
        "objectives": registry.gauge(
            "slo_objectives",
            help="SLO objectives registered across live engines",
        ),
        "evaluations": registry.counter(
            "slo_evaluations_total",
            help="SLO evaluation passes across live engines",
        ),
        "burning": registry.gauge(
            "slo_burning",
            help="objectives whose fast-window burn rate is at or above "
                 "their alert threshold",
        ),
        "burn_max": registry.gauge(
            "slo_burn_rate_max",
            help="worst fast-window burn rate across objectives "
                 "(1.0 = error budget spent exactly as fast as it accrues)",
        ),
        "firing": registry.gauge(
            "alert_firing",
            help="alerts currently firing (fast AND slow windows over "
                 "their burn threshold)",
        ),
        "fired": registry.counter(
            "alert_fired_total",
            help="alert fire transitions",
        ),
        "resolved": registry.counter(
            "alert_resolved_total",
            help="alert resolve transitions",
        ),
        "incidents": registry.counter(
            "alert_incidents_total",
            help="incident log records appended (fire + resolve lines)",
        ),
    }


class SLOEngine:
    """Evaluates a set of objectives against a rollup ring.

    One engine per subsystem (``source`` names it: "serving_tier",
    "online"); all engines in a process share the global rollup ring, the
    canonical ``slo_*``/``alert_*`` instruments, and the ``/slo`` endpoint.
    ``evaluate()`` is called from the owner's existing loop — it reads ring
    snapshots and writes at most two incident lines per objective per
    transition, so it is safe at probe-loop cadence.
    """

    def __init__(self, objectives: Sequence[SLOConfig], source: str = "slo",
                 ring: Optional[_rollup.RollupRing] = None, registry=None,
                 clock=time.time, incident_file: Optional[str] = None):
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        self.objectives = tuple(objectives)
        self.source = source
        self._ring = ring
        self._registry = registry
        self.clock = clock
        self._incident_file = incident_file
        self._lock = threading.Lock()
        self._state = {
            o.name: {"firing": False, "since": None} for o in objectives
        }
        self._last: Optional[dict] = None

    @property
    def ring(self) -> Optional[_rollup.RollupRing]:
        return self._ring if self._ring is not None else _rollup.rollup_ring()

    def _metrics(self) -> dict:
        return slo_metrics(self._registry)

    # ------------------------------------------------------------ evaluation

    def _bad_fraction(self, o: SLOConfig, window_s: float, now: float,
                      ring: _rollup.RollupRing) -> Optional[float]:
        """Observed bad fraction over one window; ``None`` = not enough
        ring history to tell (distinct from a measured 0.0)."""
        if o.kind == "quantile":
            delta = ring.window_delta(o.metric, window_s, now)
            if delta is None:
                return None
            if delta["count"] == 0:
                return 0.0  # no traffic spends no budget
            return breach_fraction_from_cumulative(delta["buckets"],
                                                   o.threshold)
        if o.kind == "gauge":
            return ring.window_breach_fraction(o.metric, o.threshold,
                                               window_s, now, op=o.op)
        bad = ring.window_rate(o.bad_metric, window_s, now)
        totals = ([o.total_metric] if isinstance(o.total_metric, str)
                  else list(o.total_metric))
        rates = [ring.window_rate(m, window_s, now) for m in totals]
        if bad is None or any(r is None for r in rates):
            return None
        total = sum(rates)
        if total <= 0:
            return 0.0
        return min(1.0, bad / total)

    def evaluate(self, now: Optional[float] = None) -> dict:
        """One evaluation pass: burn rates, alert transitions, incidents.
        Returns (and caches) the status dict the ``/slo`` endpoint serves."""
        ring = self.ring
        now = self.clock() if now is None else float(now)
        inst = self._metrics()
        if ring is None:
            status = {"source": self.source, "enabled": False, "unix": now,
                      "objectives": []}
            with self._lock:
                self._last = status
            return status
        rows = []
        with self._lock:
            for o in self.objectives:
                bad_fast = self._bad_fraction(o, o.fast_window_s, now, ring)
                bad_slow = self._bad_fraction(o, o.slow_window_s, now, ring)
                burn_fast = None if bad_fast is None else bad_fast / o.budget
                burn_slow = None if bad_slow is None else bad_slow / o.budget
                observed = None
                if o.kind == "quantile":
                    observed = ring.window_quantile(
                        o.metric, o.quantile, o.fast_window_s, now)
                state = self._state[o.name]
                should_fire = (
                    burn_fast is not None and burn_slow is not None
                    and burn_fast >= o.burn_threshold
                    and burn_slow >= o.burn_threshold
                )
                should_resolve = (
                    state["firing"]
                    and (burn_fast or 0.0) < o.burn_threshold
                )
                if should_fire and not state["firing"]:
                    state["firing"], state["since"] = True, now
                    inst["fired"].inc()
                    self._incident("fire", o, now, burn_fast, burn_slow,
                                   observed, inst)
                elif should_resolve:
                    state["firing"], state["since"] = False, None
                    inst["resolved"].inc()
                    self._incident("resolve", o, now, burn_fast, burn_slow,
                                   observed, inst)
                rows.append({
                    "name": o.name,
                    "kind": o.kind,
                    "metric": o.metric or o.bad_metric,
                    "threshold": o.threshold,
                    "target": o.target,
                    "burn_threshold": o.burn_threshold,
                    "bad_fast": bad_fast,
                    "bad_slow": bad_slow,
                    "burn_fast": burn_fast,
                    "burn_slow": burn_slow,
                    "observed": observed,
                    "firing": state["firing"],
                    "since": state["since"],
                    "description": o.description,
                })
            status = {"source": self.source, "enabled": True, "unix": now,
                      "objectives": rows}
            self._last = status
        inst["evaluations"].inc()
        _update_fleet_gauges(inst)
        return status

    def status(self) -> dict:
        """Last evaluation result (an empty shell before the first pass)."""
        with self._lock:
            if self._last is not None:
                return self._last
        return {"source": self.source, "enabled": self.ring is not None,
                "unix": None, "objectives": []}

    # -------------------------------------------------------------- incidents

    def _incident(self, event: str, o: SLOConfig, now: float,
                  burn_fast, burn_slow, observed, inst) -> None:
        record = {
            "event": event,
            "objective": o.name,
            "source": self.source,
            "unix": now,
            "run_id": _correlate.current(),
            "burn_fast": burn_fast,
            "burn_slow": burn_slow,
            "burn_threshold": o.burn_threshold,
            "threshold": o.threshold,
            "observed": observed,
            "trace_ids": worst_trace_ids(),
        }
        path = self._incident_file or incident_path()
        line = (json.dumps(record) + "\n").encode("utf-8")
        # One O_APPEND write per record: whole lines interleave atomically
        # even when several engines (or processes) share the log.
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            fd = os.open(path, os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
            try:
                os.write(fd, line)
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            return  # forensics must never take down the serving path
        inst["incidents"].inc()


# ------------------------------------------------- process-global engine set

_ENGINES: Dict[str, SLOEngine] = {}
_ENGINES_LOCK = threading.Lock()
_ENDPOINT_INSTALLED = False


def engines() -> Dict[str, SLOEngine]:
    with _ENGINES_LOCK:
        return dict(_ENGINES)


def reset_engines() -> None:
    """Drop registered engines (tests and daemon teardown)."""
    with _ENGINES_LOCK:
        _ENGINES.clear()


def maybe_engine(objectives: Sequence[SLOConfig], source: str,
                 **kwargs) -> Optional[SLOEngine]:
    """Build, register, and expose an engine — or ``None`` when telemetry
    or rollups are off.  The one call subsystem loops make; the ``None``
    return keeps their flag-off path untouched."""
    if not _runtime.enabled():
        return None
    if _rollup.ensure_rollup() is None and kwargs.get("ring") is None:
        return None
    engine = SLOEngine(objectives, source=source, **kwargs)
    with _ENGINES_LOCK:
        _ENGINES[source] = engine
    install_slo_endpoint()
    return engine


def _update_fleet_gauges(inst: dict) -> None:
    """Recompute the cross-engine ``slo_*``/``alert_*`` gauges from every
    registered engine's last status."""
    total = burning = firing = 0
    burn_max = 0.0
    for engine in engines().values():
        for row in engine.status().get("objectives", ()):
            total += 1
            burn = row.get("burn_fast")
            if burn is not None:
                burn_max = max(burn_max, burn)
                if burn >= row["burn_threshold"]:
                    burning += 1
            if row.get("firing"):
                firing += 1
    inst["objectives"].set(total)
    inst["burning"].set(burning)
    inst["burn_max"].set(burn_max)
    inst["firing"].set(firing)


def slo_view(request: Optional[dict] = None):
    """``/slo`` endpoint body: every registered engine's last status."""
    snapshot = {src: e.status() for src, e in sorted(engines().items())}
    body = {
        "enabled": bool(snapshot),
        "run_id": _correlate.current(),
        "unix": time.time(),
        "incident_log": incident_path(),
        "engines": snapshot,
    }
    return ("application/json", json.dumps(body), 200)


def install_slo_endpoint() -> None:
    global _ENDPOINT_INSTALLED
    if _ENDPOINT_INSTALLED:
        return
    from distkeras_tpu.telemetry import flightdeck

    flightdeck.add_endpoint("/slo", slo_view)
    _ENDPOINT_INSTALLED = True


# --------------------------------------------------------- default objectives


def default_serving_objectives(ttft_threshold: float = 0.25,
                               latency_threshold: float = 0.5,
                               fast_s: float = 30.0, slow_s: float = 120.0,
                               burn_threshold: float = 2.0,
                               ) -> List[SLOConfig]:
    """The serving tier's shipped objectives — what the probe loop
    evaluates and the future autoscaler verb will act on."""
    return [
        SLOConfig(
            name="serving_ttft_p99", kind="quantile",
            metric="serving_ttft_seconds", quantile=0.99,
            threshold=ttft_threshold, target=0.99,
            fast_window_s=fast_s, slow_window_s=slow_s,
            burn_threshold=burn_threshold,
            description=f"p99 time-to-first-token under "
                        f"{ttft_threshold * 1000:g}ms",
        ),
        SLOConfig(
            name="serving_tier_latency_p99", kind="quantile",
            metric="serving_tier_latency_seconds", quantile=0.99,
            threshold=latency_threshold, target=0.99,
            fast_window_s=fast_s, slow_window_s=slow_s,
            burn_threshold=burn_threshold,
            description=f"p99 end-to-end router latency under "
                        f"{latency_threshold * 1000:g}ms "
                        f"(failovers included)",
        ),
        SLOConfig(
            name="serving_tier_replicas_available", kind="gauge",
            metric="serving_tier_replicas_healthy", threshold=1.0, op="lt",
            target=0.9, fast_window_s=fast_s, slow_window_s=slow_s,
            burn_threshold=burn_threshold,
            description="at least one healthy replica behind the router",
        ),
        SLOConfig(
            name="serving_tier_shed_ratio", kind="ratio",
            bad_metric="serving_tier_sheds_total",
            total_metric=("serving_tier_routed_total",
                          "serving_tier_sheds_total"),
            target=0.99, fast_window_s=fast_s, slow_window_s=slow_s,
            burn_threshold=burn_threshold,
            description="requests shed for saturation under 1% of admitted",
        ),
    ]


def default_online_objectives(window_seconds: float,
                              fast_s: float = 30.0, slow_s: float = 120.0,
                              burn_threshold: float = 2.0,
                              ) -> List[SLOConfig]:
    """The online-learning loop's shipped objective: the retrainer keeps up
    — published-but-untrained windows never age past 2× the window span."""
    return [
        SLOConfig(
            name="online_window_lag", kind="gauge",
            metric="online_window_lag_seconds",
            threshold=2.0 * float(window_seconds), op="gt",
            target=0.9, fast_window_s=fast_s, slow_window_s=slow_s,
            burn_threshold=burn_threshold,
            description=f"oldest untrained window younger than "
                        f"{2.0 * float(window_seconds):g}s (2x window span)",
        ),
    ]
