"""Span tracer exporting Chrome trace-event JSON (Perfetto-loadable).

Usage at a host boundary (never inside jitted code):

    with telemetry.trace.span("epoch", epoch=3):
        with telemetry.trace.span("window", phase="step"):
            ...

Spans clock with ``time.perf_counter`` (monotonic — DK106's whole point),
nest per-thread, and are recorded as complete ("ph": "X") events whose
ts/dur containment gives Perfetto the nesting; each event also carries an
explicit ``args.parent`` so tests and scripts need no interval math.

When telemetry is disabled, ``span()`` returns a shared no-op context
manager — the cost is one cached-bool check and one dict-free branch, which
the test suite pins against plain dict-lookup cost.

A span opened with ``phase="step"`` (or data/h2d/commit/...) additionally
feeds the ``phase_<name>_seconds`` histogram in the global metrics registry
on exit — that is where bench.py's phase breakdown comes from.

Exceptions raised while recording are NOT swallowed: the CI tier-1 variant
with ``DISTKERAS_TELEMETRY=1`` exists precisely so instrumentation bugs fail
the build instead of silently disabling observability.

**Request tracing.**  A serving request crosses threads and processes
(router dispatch thread → replica HTTP handler → engine loop), so thread
nesting alone cannot stitch its spans together.  :meth:`Tracer.bind` binds a
``trace_id``/``request_id`` context to the current thread; every span the
thread records while bound carries those ids in its args (explicit span
attrs win).  Threads that do work *for* a request without a bound context —
the engine loop serves many requests per decode step — stamp the ids as
explicit span args instead.  ``tools/dktrace critical-path`` joins on them.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid

from distkeras_tpu.telemetry import runtime
from distkeras_tpu.telemetry.flightdeck import correlate as _correlate
from distkeras_tpu.telemetry.flightdeck.recorder import recorder as _flight_recorder
from distkeras_tpu.telemetry.metrics import metrics as _registry

__all__ = ["NOOP_SPAN", "Span", "Tracer", "new_trace_id", "trace"]


def new_trace_id() -> str:
    """A fresh 32-hex trace id (the distributed-trace correlation key —
    minted once at the first hop that sees the request, reused by every
    later hop)."""
    return uuid.uuid4().hex


class _NoopSpan:
    """Shared do-nothing context manager for the disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


NOOP_SPAN = _NoopSpan()


class _ContextBinding:
    """Context manager installing a trace context on the current thread;
    restores the previous binding on exit (bindings nest — an inner bind
    layers over, and restores, the outer one)."""

    __slots__ = ("_tracer", "_ctx", "_prev")

    def __init__(self, tracer, ctx):
        self._tracer = tracer
        self._ctx = ctx

    def __enter__(self):
        tls = self._tracer._tls
        self._prev = getattr(tls, "ctx", None)
        tls.ctx = self._ctx
        return self._ctx

    def __exit__(self, exc_type, exc, tb):
        self._tracer._tls.ctx = self._prev
        return False


class Span:
    """Context manager recording one complete trace event on exit."""

    __slots__ = ("_tracer", "name", "phase", "attrs", "_t0")

    def __init__(self, tracer, name, phase, attrs):
        self._tracer = tracer
        self.name = name
        self.phase = phase
        self.attrs = attrs

    def __enter__(self):
        self._tracer._push(self.name)
        self._t0 = self._tracer._clock()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = self._tracer._clock()
        parent = self._tracer._pop()
        self._tracer._record(self.name, self._t0, t1, parent, self.attrs)
        if self.phase is not None:
            _registry.histogram(
                f"phase_{self.phase}_seconds",
                help=f"host-visible seconds in the {self.phase} phase",
            ).observe(t1 - self._t0)
        return False


class Tracer:
    """Thread-safe span recorder with Chrome trace-event export.

    ``clock`` and ``pid`` are injectable so golden-file tests are
    deterministic; production code uses the module-global :data:`trace`.

    Only a ``correlated`` tracer stamps the fleet ``run_id`` into event args
    and feeds finished spans to the flight-recorder ring — the module-global
    :data:`trace` is; ad-hoc tracers (golden tests, scripts) default to
    uncorrelated so their output is a pure function of their inputs.
    """

    def __init__(self, clock=time.perf_counter, pid=None, correlated=False):
        self._clock = clock
        self._pid = pid
        self._correlated = correlated
        self._lock = threading.Lock()
        self._events = []
        self._tls = threading.local()
        self._tids = {}
        self._origin = clock()

    # ------------------------------------------------------------- recording

    def span(self, name, phase=None, **attrs):
        if not runtime.enabled():
            return NOOP_SPAN
        return Span(self, name, phase, attrs)

    def _stack(self):
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _push(self, name):
        self._stack().append(name)

    def _pop(self):
        stack = self._stack()
        stack.pop()
        return stack[-1] if stack else None

    def current(self):
        """Name of this thread's innermost open span, or ``None`` — used by
        the sanitizer to attribute violations to the pipeline phase."""
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    # ------------------------------------------------------- trace context

    def bind(self, trace_id=None, request_id=None, **extra):
        """Bind a trace context to the current thread for the duration of a
        ``with`` block.  Every span recorded by this thread while bound
        carries the bound ids in its event args (explicit span attrs win
        over the context).  Falsy values are skipped, so
        ``bind(trace_id=req.trace_id)`` is safe when the id may be empty.

        Works whether or not telemetry is enabled — binding is a couple of
        thread-local writes; it is the spans that no-op when disabled."""
        ctx = dict(getattr(self._tls, "ctx", None) or {})
        if trace_id:
            ctx["trace_id"] = trace_id
        if request_id:
            ctx["request_id"] = request_id
        for key, value in extra.items():
            if value:
                ctx[key] = value
        return _ContextBinding(self, ctx)

    def context(self) -> dict:
        """A copy of the current thread's bound trace context (``{}`` when
        unbound) — e.g. ``trace.context().get("trace_id")``."""
        return dict(getattr(self._tls, "ctx", None) or {})

    def record(self, name, t0, t1, **attrs):
        """Record an already-timed span (``perf_counter`` endpoints) without
        entering a context manager — for threads attributing work that began
        elsewhere, like the engine loop recording a request's queue wait
        from its admission-thread enqueue timestamp."""
        if not runtime.enabled():
            return
        self._record(name, t0, t1, None, attrs)

    def _record(self, name, t0, t1, parent, attrs):
        ident = threading.get_ident()
        args = dict(attrs)
        ctx = getattr(self._tls, "ctx", None)
        if ctx:
            for key, value in ctx.items():
                args.setdefault(key, value)
        if parent is not None:
            args["parent"] = parent
        if self._correlated:
            rid = _correlate.current()
            if rid is not None:
                args["run_id"] = rid
        with self._lock:
            tid = self._tids.setdefault(ident, len(self._tids))
            event = {
                "name": name,
                "cat": "distkeras",
                "ph": "X",
                "pid": self._pid if self._pid is not None else os.getpid(),
                "tid": tid,
                "ts": round((t0 - self._origin) * 1e6, 3),
                "dur": round((t1 - t0) * 1e6, 3),
                "args": args,
            }
            self._events.append(event)
        if self._correlated:
            _flight_recorder.record_span(event)

    def reset(self):
        with self._lock:
            self._events.clear()
            self._tids.clear()
            self._origin = self._clock()

    # --------------------------------------------------------------- export

    def events(self):
        with self._lock:
            return [dict(e) for e in self._events]

    def export(self) -> dict:
        """Chrome trace-event JSON object; open in Perfetto / chrome://tracing."""
        evs = self.events()
        evs.sort(key=lambda e: (e["tid"], e["ts"], -e["dur"]))
        return {"traceEvents": evs, "displayTimeUnit": "ms"}

    def write(self, path) -> str:
        payload = self.export()
        # tmp + replace: dktrace merge / flightdeck may read this file from
        # another process while a dump is still streaming out
        tmp = os.fspath(path) + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(payload, fh, indent=1)
        os.replace(tmp, path)
        return path


# Process-global tracer used by all instrumentation sites; correlated so its
# events carry the fleet run_id and land in the flight-recorder ring.
trace = Tracer(correlated=True)
