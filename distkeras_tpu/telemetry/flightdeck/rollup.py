"""Time-series rollup ring: bounded history for every registered instrument.

The metrics registry answers "what is the value *now*"; SLO evaluation and
the autoscaler need "what happened over the last N seconds".  This module
closes that gap with a fixed-interval, fixed-capacity ring of registry
snapshots — each tick stores, per instrument, the counter value, the gauge
value, or the histogram's cumulative bucket counts.  Windowed questions are
then answered by *differencing* two ticks:

* counter rate over a window = (value_now - value_then) / dt;
* histogram quantile over a window = quantile of the bucket-count deltas
  between the window's edges (exact on bucket boundaries — the estimator
  interpolates linearly *within* a bucket only);
* gauge breach fraction = share of ticks in the window above a threshold.

Storing cumulative buckets per tick (rather than pre-computed quantiles) is
what makes fleet merging exact: the daemon merges per-job ticks with the
same carry-forward union used by ``metrics._merge_histograms``, and
quantiles are computed *after* the merge, never averaged across jobs.

Everything is opt-in behind ``DISTKERAS_ROLLUP`` (seconds per tick; unset =
off).  With the flag off no thread starts, no memory is held, and
instrumented code paths are byte-identical — pinned by test.  Tests drive
rings directly with an injectable clock and manual :meth:`RollupRing.tick`.
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Dict, List, Optional

from distkeras_tpu.telemetry import runtime as _runtime

__all__ = [
    "RollupRing",
    "configure",
    "ensure_rollup",
    "interval",
    "merge_series",
    "quantile_from_cumulative",
    "rollup_ring",
    "stop",
    "timeseries_view",
]

DEFAULT_CAPACITY = 512

_UNSET = object()

# _UNSET = not yet resolved from the environment; None = off; float = tick
# interval in seconds once resolved or forced via configure().
_INTERVAL = _UNSET

_RING: Optional["RollupRing"] = None
_THREAD: Optional[threading.Thread] = None
_STOP = threading.Event()
_LOCK = threading.Lock()


def interval() -> Optional[float]:
    """Resolved tick interval in seconds, or ``None`` when rollups are off.
    Cached after the first environment read."""
    global _INTERVAL
    if _INTERVAL is _UNSET:
        raw = os.environ.get("DISTKERAS_ROLLUP", "").strip()
        if raw == "" or raw.lower() in ("off", "false", "no", "0"):
            _INTERVAL = None
        else:
            _INTERVAL = float(raw)
    return _INTERVAL


def configure(seconds=_UNSET) -> None:
    """Force the tick interval (float seconds), turn rollups off
    (``False``), or reset to env-driven (``None``, re-read lazily)."""
    global _INTERVAL
    if seconds is None:
        _INTERVAL = _UNSET
    elif seconds is False:
        _INTERVAL = None
    else:
        _INTERVAL = float(seconds)


class RollupRing:
    """Fixed-capacity ring of per-instrument samples at a fixed cadence.

    One entry per tick: ``(unix, {name: sample})`` where a sample is
    ``{"type": "counter"|"gauge", "value": v}`` or ``{"type": "histogram",
    "sum": s, "count": n, "buckets": {le: cumulative}}`` — the same shapes
    :meth:`Registry.snapshot` emits, so merging reuses the registry's
    histogram algebra.  All mutation is behind one lock; readers copy out.
    """

    def __init__(self, registry=None, interval: float = 10.0,
                 capacity: int = DEFAULT_CAPACITY, clock=time.time):
        if registry is None:
            from distkeras_tpu.telemetry.metrics import metrics as registry
        self.registry = registry
        self.interval = float(interval)
        self.capacity = int(capacity)
        self.clock = clock
        self._lock = threading.Lock()
        self._buf: List[Optional[tuple]] = [None] * self.capacity
        self._idx = 0

    # ------------------------------------------------------------- recording

    def tick(self, now: Optional[float] = None) -> None:
        """Snapshot the registry into the ring (one entry, oldest evicted)."""
        entry = (self.clock() if now is None else float(now),
                 self.registry.snapshot())
        with self._lock:
            self._buf[self._idx % self.capacity] = entry
            self._idx += 1

    def ingest(self, unix: float, snapshot: dict) -> None:
        """Append an externally produced sample (the daemon's fleet-merged
        ticks land here so ``dkmon watch`` sees one ring, not N)."""
        with self._lock:
            self._buf[self._idx % self.capacity] = (float(unix), snapshot)
            self._idx += 1

    # ------------------------------------------------------------ inspection

    def samples(self, since: Optional[float] = None) -> List[tuple]:
        """``[(unix, snapshot), ...]`` oldest first, optionally bounded."""
        with self._lock:
            if self._idx <= self.capacity:
                raw = self._buf[: self._idx]
            else:
                head = self._idx % self.capacity
                raw = self._buf[head:] + self._buf[:head]
        if since is None:
            return list(raw)
        return [s for s in raw if s[0] >= since]

    def __len__(self) -> int:
        with self._lock:
            return min(self._idx, self.capacity)

    def _window_edges(self, name: str, window_s: float,
                      now: Optional[float] = None):
        """(oldest, newest) samples of ``name`` inside the window, or None.

        ``oldest`` is the last sample at-or-before the window start when one
        exists (so a 60s window spans the full 60s, not just the ticks that
        happen to land inside it)."""
        now = self.clock() if now is None else float(now)
        start = now - float(window_s)
        before, inside = None, []
        for unix, snap in self.samples():
            payload = snap.get(name)
            if payload is None or unix > now:
                continue
            if unix <= start:
                before = (unix, payload)
            else:
                inside.append((unix, payload))
        if not inside:
            return None
        oldest = before if before is not None else inside[0]
        newest = inside[-1]
        if newest[0] <= oldest[0]:
            return None
        return oldest, newest

    def window_rate(self, name: str, window_s: float,
                    now: Optional[float] = None) -> Optional[float]:
        """Counter increase per second over the window (``None`` without at
        least two usable ticks).  Clamped at zero across registry resets."""
        edges = self._window_edges(name, window_s, now)
        if edges is None:
            return None
        (t0, p0), (t1, p1) = edges
        if p0.get("type") != "counter" or p1.get("type") != "counter":
            return None
        return max(0.0, p1["value"] - p0["value"]) / (t1 - t0)

    def window_delta(self, name: str, window_s: float,
                     now: Optional[float] = None) -> Optional[dict]:
        """Histogram activity inside the window: bucket-count deltas between
        the window's edge ticks, as a cumulative snapshot-shaped dict."""
        edges = self._window_edges(name, window_s, now)
        if edges is None:
            return None
        (_, p0), (_, p1) = edges
        if p0.get("type") != "histogram" or p1.get("type") != "histogram":
            return None
        buckets = {}
        for le, n in p1["buckets"].items():
            buckets[le] = max(0, n - p0["buckets"].get(le, 0))
        return {
            "type": "histogram",
            "sum": max(0.0, p1["sum"] - p0["sum"]),
            "count": max(0, p1["count"] - p0["count"]),
            "buckets": buckets,
        }

    def window_quantile(self, name: str, q: float, window_s: float,
                        now: Optional[float] = None) -> Optional[float]:
        """q-quantile of observations that landed inside the window."""
        delta = self.window_delta(name, window_s, now)
        if delta is None or delta["count"] == 0:
            return None
        return quantile_from_cumulative(delta["buckets"], q)

    def window_breach_fraction(self, name: str, threshold: float,
                               window_s: float, now: Optional[float] = None,
                               op: str = "gt") -> Optional[float]:
        """Share of in-window gauge ticks breaching ``threshold`` —
        strictly above for ``op="gt"`` (a lag gauge), strictly below for
        ``op="lt"`` (a healthy-replica count)."""
        if op not in ("gt", "lt"):
            raise ValueError(f"op must be 'gt' or 'lt', got {op!r}")
        now = self.clock() if now is None else float(now)
        start = now - float(window_s)
        seen = bad = 0
        for unix, snap in self.samples(since=start):
            payload = snap.get(name)
            if payload is None or payload.get("type") != "gauge" \
                    or unix > now:
                continue
            seen += 1
            value = payload["value"]
            if (value > threshold) if op == "gt" else (value < threshold):
                bad += 1
        if seen == 0:
            return None
        return bad / seen

    def export(self, since: Optional[float] = None,
               names: Optional[List[str]] = None) -> dict:
        """JSON view for the ``/timeseries`` endpoint and the fleet merge."""
        out = []
        for unix, snap in self.samples(since=since):
            if names:
                snap = {k: v for k, v in snap.items() if k in names}
            out.append({"unix": unix, "metrics": snap})
        return {"interval": self.interval, "capacity": self.capacity,
                "samples": out}


def quantile_from_cumulative(buckets: Dict[str, float], q: float) -> float:
    """q-quantile from cumulative ``{le: count}`` buckets.

    Exact on bucket boundaries: when the target rank lands exactly on a
    bucket's cumulative count, that bucket's upper bound is returned.
    Inside a bucket the estimator interpolates linearly from the previous
    bound (0 for the first finite bucket).  Ranks that land in the +Inf
    overflow clamp to the largest finite bound — bounded ladders cannot
    resolve beyond their top rung, and a finite answer keeps thresholds
    comparable.  Monotone in ``q`` and under carry-forward merges of
    different ladders (both only ever move cumulative counts up)."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    from distkeras_tpu.telemetry.metrics import _le_key

    ladder = sorted(((_le_key(le), n) for le, n in buckets.items()))
    total = ladder[-1][1] if ladder else 0
    if total <= 0:
        return 0.0
    rank = q * total
    prev_bound, prev_cum = 0.0, 0
    top_finite = max((b for b, _ in ladder if not math.isinf(b)), default=0.0)
    for bound, cum in ladder:
        if cum > prev_cum and rank <= cum:
            if math.isinf(bound):
                return top_finite
            frac = max(0.0, (rank - prev_cum) / (cum - prev_cum))
            return prev_bound + frac * (bound - prev_bound)
        prev_bound, prev_cum = (0.0 if math.isinf(bound) else bound), cum
    return top_finite


def merge_series(series: List[dict], align_s: float = 1.0) -> dict:
    """Merge per-job ``export()`` payloads into one fleet time-series.

    Ticks from different jobs are binned onto a shared time axis (bins of
    ``align_s``... the rollup interval is the natural choice) and each bin's
    snapshots merge with :func:`metrics.merge_snapshots` — counters sum,
    gauges keep max+mean, histogram buckets union exactly.  Bins where a job
    is silent simply contribute nothing (no interpolation: absence of a tick
    is itself a signal ``dkmon`` surfaces)."""
    from distkeras_tpu.telemetry.metrics import merge_snapshots

    bins: Dict[float, List[dict]] = {}
    interval_out = align_s
    for payload in series:
        interval_out = max(interval_out, float(payload.get("interval") or 0))
        for sample in payload.get("samples", ()):
            key = math.floor(sample["unix"] / align_s) * align_s
            bins.setdefault(key, []).append(sample["metrics"])
    samples = [
        {"unix": key, "metrics": merge_snapshots(snaps)}
        for key, snaps in sorted(bins.items())
    ]
    return {"interval": interval_out, "capacity": len(samples),
            "samples": samples}


# ------------------------------------------------------------ process global


def rollup_ring() -> Optional[RollupRing]:
    """The process-global ring, or ``None`` when rollups are off."""
    return _RING


def ensure_rollup() -> Optional[RollupRing]:
    """Start the rollup thread once (idempotent) and return the ring.

    ``None`` when telemetry or ``DISTKERAS_ROLLUP`` is off — entry points
    call this unconditionally, like :func:`server.ensure_server`.
    """
    if not _runtime.enabled():
        return None
    dt = interval()
    if dt is None:
        return None
    global _RING, _THREAD
    with _LOCK:
        if _RING is None:
            _RING = RollupRing(interval=dt)
            _STOP.clear()
            _THREAD = threading.Thread(
                target=_run, args=(_RING,), name="flightdeck-rollup",
                daemon=True,
            )
            _THREAD.start()
    return _RING


def _run(ring: RollupRing) -> None:
    while not _STOP.wait(ring.interval):
        try:
            ring.tick()
        except Exception:  # noqa: BLE001 — a rollup must never kill training
            pass


def stop() -> None:
    """Stop the rollup thread and drop the ring (tests, daemon teardown)."""
    global _RING, _THREAD
    with _LOCK:
        ring, _RING = _RING, None
        thread, _THREAD = _THREAD, None
        _STOP.set()
    if thread is not None:
        thread.join(timeout=5)


def timeseries_view(request: Optional[dict] = None):
    """``/timeseries`` endpoint body: the live ring (404-shaped JSON when
    rollups are off so scrapers can tell "off" from "empty")."""
    import json
    from urllib.parse import parse_qs

    ring = _RING
    if ring is None:
        return ("application/json",
                json.dumps({"enabled": False, "samples": []}), 200)
    query = parse_qs((request or {}).get("query") or "")
    since = query.get("since")
    names = query.get("name")
    payload = ring.export(
        since=float(since[-1]) if since else None,
        names=names or None,
    )
    payload["enabled"] = True
    return ("application/json", json.dumps(payload), 200)
