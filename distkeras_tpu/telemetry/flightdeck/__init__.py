"""``distkeras_tpu.telemetry.flightdeck`` — live scrape, crash forensics,
fleet correlation.

Three cooperating pieces on top of the flush-at-exit telemetry stack:

* :mod:`.server` — an HTTP exporter (``/metrics`` ``/healthz`` ``/vars``
  ``/trace``) on a daemon thread, gated by ``DISTKERAS_TELEMETRY_HTTP``;
* :mod:`.recorder` — a bounded flight-recorder ring of recent spans, metric
  deltas, watchdog observations, and sanitizer events, dumped as
  ``blackbox_<run_id>_<pid>.json`` at crash boundaries;
* :mod:`.correlate` — the fleet ``run_id`` stamped into every trace event
  and scrape so ``tools.dktrace merge`` can join per-process timelines.

This module imports only the correlate/recorder pieces eagerly (stdlib,
cycle-free); the HTTP server loads lazily on first use so the common
no-exporter path never pays for ``http.server``.
"""

from __future__ import annotations

from distkeras_tpu.telemetry.flightdeck import correlate
from distkeras_tpu.telemetry.flightdeck.correlate import run_id, set_run_id
from distkeras_tpu.telemetry.flightdeck.correlate import current as current_run_id
from distkeras_tpu.telemetry.flightdeck.recorder import (
    FlightRecorder,
    blackbox_dump,
    on_crash,
    recorder,
)

__all__ = [
    "FlightRecorder",
    "activate",
    "add_endpoint",
    "address",
    "blackbox_dump",
    "current_run_id",
    "ensure_rollup",
    "ensure_server",
    "http_port",
    "on_crash",
    "recorder",
    "rollup_ring",
    "run_id",
    "set_run_id",
    "set_var",
    "stop_server",
]


def activate():
    """The one call entry points make: mint/propagate the fleet ``run_id``,
    start the HTTP exporter when one is configured, and start the rollup
    ring when ``DISTKERAS_ROLLUP`` asks for one.  Returns the run id.
    """
    rid = run_id()
    ensure_server()
    ensure_rollup()
    return rid


# Thin lazy delegates — see module docstring.

def ensure_server():
    from distkeras_tpu.telemetry.flightdeck import server

    return server.ensure_server()


def address():
    from distkeras_tpu.telemetry.flightdeck import server

    return server.address()


def stop_server():
    from distkeras_tpu.telemetry.flightdeck import server

    return server.stop()


def http_port():
    from distkeras_tpu.telemetry.flightdeck import server

    return server.http_port()


def add_endpoint(path, fn):
    from distkeras_tpu.telemetry.flightdeck import server

    return server.add_endpoint(path, fn)


def set_var(name, value):
    from distkeras_tpu.telemetry.flightdeck import server

    return server.set_var(name, value)


def ensure_rollup():
    from distkeras_tpu.telemetry.flightdeck import rollup

    return rollup.ensure_rollup()


def rollup_ring():
    from distkeras_tpu.telemetry.flightdeck import rollup

    return rollup.rollup_ring()
