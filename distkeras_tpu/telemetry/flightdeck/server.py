"""Live HTTP exporter: scrape a running trainer/daemon instead of waiting.

A stdlib :class:`http.server.ThreadingHTTPServer` on a daemon thread, gated
by ``DISTKERAS_TELEMETRY_HTTP``:

* unset / empty — off (the default; nothing binds, nothing serves);
* ``<port>`` — serve on ``127.0.0.1:<port>``;
* ``0`` — serve on an ephemeral port, discoverable in-process via
  :func:`address` and across processes via the ``flightdeck_<pid>.json``
  discovery file the server drops into the telemetry directory (how the
  ``PunchcardServer`` finds its jobs' live ports).

Endpoints:

``/metrics``
    Prometheus text from the process-global registry, every sample labelled
    with the fleet ``run_id``.
``/healthz``
    Liveness: uptime, last event / last span-completion timestamps,
    watchdog state, sanitizer mode and violation tallies.
``/vars``
    JSON: full metrics snapshot, phase breakdown, last dynamics summary.
``/trace``
    The flight-recorder ring as Chrome trace JSON (open in Perfetto).
    ``?request_id=`` / ``?trace_id=`` filter the span events to one
    request's trace — the live half of ``dktrace critical-path``.
``/timeseries``
    The rollup ring (``DISTKERAS_ROLLUP``): fixed-interval history of every
    instrument, the raw feed for SLO burn rates and ``dkmon watch``.
    ``?since=<unix>`` / ``?name=<metric>`` (repeatable) filter the samples.
``/ledger``
    The per-tenant accounting ledger (``DISTKERAS_ACCOUNTING``): the
    bounded top-K usage table as JSON — what ``dkmon top`` renders and the
    daemon's ``ledger_status`` verb fleet-merges.

Handlers only *read* registry snapshots and the recorder ring (each guarded
by its own cheap lock), so scraping never blocks the training loop.  The
daemon adds its fleet ``/aggregate`` view through :func:`add_endpoint`.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional
from urllib.parse import parse_qs

from distkeras_tpu.telemetry import runtime as _runtime
from distkeras_tpu.telemetry.flightdeck import correlate
from distkeras_tpu.telemetry.flightdeck.recorder import recorder as _flight_recorder

__all__ = [
    "add_endpoint",
    "address",
    "configure",
    "ensure_server",
    "get_vars",
    "http_port",
    "set_var",
    "stop",
]

_UNSET = object()

# _UNSET = not yet resolved from the environment; None = off; int = port
# (0 = ephemeral) once resolved or forced via configure().
_PORT = _UNSET

_SERVER: Optional[ThreadingHTTPServer] = None
_THREAD: Optional[threading.Thread] = None
_LOCK = threading.Lock()

# Extra endpoint registry: path -> handler.  Zero-arg handlers return
# (content_type, body); handlers that accept an argument get a request dict
# {"method", "query", "body", "headers"} and may return a (ctype, body,
# status) triple (how the serving /generate endpoint speaks 400/503).
_EXTRA: Dict[str, Callable] = {}

# Free-form string/scalar vars surfaced under /vars "vars": the place for
# one-off facts that are not metric-shaped (e.g. bench's
# bench_backend_init_reason — *why* the device backend fell back).
_VARS: Dict[str, object] = {}
_VARS_LOCK = threading.Lock()


def set_var(name: str, value) -> None:
    """Publish a JSON-safe scalar under ``/vars``' ``"vars"`` key."""
    with _VARS_LOCK:
        _VARS[str(name)] = value


def get_vars() -> Dict[str, object]:
    with _VARS_LOCK:
        return dict(_VARS)


def http_port() -> Optional[int]:
    """Resolved exporter port (``0`` = ephemeral) or ``None`` when off.
    Cached after the first environment read."""
    global _PORT
    if _PORT is _UNSET:
        raw = os.environ.get("DISTKERAS_TELEMETRY_HTTP", "").strip()
        if raw == "" or raw.lower() in ("off", "false", "no"):
            _PORT = None
        else:
            _PORT = int(raw)
    return _PORT


def configure(port=_UNSET) -> None:
    """Force the exporter port (int, ``0`` = ephemeral), turn it off
    (``False``), or reset to env-driven (``None``, re-read lazily)."""
    global _PORT
    if port is None:
        _PORT = _UNSET
    elif port is False:
        _PORT = None
    else:
        _PORT = int(port)


def ensure_server() -> Optional[str]:
    """Start the exporter once (idempotent) and return its address.

    ``None`` when telemetry is disabled or no port is configured — callers
    sprinkle this at entry points without checking anything first.
    """
    if not _runtime.enabled():
        return None
    port = http_port()
    if port is None:
        return None
    global _SERVER, _THREAD
    with _LOCK:
        if _SERVER is None:
            srv = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
            srv.daemon_threads = True
            thread = threading.Thread(
                target=srv.serve_forever, name="flightdeck-http", daemon=True
            )
            thread.start()
            _SERVER, _THREAD = srv, thread
            _write_discovery_file()
    return address()


def address() -> Optional[str]:
    """``"127.0.0.1:<port>"`` of the live exporter, or ``None``."""
    srv = _SERVER
    if srv is None:
        return None
    host, port = srv.server_address[:2]
    return f"{host}:{port}"


def stop() -> None:
    """Shut the exporter down (tests and daemon teardown)."""
    global _SERVER, _THREAD
    with _LOCK:
        srv, _SERVER = _SERVER, None
        thread, _THREAD = _THREAD, None
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    if thread is not None:
        thread.join(timeout=5)


def add_endpoint(path: str, fn: Callable) -> None:
    """Register an extra endpoint.

    Two handler shapes, told apart by signature:

    * ``fn() -> (content_type, body)`` — read-only GET view (the daemon's
      fleet ``/aggregate``);
    * ``fn(request) -> (content_type, body[, status[, headers]])`` —
      request-aware: ``request`` is ``{"method": "GET"|"POST", "query":
      <raw query string>, "body": <decoded POST body or "">, "headers":
      <lower-cased request-header dict>}``, the optional third element
      sets the HTTP status (the serving ``/generate`` endpoint's
      400/503/504), and the optional fourth is a dict of extra response
      headers (e.g. ``Retry-After`` on a 503).  Request-aware endpoints
      also receive POSTs.
    """
    _EXTRA[path] = fn


def _wants_request(fn: Callable) -> bool:
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    return len(sig.parameters) >= 1


def _write_discovery_file() -> None:
    # Advisory: lets other processes (the daemon's status verb) find this
    # process's ephemeral port.  The exporter itself is already serving, so
    # an unwritable telemetry dir must not take it down.  tmp + replace:
    # the daemon polls this file from another process, and a bare in-place
    # dump would let it read half-written JSON.
    try:
        d = _runtime.out_dir()
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"flightdeck_{os.getpid()}.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(
                {
                    "address": address(),
                    "pid": os.getpid(),
                    "run_id": correlate.run_id(),
                },
                fh,
            )
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except OSError:
        pass


# ------------------------------------------------------------------ handler


def _event_matches(event: dict, request_id: str, trace_id: str) -> bool:
    """Does a trace event belong to the given request/trace?  Matches the
    direct ``args.request_id``/``args.trace_id`` stamps and the batched
    decode-step spellings (``args.requests`` list, ``args.trace_ids``)."""
    args = event.get("args") or {}
    if request_id:
        if args.get("request_id") == request_id:
            return True
        if request_id in (args.get("requests") or ()):
            return True
    if trace_id:
        if args.get("trace_id") == trace_id:
            return True
        if trace_id in (args.get("trace_ids") or ()):
            return True
    return False


def _render(path: str, request: Optional[dict] = None):
    """``(content_type, body, status[, headers])`` for one endpoint,
    ``None`` for 404."""
    # Lazy: metrics/trace/dynamics import this package for their ring feeds.
    from distkeras_tpu import sanitizer as _sanitizer
    from distkeras_tpu.telemetry import dynamics as _dynamics
    from distkeras_tpu.telemetry.metrics import metrics as _registry
    from distkeras_tpu.telemetry.trace import trace as _tracer

    rec = _flight_recorder
    rid = correlate.run_id()
    if path == "/metrics":
        text = _registry.to_prometheus(labels={"run_id": rid})
        return ("text/plain; version=0.0.4; charset=utf-8", text, 200)
    if path == "/healthz":
        counts: Dict[str, int] = {}
        for kind, _msg in _sanitizer.violations():
            counts[kind] = counts.get(kind, 0) + 1
        body = {
            "status": "ok",
            "run_id": rid,
            "pid": os.getpid(),
            "unix": time.time(),
            "uptime_seconds": round(rec.uptime_seconds(), 3),
            "last_event_unix": rec.last_event_unix(),
            "last_spans": rec.last_spans(),
            "watchdog": rec.watchdog_state(),
            "sanitizer": {"mode": _sanitizer.mode(), "violations": counts},
        }
        return ("application/json", json.dumps(body), 200)
    if path == "/vars":
        body = {
            "run_id": rid,
            "pid": os.getpid(),
            "metrics": _registry.snapshot(),
            "phase_breakdown": _registry.phase_breakdown(),
            "dynamics": _dynamics.last_summary(),
            "vars": get_vars(),
        }
        return ("application/json", json.dumps(body), 200)
    if path == "/timeseries":
        from distkeras_tpu.telemetry.flightdeck import rollup as _rollup

        return _rollup.timeseries_view(request)
    if path == "/ledger":
        from distkeras_tpu.telemetry import accounting as _accounting

        return _accounting.ledger_view(request)
    if path == "/trace":
        payload = rec.trace_export(origin=_tracer._origin)
        query = parse_qs((request or {}).get("query") or "")
        want_rid = (query.get("request_id") or [""])[-1]
        want_tid = (query.get("trace_id") or [""])[-1]
        if want_rid or want_tid:
            payload = dict(payload)
            payload["traceEvents"] = [
                e for e in payload.get("traceEvents", [])
                if _event_matches(e, want_rid, want_tid)
            ]
        return ("application/json", json.dumps(payload), 200)
    fn = _EXTRA.get(path)
    if fn is not None:
        out = fn(request or {"method": "GET", "query": "", "body": ""}) \
            if _wants_request(fn) else fn()
        if len(out) == 2:
            return (out[0], out[1], 200)
        return out
    return None


class _Handler(BaseHTTPRequestHandler):
    server_version = "distkeras-flightdeck"

    def log_message(self, fmt, *args):  # noqa: D102 — silence stderr access log
        pass

    def _dispatch(self, method: str) -> None:
        path, _, query = self.path.partition("?")
        body = ""
        if method == "POST":
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length).decode("utf-8", "replace")
            if path not in _EXTRA:
                self._reply(405, "text/plain",
                            "POST only supported on registered endpoints")
                return
        request = {
            "method": method,
            "query": query,
            "body": body,
            "headers": {k.lower(): v for k, v in self.headers.items()},
        }
        try:
            payload = _render(path, request)
        except Exception as e:  # noqa: BLE001 — a scrape must never kill training
            self._reply(500, "text/plain", f"{type(e).__name__}: {e}")
            return
        if payload is None:
            known = ["/metrics", "/healthz", "/vars", "/trace",
                     "/timeseries", "/ledger", *sorted(_EXTRA)]
            self._reply(404, "text/plain", "not found; endpoints: " + " ".join(known))
            return
        ctype, text, status = payload[:3]
        headers = payload[3] if len(payload) > 3 else None
        self._reply(status, ctype, text, headers)

    def do_GET(self):  # noqa: N802 — http.server API
        self._dispatch("GET")

    def do_POST(self):  # noqa: N802 — http.server API
        self._dispatch("POST")

    def _reply(self, code: int, ctype: str, body: str,
               headers: Optional[Dict[str, str]] = None) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        for key, value in (headers or {}).items():
            self.send_header(key, str(value))
        self.end_headers()
        self.wfile.write(data)
