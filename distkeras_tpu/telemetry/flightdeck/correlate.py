"""Fleet-wide run correlation: one ``run_id`` across trainer, daemon, jobs.

A fleet run involves several processes — a trainer, a ``PunchcardServer``
daemon, N spawned jobs — each writing its own trace and metrics files.
Without a shared key those artifacts cannot be joined back into one
timeline.  The ``run_id`` is that key: a short opaque token minted once per
fleet (by whichever entry point runs first — ``Trainer.fit``,
``PunchcardServer.start``, or an explicit :func:`run_id` call) and handed to
child processes through the ``DISTKERAS_RUN_ID`` environment variable.  The
correlated tracer stamps it into every span's ``args`` and the live
``/metrics`` scrape carries it as a Prometheus label, so
``tools.dktrace merge`` can verify that the traces it is stitching together
actually belong to the same run.

Resolution order: an explicit :func:`set_run_id`, then ``DISTKERAS_RUN_ID``
(the inherited fleet id), then — only when :func:`run_id` is called — a
freshly minted token.  :func:`current` never mints, so processes that never
start a run (imports, unit tests) stay unstamped.
"""

from __future__ import annotations

import os
import threading
import uuid
from typing import Optional

__all__ = ["current", "run_id", "set_run_id"]

_LOCK = threading.Lock()

# None = not yet resolved; once _RESOLVED is True, _RUN_ID holds the answer
# (possibly still None when the env carries no id and nothing minted one).
_RUN_ID: Optional[str] = None
_RESOLVED = False


def current() -> Optional[str]:
    """The run id this process is correlated under, or ``None``.

    Never mints: the hot stamping path (one call per recorded span) must not
    invent ids for processes that never started a run.  Cached after the
    first environment read.
    """
    global _RUN_ID, _RESOLVED
    if not _RESOLVED:
        with _LOCK:
            if not _RESOLVED:
                _RUN_ID = os.environ.get("DISTKERAS_RUN_ID") or None
                _RESOLVED = True
    return _RUN_ID


def run_id() -> str:
    """The run id, minting a fresh one if neither env nor a prior call set it.

    Entry points (``Trainer.fit``, ``PunchcardServer.start``, blackbox dumps)
    call this; everything downstream reads :func:`current`.
    """
    global _RUN_ID, _RESOLVED
    rid = current()
    if rid is None:
        with _LOCK:
            if _RUN_ID is None:
                _RUN_ID = uuid.uuid4().hex[:12]
                _RESOLVED = True
            rid = _RUN_ID
    return rid


def set_run_id(rid: Optional[str]) -> None:
    """Force the run id (tests, explicit fleet wiring) or reset to env-driven
    (``None``, re-read lazily on the next :func:`current` call)."""
    global _RUN_ID, _RESOLVED
    with _LOCK:
        _RUN_ID = rid
        _RESOLVED = rid is not None
