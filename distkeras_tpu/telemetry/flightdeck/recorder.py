"""Flight recorder: a bounded ring of recent telemetry, dumped on crash.

The flush-at-exit telemetry files answer "what happened over the whole run";
the flight recorder answers "what happened in the last few seconds before it
died".  It is a fixed-size ring — a preallocated list plus a monotonically
increasing index, both touched under one cheap lock — fed by the correlated
tracer (every finished span), the metrics registry (every counter/gauge
delta while telemetry is on), the :class:`DivergenceWatchdog` (every
observation), and the sanitizer (every violation).  Recording is a tuple
store; the per-event overhead is pinned by test next to the span fast path.

On an unhandled trainer exception, a watchdog halt, a strict sanitizer
violation, or a daemon job crash, :func:`blackbox_dump` serialises the ring
together with the run configuration (``DISTKERAS_*``/``JAX_*`` environment),
process facts, the last dynamics summary, and a full metrics snapshot into
``blackbox_<run_id>_<pid>.json`` in the telemetry directory — the black box
an operator opens first.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from distkeras_tpu.telemetry import runtime as _runtime
from distkeras_tpu.telemetry.flightdeck import correlate

__all__ = ["FlightRecorder", "blackbox_dump", "on_crash", "recorder"]

DEFAULT_CAPACITY = 2048

# /healthz liveness map: bounded number of distinct span names tracked.
_MAX_LAST_SPANS = 64


class FlightRecorder:
    """Bounded in-memory ring of recent telemetry events.

    Entries are ``(kind, name, unix, perf, data, event)`` tuples — ``kind``
    one of ``span``/``metric``/``watchdog``/``sanitizer``, ``unix`` the wall
    timestamp (for humans), ``perf`` the ``perf_counter`` reading (for trace
    export), ``data`` a small JSON-safe payload, ``event`` the full Chrome
    trace event dict for spans.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._buf: List[Any] = [None] * self.capacity
        self._idx = 0
        self._last_spans: Dict[str, float] = {}
        self._watchdog: Optional[Dict[str, Any]] = None
        self._started_perf = time.perf_counter()

    # ------------------------------------------------------------- recording

    def record(self, kind: str, name: str, data=None, event=None) -> None:
        """Append one entry: a tuple build and a list store under the lock."""
        entry = (kind, name, time.time(), time.perf_counter(), data, event)
        with self._lock:
            self._buf[self._idx % self.capacity] = entry
            self._idx += 1
            if kind == "span" and (
                name in self._last_spans or len(self._last_spans) < _MAX_LAST_SPANS
            ):
                self._last_spans[name] = entry[2]

    def record_span(self, event: Dict[str, Any]) -> None:
        """Fed by the correlated tracer with the already-built trace event."""
        self.record("span", event["name"], event=event)

    def record_metric(self, name: str, value: float) -> None:
        self.record("metric", name, data={"value": value})

    def record_watchdog(self, payload: Dict[str, Any]) -> None:
        with self._lock:
            self._watchdog = payload
        self.record("watchdog", payload.get("action", "observe"), data=payload)

    def record_sanitizer(self, kind: str, message: str, strict: bool) -> None:
        self.record(
            "sanitizer", kind, data={"message": message, "strict": strict}
        )

    # ----------------------------------------------------------- inspection

    def events(self) -> List[Dict[str, Any]]:
        """Ring contents, oldest first, as JSON-safe dicts."""
        with self._lock:
            if self._idx <= self.capacity:
                raw = self._buf[: self._idx]
            else:
                head = self._idx % self.capacity
                raw = self._buf[head:] + self._buf[:head]
        out = []
        for kind, name, unix, perf, data, event in raw:
            d = {"kind": kind, "name": name, "unix": unix, "perf": perf}
            if data is not None:
                d["data"] = data
            if event is not None:
                d["event"] = event
            out.append(d)
        return out

    def last_spans(self) -> Dict[str, float]:
        """Span name -> wall timestamp of its most recent completion (the
        /healthz liveness signal: a live fit keeps bumping ``epoch``)."""
        with self._lock:
            return dict(self._last_spans)

    def last_event_unix(self) -> Optional[float]:
        with self._lock:
            if self._idx == 0:
                return None
            return self._buf[(self._idx - 1) % self.capacity][2]

    def watchdog_state(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._watchdog

    def uptime_seconds(self) -> float:
        return time.perf_counter() - self._started_perf

    def trace_export(self, origin: Optional[float] = None) -> Dict[str, Any]:
        """The ring as a Chrome trace object (the /trace endpoint).

        Span entries carry their original trace events; everything else
        becomes an instant event on tid 0, placed on the same microsecond
        axis via ``origin`` (the live tracer's perf origin).
        """
        evs = self.events()
        if origin is None:
            origin = min((e["perf"] for e in evs), default=0.0)
        out = []
        pid = os.getpid()
        for e in evs:
            if e["kind"] == "span":
                out.append(e["event"])
                continue
            out.append({
                "name": f'{e["kind"]}:{e["name"]}',
                "cat": "distkeras",
                "ph": "i",
                "s": "p",
                "pid": pid,
                "tid": 0,
                "ts": round((e["perf"] - origin) * 1e6, 3),
                "args": e.get("data") or {},
            })
        return {"traceEvents": out, "displayTimeUnit": "ms"}

    def reset(self) -> None:
        with self._lock:
            self._buf = [None] * self.capacity
            self._idx = 0
            self._last_spans.clear()
            self._watchdog = None
            self._started_perf = time.perf_counter()


#: Process-global recorder every instrumentation site feeds.
recorder = FlightRecorder()


def blackbox_dump(reason: str, directory=None, extra=None) -> Optional[str]:
    """Write ``blackbox_<run_id>_<pid>.json`` and return its path.

    ``None`` when telemetry is disabled.  The payload is self-contained:
    ring, run/environment configuration, last dynamics summary, watchdog
    state, and a full metrics snapshot — everything needed to diagnose a
    dead process without its (possibly never-flushed) telemetry files.
    """
    if not _runtime.enabled():
        return None
    # Lazy: keeps this module import-light and cycle-free (metrics imports
    # the recorder for its ring feed).
    from distkeras_tpu.telemetry import dynamics as _dynamics
    from distkeras_tpu.telemetry.metrics import metrics as _registry

    rid = correlate.run_id()
    pid = os.getpid()
    payload = {
        "reason": reason,
        "run_id": rid,
        "pid": pid,
        "unix": time.time(),
        "config": {
            k: v
            for k, v in sorted(os.environ.items())
            if k.startswith(("DISTKERAS_", "JAX_", "XLA_"))
        },
        "process": {
            "argv": list(sys.argv),
            "cwd": os.getcwd(),
            "python": sys.version.split()[0],
        },
        "dynamics": _dynamics.last_summary(),
        "watchdog": recorder.watchdog_state(),
        "metrics": _registry.snapshot(),
        "ring": recorder.events(),
    }
    if extra:
        payload["extra"] = extra
    d = directory or _runtime.out_dir()
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"blackbox_{rid}_{pid}.json")
    # tmp + replace: post-mortem tooling globs blackbox_*.json from another
    # process; the crashing dump must appear complete or not at all
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, default=repr)
    os.replace(tmp, path)
    _registry.counter(
        "telemetry_blackbox_dumps_total",
        help="flight-recorder blackbox files written on crash boundaries",
    ).inc()
    return path


def on_crash(reason: str, directory=None, extra=None) -> Optional[str]:
    """Best-effort :func:`blackbox_dump` at a crash boundary.

    Swallows everything: forensics must never mask the original exception
    that is about to propagate.
    """
    try:
        return blackbox_dump(reason, directory=directory, extra=extra)
    except Exception:  # noqa: BLE001 — crash path; the real error re-raises
        return None
