"""``distkeras_tpu.telemetry`` — spans, metrics, and profiler hooks.

One subsystem, three surfaces:

* :mod:`.trace` — ``trace.span("epoch")`` context managers exporting Chrome
  trace-event JSON (open in Perfetto);
* :mod:`.metrics` — process-global registry of counters/gauges/histograms
  with Prometheus-text, JSONL, and ScalarLogger exporters, plus
  ``jax.monitoring`` compile hooks;
* :mod:`.profiler` — step-windowed ``jax.profiler`` capture via
  ``DISTKERAS_PROFILE=dir``;
* :mod:`.flightdeck` — live HTTP scrape (``DISTKERAS_TELEMETRY_HTTP``),
  flight-recorder ring with crash blackbox dumps, and the fleet ``run_id``
  stamped into every trace event and scrape.

Everything is gated on ``DISTKERAS_TELEMETRY`` (see :mod:`.runtime`): with
the flag unset, ``trace.span()`` returns a shared no-op and instrumented
code paths take their original branch — no extra host syncs, no extra
allocations.  Import cost is stdlib-only; jax is touched lazily.
"""

from __future__ import annotations

import os

from distkeras_tpu.telemetry import accounting, dynamics, flightdeck, runtime
from distkeras_tpu.telemetry.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    install_jax_hooks,
    metrics,
)
from distkeras_tpu.telemetry.profiler import ProfilerHook
from distkeras_tpu.telemetry.runtime import configure, enabled, out_dir
from distkeras_tpu.telemetry.trace import Span, Tracer, trace

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "ProfilerHook",
    "Registry",
    "Span",
    "Tracer",
    "accounting",
    "configure",
    "dynamics",
    "enabled",
    "flightdeck",
    "flush",
    "install_jax_hooks",
    "metrics",
    "out_dir",
    "runtime",
    "trace",
]


def flush(directory=None):
    """Write the trace and a metrics snapshot to ``directory`` (default:
    :func:`out_dir`).  Returns ``(trace_path, metrics_path)``, or ``None``
    when telemetry is disabled."""
    if not enabled():
        return None
    d = directory or out_dir()
    os.makedirs(d, exist_ok=True)
    pid = os.getpid()
    extra = {"pid": pid}
    rid = flightdeck.current_run_id()
    if rid is not None:
        extra["run_id"] = rid
    trace_path = trace.write(os.path.join(d, f"trace_{pid}.json"))
    metrics_path = metrics.write_jsonl(
        os.path.join(d, f"metrics_{pid}.jsonl"), extra=extra
    )
    return trace_path, metrics_path
