"""Window scheduling — the train half of the online loop, co-scheduled with
serving.

:class:`WindowScheduler` polls a capture directory for windows
:class:`~distkeras_tpu.online.capture.TrafficLog` has published, and closes
each one through the hardened train→serve wire: verify the window's shard
digests, retrain on it (``train_fn``), save the resulting state as a
checkpoint step with a :class:`~distkeras_tpu.datapipe.DataState` sidecar
tying the step back to the capture stream position, and block until the
verified manifest publishes — at which point the serving tier's checkpoint
watcher (:meth:`ServingTier.watch_checkpoints` /
:func:`~distkeras_tpu.serving.watch_and_swap`) rolls the fleet while it
keeps serving.  Chaos folds in at the ``epoch`` fault site (a seeded
``kill_epoch`` kills one retrain, the scheduler retries the window) and the
checkpoint corruption sites (a ``torn_ckpt`` step is rejected at swap time;
the next window's step swaps instead).

:func:`plan_placement` is the capacity-aware placement decision the daemon's
``online_loop`` verb records: given the fleet's live leases, the trainer
lands on the highest-capacity member and serving replicas spread over the
remaining capacity round-robin (sharing the trainer's member only when the
fleet is that small).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from distkeras_tpu import chaos as _chaos
from distkeras_tpu.datapipe.state import DataState
from distkeras_tpu.online.capture import (
    load_window_manifest,
    online_metrics,
    published_windows,
    verify_window,
    window_source,
)

__all__ = ["WindowScheduler", "plan_placement"]


def plan_placement(members: Dict[str, dict], replicas: int) -> dict:
    """Capacity-aware placement of one trainer job + ``replicas`` serving
    replicas over the fleet's live leases.

    ``members`` is the :meth:`FleetMembership.snapshot` ``members`` map
    (``{worker_id: {"workers": capacity, ...}}``).  The trainer takes the
    highest-capacity member (retraining is the throughput-bound job);
    replicas fill the *other* members round-robin weighted by capacity, and
    only overflow onto the trainer's member when the remaining capacity
    cannot hold them — so a one-member fleet still gets a complete
    placement instead of a refusal.  Returns ``{"trainer": worker_id|None,
    "replicas": {worker_id: count}, "capacity": total}``.
    """
    replicas = max(0, int(replicas))
    if not members:
        return {"trainer": None, "replicas": {}, "capacity": 0}
    ranked = sorted(members,
                    key=lambda wid: (-int(members[wid].get("workers", 1)), wid))
    trainer = ranked[0]
    capacity = {wid: max(1, int(members[wid].get("workers", 1)))
                for wid in ranked}
    # serving members: everyone but the trainer, unless that leaves nobody
    # or too little capacity for the replica count
    serving = ranked[1:] or ranked
    if sum(capacity[w] for w in serving) < replicas and trainer not in serving:
        serving = serving + [trainer]
    placed: Dict[str, int] = {}
    slots = [w for w in serving for _ in range(capacity[w])]
    for i in range(replicas):
        wid = slots[i % len(slots)]
        placed[wid] = placed.get(wid, 0) + 1
    return {"trainer": trainer, "replicas": placed,
            "capacity": sum(capacity.values())}


class WindowScheduler:
    """Close published capture windows into verified, hot-swappable
    checkpoints.

    ``train_fn(window, source) -> state`` does the retrain: ``window`` is
    the window index, ``source`` a
    :class:`~distkeras_tpu.datapipe.MemmapSource` over its shards, and the
    returned pytree is what :func:`distkeras_tpu.checkpoint.save_checkpoint`
    publishes as step ``window + step_offset``.  Steps must be new — the
    scheduler never re-publishes a step that already committed (restart
    safety: it baselines on the capture directory's trained cursor, carried
    in the checkpoint directory's committed steps).

    Single-threaded: call :meth:`step_once` from your own loop, or
    :meth:`start` the built-in polling thread.
    """

    def __init__(self, capture_dir: str, train_fn: Callable,
                 checkpoint_dir: str, *, poll_interval: float = 0.25,
                 step_offset: int = 1, max_retries: int = 3,
                 registry=None, window_span_s: float = 30.0,
                 slo_objectives=None, clock=time.monotonic):
        self.capture_dir = capture_dir
        self.checkpoint_dir = checkpoint_dir
        self.train_fn = train_fn
        self.poll_interval = float(poll_interval)
        self.step_offset = int(step_offset)
        self.max_retries = int(max_retries)
        # window_span_s: expected wall-clock cadence of window publication;
        # the shipped SLO alerts once the untrained backlog ages past 2x it.
        self.window_span_s = float(window_span_s)
        self._slo_objectives = slo_objectives
        self._slo = None
        self._clock = clock
        self._metrics = online_metrics(registry)
        self._seen: Dict[int, float] = {}  # window -> first-seen monotonic
        self._last_publish: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.trained = self._baseline_trained()

    def _baseline_trained(self) -> int:
        """Highest window already closed into a committed checkpoint step
        (restart safety: never retrain or re-publish it)."""
        from distkeras_tpu.checkpoint import committed_steps

        steps = committed_steps(self.checkpoint_dir)
        return (max(steps) - self.step_offset) if steps else -1

    # ----------------------------------------------------------- the loop

    def pending_windows(self) -> list:
        """Published-but-untrained window indices, oldest first."""
        published = published_windows(self.capture_dir)
        with self._lock:
            trained = self.trained
        return [w for w in published if w > trained]

    def _update_gauges(self, pending: list) -> None:
        now = self._clock()
        with self._lock:
            for w in pending:
                self._seen.setdefault(w, now)
            self._seen = {w: t for w, t in self._seen.items()
                          if w in set(pending)}
            lag = ((now - min(self._seen[w] for w in pending))
                   if pending else 0.0)
            last_publish = self._last_publish
        self._metrics["window_lag_seconds"].set(lag)
        if last_publish is not None:
            self._metrics["swap_age_seconds"].set(now - last_publish)

    def step_once(self) -> Optional[int]:
        """Train the oldest pending window end to end; returns its index,
        or ``None`` when nothing is pending.  A retrain that raises (chaos
        ``kill_epoch``, a transient trainer fault) is retried up to
        ``max_retries`` times before the error propagates."""
        from distkeras_tpu.checkpoint import (
            save_checkpoint,
            save_data_state,
            wait_until_finished,
        )

        pending = self.pending_windows()
        self._update_gauges(pending)
        if not pending:
            return None
        window = pending[0]
        bad = verify_window(self.capture_dir, window)
        if bad is not None:
            raise RuntimeError(f"window {window} failed shard verification "
                               f"({bad}); refusing to train on torn data")
        manifest = load_window_manifest(self.capture_dir, window)
        source = window_source(self.capture_dir, window)
        t0 = self._clock()
        last_error: Optional[BaseException] = None
        for _ in range(self.max_retries + 1):
            try:
                if _chaos.enabled():
                    _chaos.fault("epoch")  # a killed retrain is retried
                state = self.train_fn(window, source)
                last_error = None
                break
            except Exception as e:  # noqa: BLE001 — counted, then retried
                last_error = e
                self._metrics["retrain_failures"].inc()
        if last_error is not None:
            raise last_error
        step = window + self.step_offset
        save_checkpoint(self.checkpoint_dir, state, step)
        save_data_state(
            self.checkpoint_dir,
            DataState(epoch=window,
                      block_cursor=int(manifest["last_seq"]) + 1),
            step)
        wait_until_finished()  # the verified manifest is the swap trigger
        with self._lock:
            self.trained = window
            self._last_publish = self._clock()
        self._metrics["windows_trained"].inc()
        self._metrics["retrain_seconds"].observe(self._clock() - t0)
        self._update_gauges(self.pending_windows())
        return window

    # ------------------------------------------------------------ control

    def start(self) -> None:
        """Run :meth:`step_once` from a background polling thread until
        :meth:`stop`.  A failed window (exhausted retries, torn shards) is
        left pending and re-attempted next poll rather than killing the
        loop."""
        from distkeras_tpu.telemetry import slo as _slo

        objectives = self._slo_objectives
        if objectives is None:
            objectives = _slo.default_online_objectives(self.window_span_s)
        # None unless telemetry + DISTKERAS_ROLLUP are on — the flag-off
        # polling loop is untouched.
        engine = _slo.maybe_engine(objectives, source="online")
        with self._lock:
            if self._thread is not None:
                return
            if self._slo is None:
                self._slo = engine
            self._stop.clear()

            def _loop():
                while not self._stop.wait(self.poll_interval):
                    try:
                        self.step_once()
                        with self._lock:
                            slo_engine = self._slo
                        if slo_engine is not None:
                            slo_engine.evaluate()
                    except Exception:  # noqa: BLE001 — retried next poll
                        continue

            self._thread = threading.Thread(
                target=_loop, name="online-window-scheduler", daemon=True)
            self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        with self._lock:
            thread, self._thread = self._thread, None
        self._stop.set()
        if thread is not None:
            thread.join(timeout=timeout)

    def status(self) -> dict:
        """JSON-safe progress view (the daemon's ``online_status`` verb)."""
        published = published_windows(self.capture_dir)
        with self._lock:
            trained = self.trained
        return {
            "windows_published": len(published),
            "windows_trained": trained + 1,
            "pending": [w for w in published if w > trained],
        }
