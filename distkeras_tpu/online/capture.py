"""Traffic capture — the serve→train half of the online loop.

:class:`TrafficLog` hangs off the serving frontend's ``/generate`` path (or
any other dispatch point) and turns completed generations back into training
data: each admitted prompt+response becomes one fixed-width int32 token row
in a bounded in-memory ring, and every ``window_samples`` admitted rows the
ring rotates into a pair of :class:`~distkeras_tpu.datapipe.MemmapSource`-
compatible ``.npy`` shards published with a per-window manifest — the same
tmp + fsync + ``os.replace`` verified-publication discipline as checkpoint
manifests (DK118), so a cross-process :class:`WindowScheduler` polling the
directory can never see a torn shard.

Admission is governed by a :class:`SamplingPolicy`: a deterministic sampling
rate (seeded per-sequence-number, no RNG state to checkpoint), an optional
content filter, a per-tenant window quota so one hot client cannot dominate
a retrain window, and an optional per-tenant *rate* policy keyed off the
accounting ledger's rolling usage
(:mod:`distkeras_tpu.telemetry.accounting`) — tenants above the target
tokens-or-samples/sec are deterministically thinned back to it through the
same splitmix admit path.

Crash safety is journal-based: every *offered* sample — admitted or dropped,
with its decision — appends one line to the current window's journal before
the ring mutates, and a :class:`~distkeras_tpu.datapipe.DataState` sidecar
(``capture_state.json``) is republished atomically at every rotation.  A
killed capture therefore resumes **bitwise**: replaying the journal restores
the exact pending rows, per-tenant counts, drop tallies, and sequence
cursor, and an interrupted rotation (shards landed, manifest missing — the
chaos ``kill_rotate`` window) is completed idempotently on resume, so no
sample is ever lost or duplicated.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from distkeras_tpu import chaos as _chaos
from distkeras_tpu import telemetry
from distkeras_tpu.datapipe.source import MemmapSource, atomic_write_npy
from distkeras_tpu.datapipe.state import DataState

__all__ = [
    "SamplingPolicy",
    "TrafficLog",
    "load_window_manifest",
    "online_metrics",
    "published_windows",
    "verify_window",
    "window_manifest_path",
    "window_source",
]

_STATE_FILE = "capture_state.json"


def online_metrics(registry=None) -> dict:
    """Get-or-create the online loop's instruments (default: process-global
    registry).  One canonical home for names/help so capture, scheduler,
    the golden test, and the CI loop smoke assert the same schema."""
    if registry is None:
        from distkeras_tpu.telemetry.metrics import metrics as registry
    return {
        "ingested": registry.counter(
            "online_samples_ingested_total",
            help="served samples admitted into the capture window ring",
        ),
        "dropped": registry.counter(
            "online_samples_dropped_total",
            help="served samples dropped at capture admission "
                 "(sampling rate, content filter, or tenant quota)",
        ),
        "quota_drops": registry.counter(
            "online_quota_drops_total",
            help="served samples dropped by the per-tenant window quota",
        ),
        "rate_drops": registry.counter(
            "online_rate_drops_total",
            help="served samples dropped by the per-tenant rate policy "
                 "(rolling ledger rate above the configured tenant_rate)",
        ),
        "capture_errors": registry.counter(
            "online_capture_errors_total",
            help="capture hook failures swallowed at the serving path "
                 "(the response still left)",
        ),
        "windows_published": registry.counter(
            "online_windows_published_total",
            help="capture windows rotated into published replay shards",
        ),
        "windows_trained": registry.counter(
            "online_windows_trained_total",
            help="published windows retrained into a verified checkpoint",
        ),
        "retrain_failures": registry.counter(
            "online_retrain_failures_total",
            help="window retrains that raised and were retried",
        ),
        "window_lag_seconds": registry.gauge(
            "online_window_lag_seconds",
            help="age of the oldest published-but-untrained window",
        ),
        "swap_age_seconds": registry.gauge(
            "online_swap_age_seconds",
            help="seconds since the last retrained checkpoint published "
                 "(freshness of what the serving fleet hot-swaps to)",
        ),
        "retrain_seconds": registry.histogram(
            "online_retrain_seconds",
            help="wall seconds per window retrain (train step + verified "
                 "checkpoint publish)",
        ),
    }


class SamplingPolicy:
    """Admission policy for captured traffic.

    ``rate``: fraction of offered samples kept, decided by a *deterministic*
    per-sequence-number draw (seeded splitmix-style hash, no RNG object) —
    the decision for sample ``seq`` is a pure function of ``(seed, seq)``,
    so a resumed capture re-derives identical decisions without
    checkpointing generator state.  ``filter``: optional
    ``f(prompt, tokens) -> bool`` content gate (False drops).
    ``tenant_quota``: max admitted samples any one tenant gets per window —
    the fairness backstop that keeps a hot client from flooding a retrain
    window (dropped-by-quota is separately counted and surfaced).
    ``tenant_rate``: a per-tenant *rate* target in ``rate_unit``/sec
    (``"samples"`` or ``"tokens"``), judged against the accounting
    ``ledger``'s rolling usage
    (:meth:`~distkeras_tpu.telemetry.accounting.TenantLedger.rolling_rate`):
    a tenant running above the target is thinned with admission probability
    ``target / observed`` through a decorrelated splitmix draw — the same
    stateless (seed, seq) determinism as ``rate``, so resume re-derives the
    decisions given the same observed rates.  Without a ``ledger`` (or for
    a tenant it has never seen) the rate policy admits — no usage signal,
    no throttle.
    """

    def __init__(self, rate: float = 1.0,
                 tenant_quota: Optional[int] = None,
                 filter: Optional[Callable] = None,  # noqa: A002 — API word
                 seed: int = 0,
                 tenant_rate: Optional[float] = None,
                 rate_unit: str = "samples",
                 ledger=None):
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {rate}")
        if tenant_quota is not None and tenant_quota < 1:
            raise ValueError(f"tenant_quota must be >= 1, got {tenant_quota}")
        if tenant_rate is not None and tenant_rate <= 0:
            raise ValueError(f"tenant_rate must be > 0, got {tenant_rate}")
        if rate_unit not in ("samples", "tokens"):
            raise ValueError(
                f"rate_unit must be 'samples' or 'tokens', got {rate_unit!r}")
        self.rate = float(rate)
        self.tenant_quota = None if tenant_quota is None else int(tenant_quota)
        self.filter = filter
        self.seed = int(seed)
        self.tenant_rate = None if tenant_rate is None else float(tenant_rate)
        self.rate_unit = rate_unit
        self.ledger = ledger

    def _uniform(self, seq: int) -> float:
        # splitmix64 finalizer over (seed, seq): uniform enough for a
        # sampling gate, stateless, and bit-stable across platforms
        x = ((self.seed << 32) ^ seq) & 0xFFFFFFFFFFFFFFFF
        x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9 & 0xFFFFFFFFFFFFFFFF
        x = (x ^ (x >> 27)) * 0x94D049BB133111EB & 0xFFFFFFFFFFFFFFFF
        x ^= x >> 31
        return (x >> 11) / float(1 << 53)

    def _keep(self, seq: int) -> bool:
        return self._uniform(seq) < self.rate

    def admit(self, seq: int, tenant: str, tenant_count: int,
              prompt, tokens) -> Optional[str]:
        """``None`` to admit, else the drop reason (``"sampled"``,
        ``"filtered"``, ``"rate"``, ``"quota"``).  ``tenant_count`` is the
        tenant's admitted-sample count in the current window."""
        if self.rate < 1.0 and not self._keep(seq):
            return "sampled"
        if self.filter is not None and not self.filter(prompt, tokens):
            return "filtered"
        if self.tenant_rate is not None and self.ledger is not None:
            unit = "tokens" if self.rate_unit == "tokens" else "requests"
            observed = self.ledger.rolling_rate(tenant, unit=unit)
            if observed > self.tenant_rate:
                # thin to the target: admit with p = target/observed; the
                # xor decorrelates this draw from the sampling-rate draw so
                # the two gates stay independent per sequence number
                draw = self._uniform(seq ^ 0x9E3779B97F4A7C15)
                if draw >= self.tenant_rate / observed:
                    return "rate"
        if self.tenant_quota is not None and tenant_count >= self.tenant_quota:
            return "quota"
        return None


def window_manifest_path(directory: str, window: int) -> str:
    """The ``window_<n>.manifest.json`` publication record — present iff
    the window's shards are complete and durable."""
    return os.path.join(os.path.abspath(directory),
                        f"window_{int(window):06d}.manifest.json")


def _shard_paths(directory: str, window: int) -> tuple:
    directory = os.path.abspath(directory)
    return (os.path.join(directory, f"window_{int(window):06d}.features.npy"),
            os.path.join(directory, f"window_{int(window):06d}.labels.npy"))


def published_windows(directory: str) -> List[int]:
    """Sorted indices of fully published windows (manifest present)."""
    out = []
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return out
    for name in names:
        if name.startswith("window_") and name.endswith(".manifest.json"):
            digits = name[len("window_"):-len(".manifest.json")]
            if digits.isdigit():
                out.append(int(digits))
    return sorted(out)


def load_window_manifest(directory: str, window: int) -> dict:
    with open(window_manifest_path(directory, window), encoding="utf-8") as fh:
        return json.load(fh)


def verify_window(directory: str, window: int) -> Optional[str]:
    """Re-verify a published window's shard bytes against the manifest
    digests (the same full-hash gate the checkpoint watcher applies at swap
    time).  Returns a human-readable failure, or ``None`` when clean."""
    import hashlib

    try:
        manifest = load_window_manifest(directory, window)
    except (OSError, ValueError) as e:
        return f"manifest unreadable: {e}"
    for rel, meta in manifest.get("files", {}).items():
        path = os.path.join(os.path.abspath(directory), rel)
        try:
            size = os.path.getsize(path)
        except OSError:
            return f"{rel}: missing"
        if size != meta["bytes"]:
            return f"{rel}: {size} bytes, manifest says {meta['bytes']}"
        h = hashlib.sha256()
        with open(path, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
        if h.hexdigest() != meta["sha256"]:
            return f"{rel}: sha256 mismatch"
    return None


def window_source(directory: str, window: int, **kwargs) -> MemmapSource:
    """A :class:`MemmapSource` over one published window's shards.
    Capture shards are already per-host, so sharding defaults off."""
    feats, labels = _shard_paths(directory, window)
    kwargs.setdefault("shard", False)
    return MemmapSource(feats, labels, **kwargs)


class TrafficLog:
    """Bounded capture ring over served generations, rotated into published
    replay windows.

    ``record(request, result)`` offers one completed generation; admitted
    samples become ``prompt + tokens`` rows padded/truncated to ``max_len``
    (features: ``[n, max_len]`` int32; labels: ``[n]`` int32 true lengths,
    the loss mask for next-token retraining).  Constructing a TrafficLog on
    a directory with prior capture state **resumes** it — see the module
    docstring for the journal/sidecar protocol.

    Thread-safe: the serving frontend calls ``record`` from per-request
    handler threads.
    """

    def __init__(self, directory: str, *, window_samples: int = 64,
                 max_len: int = 64, pad_id: int = 0,
                 policy: Optional[SamplingPolicy] = None,
                 registry=None):
        if window_samples < 1:
            raise ValueError(f"window_samples must be >= 1, got {window_samples}")
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2, got {max_len}")
        self.directory = os.path.abspath(directory)
        self.window_samples = int(window_samples)
        self.max_len = int(max_len)
        self.pad_id = int(pad_id)
        self.policy = policy or SamplingPolicy()
        self._metrics = (online_metrics(registry)
                         if registry is not None or telemetry.enabled()
                         else None)
        # reentrant: record/flush/_resume hold it across _rotate, which
        # re-acquires (keeping every mutation lexically under the lock)
        self._lock = threading.RLock()
        self._pending: List[tuple] = []  # (seq, tenant, row, length)
        self._tenant_counts: Dict[str, int] = {}
        self._dropped: Dict[str, int] = {}
        self._window = 0
        self._seq = 0
        self._journal = None
        os.makedirs(self.directory, exist_ok=True)
        self._resume()

    # ------------------------------------------------------------- resume

    def _journal_path(self, window: int) -> str:
        return os.path.join(self.directory, f"journal_{int(window):06d}.jsonl")

    def _resume(self) -> None:
        """Roll state forward from disk: published manifests are ground
        truth for completed windows, the sidecar for cumulative counters,
        and the newest journal for pending rows and unaccounted drops.
        Every crash window of the rotation sequence (shards → manifest →
        sidecar → journal rollover) resumes to the same state the
        uninterrupted capture would have reached — no sample lost, none
        duplicated."""
        with self._lock:
            state_path = os.path.join(self.directory, _STATE_FILE)
            state_window = 0
            if os.path.exists(state_path):
                with open(state_path, encoding="utf-8") as fh:
                    state = json.load(fh)
                state_window = int(state.get("window", 0))
                self._seq = int(state.get("next_seq", 0))
                self._dropped = {k: int(v)
                                 for k, v in (state.get("dropped") or {}).items()}
            self._window = state_window
            published = published_windows(self.directory)
            covered = -1  # newest seq owned by a published window
            if published and published[-1] >= state_window:
                # crashed after manifest publish but before the sidecar update:
                # the manifest wins — its rows are done, but the journal still
                # holds that window's drop decisions (not yet folded into the
                # sidecar) and any carry-over rows past the manifest boundary
                manifest = load_window_manifest(self.directory, published[-1])
                covered = int(manifest["last_seq"])
                self._window = published[-1] + 1
                self._seq = max(self._seq, covered + 1)
            # journals strictly older than the sidecar's window are fully
            # accounted (rows published, drops folded in): replaying them
            # would double-count
            for window in range(state_window):
                try:
                    os.remove(self._journal_path(window))
                except FileNotFoundError:
                    pass
            # replay the newest journal: pending rows (skipping any a published
            # manifest already owns), tenant counts, drop tallies, seq cursor
            replay = self._journal_path(state_window)
            if os.path.exists(replay):
                with open(replay, encoding="utf-8") as fh:
                    for line in fh:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            break  # torn tail line from a mid-write kill
                        seq = int(rec["seq"])
                        self._seq = max(self._seq, seq + 1)
                        reason = rec.get("drop")
                        if reason is not None:
                            self._dropped[reason] = self._dropped.get(reason, 0) + 1
                            continue
                        if seq <= covered:
                            continue  # already in a published shard
                        tenant = str(rec.get("tenant", ""))
                        row = np.asarray(rec["row"], dtype=np.int32)
                        self._pending.append((seq, tenant, row, int(rec["len"])))
                        self._tenant_counts[tenant] = \
                            self._tenant_counts.get(tenant, 0) + 1
            current = self._journal_path(self._window)
            if self._window != state_window:
                # the replayed remainder belongs to the advanced window's
                # journal; rewrite it there, then retire the stale journal
                self._journal = open(current, "w", encoding="utf-8")
                for seq, tenant, row, length in self._pending:
                    self._journal_write({"seq": seq, "tenant": tenant,
                                         "row": [int(t) for t in row],
                                         "len": length})
                self._write_state()
                if replay != current:
                    try:
                        os.remove(replay)
                    except FileNotFoundError:
                        pass
            else:
                self._journal = open(current, "a", encoding="utf-8")
            # an interrupted rotation (full pending ring, shards maybe on disk,
            # manifest missing) completes now — idempotently, same bytes
            while len(self._pending) >= self.window_samples:
                self._rotate()

    # ------------------------------------------------------------- capture

    def record(self, request, result) -> bool:
        """Offer one completed generation (a
        :class:`~distkeras_tpu.serving.GenerateRequest` and its
        :class:`~distkeras_tpu.serving.GenerateResult`); returns whether it
        was admitted into the current window."""
        prompt = [int(t) for t in request.prompt]
        tokens = [int(t) for t in result.tokens]
        tenant = str(getattr(request, "tenant", "") or "")
        with self._lock:
            seq = self._seq
            self._seq += 1
            reason = self.policy.admit(
                seq, tenant, self._tenant_counts.get(tenant, 0),
                prompt, tokens)
            if reason is not None:
                self._dropped[reason] = self._dropped.get(reason, 0) + 1
                self._journal_write({"seq": seq, "tenant": tenant,
                                     "drop": reason})
                if self._metrics is not None:
                    self._metrics["dropped"].inc()
                    if reason == "quota":
                        self._metrics["quota_drops"].inc()
                    elif reason == "rate":
                        self._metrics["rate_drops"].inc()
                return False
            row = np.full(self.max_len, self.pad_id, dtype=np.int32)
            merged = (prompt + tokens)[:self.max_len]
            row[:len(merged)] = merged
            self._journal_write({"seq": seq, "tenant": tenant,
                                 "row": [int(t) for t in row],
                                 "len": len(merged)})
            self._pending.append((seq, tenant, row, len(merged)))
            self._tenant_counts[tenant] = self._tenant_counts.get(tenant, 0) + 1
            if self._metrics is not None:
                self._metrics["ingested"].inc()
            if len(self._pending) >= self.window_samples:
                self._rotate()
            return True

    def _journal_write(self, rec: dict) -> None:
        self._journal.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._journal.flush()

    # ------------------------------------------------------------ rotation

    def _rotate(self) -> int:
        """Publish the pending ring head as window ``self._window``
        (re-acquires the reentrant lock, so callers may already hold it).
        Order: shards (atomic each) → chaos ``window_rotate`` site →
        manifest (atomic) → sidecar (atomic) → journal rollover.  A kill
        at the chaos site leaves shards without a manifest; resume replays
        the journal and re-runs this function, producing byte-identical
        shards — publication is idempotent."""
        import hashlib

        with self._lock:
            batch = self._pending[:self.window_samples]
            window = self._window
            features = np.stack([row for _, _, row, _ in batch])
            labels = np.asarray([length for _, _, _, length in batch],
                                dtype=np.int32)
            f_path, l_path = _shard_paths(self.directory, window)
            atomic_write_npy(f_path, features)
            atomic_write_npy(l_path, labels)
            # the journal must be durable before the manifest claims the window:
            # a resume after the chaos site below replays it to re-publish
            os.fsync(self._journal.fileno())
            if _chaos.enabled():
                _chaos.fault("window_rotate")
            files = {}
            for path in (f_path, l_path):
                h = hashlib.sha256()
                with open(path, "rb") as fh:
                    for chunk in iter(lambda: fh.read(1 << 20), b""):
                        h.update(chunk)
                files[os.path.basename(path)] = {
                    "sha256": h.hexdigest(), "bytes": os.path.getsize(path)}
            tenants: Dict[str, int] = {}
            for _, tenant, _, _ in batch:
                tenants[tenant] = tenants.get(tenant, 0) + 1
            _atomic_write_json(window_manifest_path(self.directory, window), {
                "version": 1,
                "window": window,
                "samples": len(batch),
                "first_seq": batch[0][0],
                "last_seq": batch[-1][0],
                "max_len": self.max_len,
                "tenants": tenants,
                "files": files,
            })
            # window closed: advance the cursor, then make the new position
            # durable before fresh samples can land in the next journal
            self._pending = self._pending[self.window_samples:]
            self._tenant_counts = {}
            for _, tenant, _, _ in self._pending:
                self._tenant_counts[tenant] = self._tenant_counts.get(tenant, 0) + 1
            self._window = window + 1
            self._write_state()
            old = self._journal
            self._journal = open(self._journal_path(self._window), "a",
                                 encoding="utf-8")
            # carry-over samples (admitted past the window boundary) belong to
            # the new journal so resume finds them there
            for seq, tenant, row, length in self._pending:
                self._journal_write({"seq": seq, "tenant": tenant,
                                     "row": [int(t) for t in row],
                                     "len": length})
            old.close()
            try:
                os.remove(self._journal_path(window))
            except FileNotFoundError:
                pass
            if self._metrics is not None:
                self._metrics["windows_published"].inc()
            return window

    def _write_state(self) -> None:
        _atomic_write_json(os.path.join(self.directory, _STATE_FILE), {
            "version": 1,
            "window": self._window,
            "next_seq": self._seq,
            "dropped": dict(self._dropped),
            "data_state": DataState(epoch=self._window,
                                    block_cursor=self._seq).to_json(),
        })

    # ------------------------------------------------------------- control

    def flush(self) -> Optional[int]:
        """Force-rotate a partial window (shutdown path: trailing samples
        still become a training window).  Returns the published window
        index, or ``None`` when nothing was pending."""
        with self._lock:
            if not self._pending:
                return None
            saved = self.window_samples
            self.window_samples = len(self._pending)
            try:
                return self._rotate()
            finally:
                self.window_samples = saved

    def close(self) -> None:
        with self._lock:
            self._write_state()
            if self._journal is not None:
                self._journal.close()
                self._journal = None

    # ---------------------------------------------------------- inspection

    @property
    def window(self) -> int:
        """Index the *next* rotation will publish."""
        with self._lock:
            return self._window

    @property
    def next_seq(self) -> int:
        with self._lock:
            return self._seq

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def dropped(self) -> Dict[str, int]:
        """Cumulative drop counts by reason."""
        with self._lock:
            return dict(self._dropped)


def _atomic_write_json(path: str, obj) -> None:
    # same tmp+fsync+replace+dir-fsync discipline as checkpoint manifests;
    # duplicated locally so the capture path never imports the (jax/orbax-
    # heavy) checkpoint module
    from distkeras_tpu.datapipe.source import _fsync_dir

    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(obj, fh, sort_keys=True)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))
