"""Online learning loop — close the serve→train circle on one fleet.

The train→serve half of continuous learning already exists (verified
checkpoint publication + the serving tier's rolling hot-swap, ROADMAP item
5); this package adds the missing serve→train half, so one fleet serves,
captures what it served, retrains on it, and hot-swaps to the result —
continuously, and provably under fault injection:

* :class:`~distkeras_tpu.online.capture.TrafficLog` — bounded in-memory
  ring over served generations, journal-backed for bitwise crash resume,
  rotated into :class:`~distkeras_tpu.datapipe.MemmapSource`-compatible
  ``.npy`` replay shards published atomically with per-window manifests
  (tmp + fsync + ``os.replace``, per-file sha256 — the checkpoint
  discipline applied to data);
* :class:`~distkeras_tpu.online.capture.SamplingPolicy` — deterministic
  sampling rate, content filter, and per-tenant window quotas so one hot
  client cannot dominate a retrain window;
* :class:`~distkeras_tpu.online.scheduler.WindowScheduler` — polls for
  published windows and closes each into retrain → verified checkpoint
  publish (+ :class:`~distkeras_tpu.datapipe.DataState` sidecar) → the
  serving tier's watcher rolls the fleet, zero dropped requests;
* :func:`~distkeras_tpu.online.scheduler.plan_placement` — capacity-aware
  trainer/replica placement over live fleet leases, recorded by the
  daemon's ``online_loop`` / ``online_status`` / ``stop_online`` verbs
  (:mod:`distkeras_tpu.job_deployment`);
* :func:`~distkeras_tpu.online.capture.online_metrics` — the ``online_*``
  flightdeck schema (window lag, samples ingested / dropped-by-quota,
  swap age), pinned by ``tests/golden/online_metrics.txt``.

Wire it up in-process::

    from distkeras_tpu import online, serving
    log = online.TrafficLog(capture_dir, window_samples=256,
                            policy=online.SamplingPolicy(tenant_quota=64))
    serving.install_http_endpoint(engine, traffic_log=log)   # capture
    sched = online.WindowScheduler(capture_dir, train_fn, ckpt_dir)
    tier.watch_checkpoints(ckpt_dir, loader)                 # hot-swap
    sched.start()                                            # retrain

or as a daemon deployment: ``Job.online_loop(replicas=3, ...)`` spawns the
serving tier and the scheduler loop as co-scheduled jobs on one fleet.
``bench.py --loop`` runs the whole circle — served traffic → captured
windows → retrain → verified publish → rolling hot-swap — with the chaos
harness armed.
"""

from distkeras_tpu.online.capture import (
    SamplingPolicy,
    TrafficLog,
    load_window_manifest,
    online_metrics,
    published_windows,
    verify_window,
    window_manifest_path,
    window_source,
)
from distkeras_tpu.online.scheduler import WindowScheduler, plan_placement

__all__ = [
    "SamplingPolicy",
    "TrafficLog",
    "WindowScheduler",
    "load_window_manifest",
    "online_metrics",
    "plan_placement",
    "published_windows",
    "verify_window",
    "window_manifest_path",
    "window_source",
]
