"""Compute ops: losses, metrics, optimizer registry (all jit-safe)."""

from distkeras_tpu.ops.losses import get_loss
from distkeras_tpu.ops.metrics import accuracy, get_metric, token_accuracy
from distkeras_tpu.ops.optimizers import get_optimizer
from distkeras_tpu.ops.pooling import max_pool

__all__ = ["get_loss", "get_metric", "get_optimizer", "accuracy", "token_accuracy", "max_pool"]
