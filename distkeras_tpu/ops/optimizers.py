"""Worker-optimizer registry.

The reference hands Keras optimizer strings/objects to ``model.compile`` in
the worker (``distkeras/workers.py``).  Here the same strings resolve to
``optax`` gradient transformations — the local (worker-side) optimizer that
runs between parameter-server commits.
"""

from __future__ import annotations

import optax

__all__ = ["get_optimizer"]

_DEFAULT_LR = {
    "sgd": 0.01,
    "momentum": 0.01,
    "adam": 0.001,
    "adagrad": 0.01,
    "rmsprop": 0.001,
    "adamw": 0.001,
}


def get_optimizer(spec, learning_rate: float | None = None, **kwargs) -> optax.GradientTransformation:
    """Resolve an optimizer spec: optax transform | name | (name, kwargs)."""
    if isinstance(spec, optax.GradientTransformation):
        return spec
    if isinstance(spec, tuple):
        name, kw = spec
        return get_optimizer(name, **{**kw, **kwargs})
    name = str(spec).lower()
    lr = learning_rate if learning_rate is not None else kwargs.pop("lr", _DEFAULT_LR.get(name, 0.01))
    if name == "sgd":
        return optax.sgd(lr, momentum=kwargs.get("momentum", 0.0), nesterov=kwargs.get("nesterov", False))
    if name == "momentum":
        return optax.sgd(lr, momentum=kwargs.get("momentum", 0.9), nesterov=kwargs.get("nesterov", True))
    if name == "adam":
        return optax.adam(lr)
    if name == "adamw":
        return optax.adamw(lr, weight_decay=kwargs.get("weight_decay", 1e-4))
    if name == "adagrad":
        return optax.adagrad(lr)
    if name == "rmsprop":
        return optax.rmsprop(lr)
    raise ValueError(f"unknown optimizer {spec!r}")
