"""Jit-safe metric functions (mirrors the ``metrics=['accuracy']`` surface of
``distkeras/trainers.py`` and the offline ``AccuracyEvaluator``)."""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["accuracy", "token_accuracy", "get_metric"]


def accuracy(preds, labels):
    """Top-1 accuracy; labels may be class indices or one-hot/prob vectors."""
    preds = jnp.asarray(preds)
    labels = jnp.asarray(labels)
    if preds.ndim > 1 and preds.shape[-1] > 1:
        pred_idx = jnp.argmax(preds, axis=-1)
    else:
        pred_idx = (preds.reshape(-1) > 0.5).astype(jnp.int32)
    if labels.ndim > 1 and labels.shape[-1] > 1:
        label_idx = jnp.argmax(labels, axis=-1)
    else:
        label_idx = labels.reshape(-1).astype(jnp.int32)
    return jnp.mean((pred_idx == label_idx).astype(jnp.float32))


def token_accuracy(preds, labels):
    """Next-token top-1 accuracy: preds [B, T, V], labels int [B, T]."""
    preds = jnp.asarray(preds)
    labels = jnp.asarray(labels).astype(jnp.int32)
    return jnp.mean((jnp.argmax(preds, axis=-1) == labels).astype(jnp.float32))


def get_metric(spec):
    if callable(spec):
        return spec
    name = str(spec).lower()
    if name in ("accuracy", "acc", "categorical_accuracy"):
        return accuracy
    if name in ("token_accuracy", "lm_accuracy"):
        return token_accuracy
    raise ValueError(f"unknown metric {spec!r}")
