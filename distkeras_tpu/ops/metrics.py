"""Jit-safe metric functions (mirrors the ``metrics=['accuracy']`` surface of
``distkeras/trainers.py`` and the offline ``AccuracyEvaluator``)."""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["accuracy", "token_accuracy", "get_metric", "per_token_metric_names"]

#: every alias get_metric resolves to the classifier accuracy — kept in one
#: place so the per-token rewrite below can't drift from the registry
_ACCURACY_ALIASES = ("accuracy", "acc", "categorical_accuracy")


def per_token_metric_names(metrics):
    """Canonicalise a metrics spec for per-token (LM) models: any classifier
    accuracy alias becomes ``token_accuracy`` (its [B, T] labels would
    otherwise be read as one-hot rows).  Callables pass through untouched."""
    return tuple(
        "token_accuracy"
        if isinstance(m, str) and m.lower() in _ACCURACY_ALIASES
        else m
        for m in metrics
    )


def accuracy(preds, labels):
    """Top-1 accuracy; labels may be class indices or one-hot/prob vectors."""
    preds = jnp.asarray(preds)
    labels = jnp.asarray(labels)
    if preds.ndim > 1 and preds.shape[-1] > 1:
        pred_idx = jnp.argmax(preds, axis=-1)
    else:
        pred_idx = (preds.reshape(-1) > 0.5).astype(jnp.int32)
    if labels.ndim > 1 and labels.shape[-1] > 1:
        label_idx = jnp.argmax(labels, axis=-1)
    else:
        label_idx = labels.reshape(-1).astype(jnp.int32)
    return jnp.mean((pred_idx == label_idx).astype(jnp.float32))


def token_accuracy(preds, labels):
    """Next-token top-1 accuracy: preds [B, T, V], labels int [B, T]."""
    preds = jnp.asarray(preds)
    labels = jnp.asarray(labels).astype(jnp.int32)
    return jnp.mean((jnp.argmax(preds, axis=-1) == labels).astype(jnp.float32))


def get_metric(spec):
    if callable(spec):
        return spec
    name = str(spec).lower()
    if name in _ACCURACY_ALIASES:
        return accuracy
    if name in ("token_accuracy", "lm_accuracy"):
        return token_accuracy
    raise ValueError(f"unknown metric {spec!r}")
