"""Loss registry with Keras-string parity.

The reference passes Keras loss strings straight into ``model.compile``
(``distkeras/workers.py :: Worker.prepare_model``).  Here the same strings
resolve to pure jit-safe functions ``loss(preds, labels) -> scalar``; each has
a logits and a probabilities form so both the in-tree zoo (logits out) and
Keras models (softmax out) get numerically-stable loss values.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax

__all__ = ["get_loss"]

_EPS = 1e-7


def _maybe_onehot(labels, num_classes):
    labels = jnp.asarray(labels)
    if labels.ndim >= 1 and labels.shape[-1] == num_classes and jnp.issubdtype(labels.dtype, jnp.floating):
        return labels
    return jax.nn.one_hot(labels.reshape(labels.shape[0], -1)[..., 0].astype(jnp.int32), num_classes)


def _categorical_crossentropy(from_logits: bool):
    def loss(preds, labels):
        labels = _maybe_onehot(labels, preds.shape[-1])
        if from_logits:
            return optax.softmax_cross_entropy(preds, labels).mean()
        p = jnp.clip(preds, _EPS, 1.0 - _EPS)
        return -(labels * jnp.log(p)).sum(-1).mean()

    return loss


def _binary_crossentropy(from_logits: bool):
    def loss(preds, labels):
        preds = preds.reshape(preds.shape[0], -1)
        labels = jnp.asarray(labels, preds.dtype).reshape(preds.shape)
        if from_logits:
            return optax.sigmoid_binary_cross_entropy(preds, labels).mean()
        p = jnp.clip(preds, _EPS, 1.0 - _EPS)
        return -(labels * jnp.log(p) + (1 - labels) * jnp.log(1 - p)).mean()

    return loss


def _mse(preds, labels):
    labels = jnp.asarray(labels, preds.dtype).reshape(preds.shape)
    return jnp.mean(jnp.square(preds - labels))


def _mae(preds, labels):
    labels = jnp.asarray(labels, preds.dtype).reshape(preds.shape)
    return jnp.mean(jnp.abs(preds - labels))


def _token_crossentropy(from_logits: bool):
    """Per-token LM crossentropy: preds [B, T, V] (logits), labels int [B, T].
    Mean over batch and tokens — under sequence parallelism each shard's
    local mean over its equal-size block makes the shard-averaged gradient
    exactly the global-mean gradient (see WindowedEngine._sync_grads)."""

    def loss(preds, labels):
        labels = jnp.asarray(labels).astype(jnp.int32)
        if from_logits:
            return optax.softmax_cross_entropy_with_integer_labels(
                preds, labels
            ).mean()
        p = jnp.clip(preds, _EPS, 1.0 - _EPS)
        picked = jnp.take_along_axis(p, labels[..., None], axis=-1)[..., 0]
        return -jnp.log(picked).mean()

    return loss


def _masked_token_crossentropy(from_logits: bool):
    """:func:`_token_crossentropy` with an ignore label: positions whose
    label is ``< 0`` (the sequence-packing convention — pads and segment
    tails carry ``-1``, :mod:`distkeras_tpu.datapipe.packing`) contribute
    nothing, and the mean runs over real tokens only.  The clamp to 0 keeps
    the gather in-range; its contribution is zeroed by the mask."""

    def loss(preds, labels):
        labels = jnp.asarray(labels).astype(jnp.int32)
        mask = (labels >= 0).astype(preds.dtype)
        safe = jnp.maximum(labels, 0)
        if from_logits:
            per_tok = optax.softmax_cross_entropy_with_integer_labels(
                preds, safe
            )
        else:
            p = jnp.clip(preds, _EPS, 1.0 - _EPS)
            per_tok = -jnp.log(
                jnp.take_along_axis(p, safe[..., None], axis=-1)[..., 0]
            )
        return (per_tok * mask).sum() / jnp.maximum(mask.sum(), 1.0)

    return loss


def get_loss(spec, from_logits: bool = True) -> Callable:
    """Resolve a Keras-style loss string (or pass through a callable)."""
    if callable(spec):
        return spec
    name = str(spec).lower()
    if name in ("token_crossentropy", "lm_crossentropy"):
        return _token_crossentropy(from_logits)
    if name in ("masked_token_crossentropy", "packed_crossentropy"):
        return _masked_token_crossentropy(from_logits)
    if name in ("categorical_crossentropy", "sparse_categorical_crossentropy", "crossentropy"):
        return _categorical_crossentropy(from_logits)
    if name in ("binary_crossentropy",):
        return _binary_crossentropy(from_logits)
    if name in ("mse", "mean_squared_error"):
        return _mse
    if name in ("mae", "mean_absolute_error"):
        return _mae
    raise ValueError(f"unknown loss {spec!r}")
