"""Fused flash-attention Pallas TPU kernels.

The reference delegates all tensor math to Keras/TF kernels (SURVEY.md §2:
"zero native components"); this module is the TPU-native analogue for the one
op where fusion matters most at long context: attention.  The jnp ring /
local attention in :mod:`distkeras_tpu.parallel.ring` already avoids the
[seq, seq] materialisation at the *inter-device* level; these kernels do the
same at the *intra-device* level — tiled online-softmax in VMEM, so HBM
traffic is O(seq·d) instead of O(seq²), with the matmuls shaped for the MXU.

Forward and backward (FlashAttention-2 style: recompute probabilities
blockwise, separate dQ and dK/dV passes) are both Pallas kernels, joined by a
``jax.custom_vjp``.  On non-TPU backends the same kernels run under the Pallas
interpreter (tests exercise them on the CPU device mesh); production CPU paths
should keep using the jnp fallback in ``parallel.ring``.

Layout convention matches the rest of the framework: [batch, seq, heads, dim].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

_NEG_BIG = -1e30  # used instead of -inf so fully-masked rows stay NaN-free
_LANES = 128


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _validity_mask(i, j, bq, bk, lq_valid, lk_valid, causal):
    """[bq, bk] bool mask: True where the score element is attended."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + i * bq
    cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + j * bk
    mask = (rows < lq_valid) & (cols < lk_valid)
    if causal:
        mask &= rows >= cols
    return mask


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal, bq, bk, lq_valid, lk_valid):
    i = pl.program_id(1)  # q block
    j = pl.program_id(2)  # k block (innermost)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_BIG)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Causal: K blocks strictly above this Q block's diagonal contribute
    # nothing — skip their FLOPs entirely (predicated out, grid is static).
    live = (j * bk <= i * bq + bq - 1) if causal else True

    @pl.when(live)
    def _block():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        mask = _validity_mask(i, j, bq, bk, lq_valid, lk_valid, causal)
        s = jnp.where(mask, s, _NEG_BIG)

        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new) * mask.astype(jnp.float32)
        l_new = alpha * l_prev + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        l_fin = l_ref[:, :1]
        l_safe = jnp.where(l_fin == 0.0, 1.0, l_fin)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        # lse = m + log(l); 0 for fully-masked (padding) rows — bwd masks them.
        lse = jnp.where(
            l_fin > 0.0, m_ref[:, :1] + jnp.log(l_safe), 0.0
        )
        lse_ref[0, 0] = lse[:, 0]


def _fwd_call(qt, kt, vt, *, scale, causal, bq, bk, lq_valid, lk_valid,
              interpret):
    bh, lq, d = qt.shape
    lk = kt.shape[1]
    grid = (bh, lq // bq, lk // bk)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk,
        lq_valid=lq_valid, lk_valid=lk_valid,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq, d), qt.dtype),
            jax.ShapeDtypeStruct((bh, 1, lq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),       # output accumulator
            pltpu.VMEM((bq, _LANES), jnp.float32),  # running max m
            pltpu.VMEM((bq, _LANES), jnp.float32),  # running denominator l
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out, lse


# ---------------------------------------------------------------------------
# backward (FlashAttention-2: blockwise recompute; dQ pass + dK/dV pass)
# ---------------------------------------------------------------------------


def _p_ds(q, k, v, do, lse, delta, i, j, *, scale, causal, bq, bk,
          lq_valid, lk_valid):
    """Recompute the probability block P and its gradient dS (both [bq, bk])."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    mask = _validity_mask(i, j, bq, bk, lq_valid, lk_valid, causal)
    p = jnp.exp(s - lse[:, None]) * mask.astype(jnp.float32)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta[:, None])
    return p, ds


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_ref, *, scale, causal, bq, bk, lq_valid, lk_valid):
    i = pl.program_id(1)  # q block
    j = pl.program_id(2)  # k block (innermost)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    live = (j * bk <= i * bq + bq - 1) if causal else True

    @pl.when(live)
    def _block():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        _, ds = _p_ds(q, k, v, do, lse_ref[0, 0], delta_ref[0, 0], i, j,
                      scale=scale, causal=causal, bq=bq, bk=bk,
                      lq_valid=lq_valid, lk_valid=lk_valid)
        acc_ref[:] += scale * jnp.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc,
                *, scale, causal, bq, bk, lq_valid, lk_valid):
    j = pl.program_id(1)  # k block
    i = pl.program_id(2)  # q block (innermost)
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    # Causal: Q blocks entirely above this K block see none of it.
    live = (i * bq + bq - 1 >= j * bk) if causal else True

    @pl.when(live)
    def _block():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        p, ds = _p_ds(q, k, v, do, lse_ref[0, 0], delta_ref[0, 0], i, j,
                      scale=scale, causal=causal, bq=bq, bk=bk,
                      lq_valid=lq_valid, lk_valid=lk_valid)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dk_acc[:] += scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(i == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_call(qt, kt, vt, out, lse, dot_, *, scale, causal, bq, bk,
              lq_valid, lk_valid, interpret):
    bh, lq, d = qt.shape
    lk = kt.shape[1]
    # delta_i = rowsum(dO_i · O_i); tiny elementwise op, XLA fuses it.
    delta = jnp.sum(dot_.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, None, :]

    q_spec = pl.BlockSpec((1, bq, d), lambda b, x, y: (b, x, 0),
                          memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, bk, d), lambda b, x, y: (b, y, 0),
                          memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, 1, bq), lambda b, x, y: (b, 0, x),
                            memory_space=pltpu.VMEM)
    common = dict(scale=scale, causal=causal, bq=bq, bk=bk,
                  lq_valid=lq_valid, lk_valid=lk_valid)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **common),
        grid=(bh, lq // bq, lk // bk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, lq, d), qt.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot_, lse, delta)

    # dK/dV pass: grid transposed — (k block, q block innermost).
    q_spec_t = pl.BlockSpec((1, bq, d), lambda b, y, x: (b, x, 0),
                            memory_space=pltpu.VMEM)
    k_spec_t = pl.BlockSpec((1, bk, d), lambda b, y, x: (b, y, 0),
                            memory_space=pltpu.VMEM)
    row_spec_t = pl.BlockSpec((1, 1, bq), lambda b, y, x: (b, 0, x),
                              memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, **common),
        grid=(bh, lk // bk, lq // bq),
        in_specs=[q_spec_t, k_spec_t, k_spec_t, q_spec_t, row_spec_t,
                  row_spec_t],
        out_specs=[k_spec_t, k_spec_t],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lk, d), kt.dtype),
            jax.ShapeDtypeStruct((bh, lk, d), vt.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, dot_, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry — [batch, seq, heads, dim], custom VJP
# ---------------------------------------------------------------------------


def _to_bh(x):
    b, l, h, d = x.shape
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, l, d)


def _from_bh(x, b, h):
    bh, l, d = x.shape
    return jnp.transpose(x.reshape(b, h, l, d), (0, 2, 1, 3))


def _pad_seq(x, block):
    l = x.shape[1]
    lp = _round_up(l, block)
    if lp == l:
        return x
    return jnp.pad(x, ((0, 0), (0, lp - l), (0, 0)))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=False, block_q=256, block_k=512,
                    interpret=None):
    """Fused attention over [batch, seq, heads, dim] tensors.

    Semantics match ``parallel.ring.local_attention`` (softmax(QKᵀ/√d)·V,
    optional causal mask) but run as tiled Pallas kernels: online softmax in
    VMEM, no [seq, seq] materialisation in HBM, f32 accumulation regardless of
    input dtype.  ``interpret=None`` auto-selects the Pallas interpreter on
    non-TPU backends (used by the CPU-mesh test suite).

    Measured on TPU v5e (1 chip, b=2 h=8 d=64, causal, bf16, fwd+bwd): parity
    with the XLA jnp path at seq 2048, 1.36x faster at 8192, and still running
    at 16384 where the materialised-scores path fails to compile.  Default
    blocks (256, 512) are from that sweep.
    """
    return _fa_fwd(q, k, v, causal, block_q, block_k, interpret)[0]


def _prep(lq, lk, block_q, block_k, interpret):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bq = min(block_q, _round_up(lq, 16))
    bk = min(block_k, _round_up(lk, 16))
    return bq, bk, interpret


def _fa_fwd(q, k, v, causal, block_q, block_k, interpret):
    b, lq, h, d = q.shape
    lk = k.shape[1]
    bq, bk, interpret = _prep(lq, lk, block_q, block_k, interpret)
    scale = 1.0 / (d ** 0.5)
    qt = _pad_seq(_to_bh(q), bq)
    kt = _pad_seq(_to_bh(k), bk)
    vt = _pad_seq(_to_bh(v), bk)
    out_p, lse = _fwd_call(
        qt, kt, vt, scale=scale, causal=causal, bq=bq, bk=bk,
        lq_valid=lq, lk_valid=lk, interpret=interpret,
    )
    out = _from_bh(out_p[:, :lq], b, h)
    return out, (q, k, v, out_p, lse)


def _fa_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out_p, lse = res
    b, lq, h, d = q.shape
    lk = k.shape[1]
    bq, bk, interpret = _prep(lq, lk, block_q, block_k, interpret)
    scale = 1.0 / (d ** 0.5)
    qt = _pad_seq(_to_bh(q), bq)
    kt = _pad_seq(_to_bh(k), bk)
    vt = _pad_seq(_to_bh(v), bk)
    dot_ = _pad_seq(_to_bh(g), bq)
    dq, dk, dv = _bwd_call(
        qt, kt, vt, out_p, lse, dot_, scale=scale, causal=causal,
        bq=bq, bk=bk, lq_valid=lq, lk_valid=lk, interpret=interpret,
    )
    return (_from_bh(dq[:, :lq], b, h), _from_bh(dk[:, :lk], b, h),
            _from_bh(dv[:, :lk], b, h))


flash_attention.defvjp(_fa_fwd, _fa_bwd)
