"""Pallas TPU kernels for the framework's hot ops.

Kernels auto-fall-back to the Pallas interpreter on non-TPU backends so the
CPU device-mesh test suite exercises the same code path the TPU runs.
"""

from distkeras_tpu.ops.pallas.flash_attention import flash_attention

__all__ = ["flash_attention"]
