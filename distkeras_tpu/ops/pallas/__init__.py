"""Pallas TPU kernels for the framework's hot ops.

On non-TPU backends the kernels can run under the Pallas interpreter
(``interpret=True``), which is how ``tests/test_pallas_kernels.py`` validates
them against the reference jnp attention.  Note the production dispatcher
(``distkeras_tpu.parallel.ring.attention``) routes non-TPU backends to the
jnp path, so the CPU device-mesh integration tests do NOT exercise these
kernels — only the dedicated kernel tests do.
"""

from distkeras_tpu.ops.pallas.flash_attention import flash_attention

__all__ = ["flash_attention"]
