"""TPU-friendly pooling.

``flax.linen.max_pool`` lowers to ``lax.reduce_window`` whose gradient is an
XLA ``select-and-scatter`` — profiled at ~11% of the CIFAR-CNN training step
on TPU v5e (it cannot fuse with the surrounding conv/ReLU fusions).  For the
overwhelmingly common case — non-overlapping windows, VALID padding, evenly
divisible spatial dims — an exact reshape-then-reduce formulation lowers to a
plain ``reduce_max`` whose gradient is an elementwise equality mask that XLA
fuses into neighbouring kernels.

The reference has no pooling op of its own (all compute is delegated to
Keras/TF — ``distkeras/workers.py`` just calls ``train_on_batch``); this
module exists because the rebuild owns its compute path.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["max_pool"]


def max_pool(
    x: jnp.ndarray,
    window_shape: Sequence[int] = (2, 2),
    strides: Optional[Sequence[int]] = None,
    padding: str = "VALID",
) -> jnp.ndarray:
    """Drop-in replacement for ``flax.linen.max_pool`` (NHWC / NWC layouts).

    Takes the reshape fast path when windows are non-overlapping
    (``strides == window_shape``), padding is VALID, and every pooled spatial
    dim divides evenly; falls back to ``flax.linen.max_pool`` otherwise.
    Forward numerics are identical in every case.  Gradients differ when a
    window holds exact ties: the fast path distributes the tie's gradient
    evenly across the tied positions, while select-and-scatter picks a single
    winner.  Both are valid subgradients of max, but ties are *common* in
    practice — these layers pool post-ReLU feature maps, where exact zeros
    carry large probability mass — so training trajectories can differ from
    the flax path routinely, not just on a measure-zero set.
    """
    window_shape = tuple(window_shape)
    strides = window_shape if strides is None else tuple(strides)
    spatial = x.shape[1:-1]  # leading batch, trailing channels
    if (
        padding == "VALID"
        and strides == window_shape
        and len(spatial) == len(window_shape)
        and all(s % w == 0 for s, w in zip(spatial, window_shape))
    ):
        shape = [x.shape[0]]
        axes = []
        for dim, w in zip(spatial, window_shape):
            shape.extend((dim // w, w))
            axes.append(len(shape) - 1)
        shape.append(x.shape[-1])
        return x.reshape(shape).max(axis=tuple(axes))
    return nn.max_pool(x, window_shape, strides=strides, padding=padding)
