"""Intraprocedural dataflow for dklint v3: CFG + reaching definitions +
value provenance.

Checkers ask two questions the flat AST walks of v1/v2 could not answer:

  * **which definition does this name refer to here?** —
    :meth:`FunctionFlow.reaching` maps every ``Name`` load to the set of
    definitions (assignments, loop targets, parameters, ...) that may have
    produced the value it reads, computed over a per-function control-flow
    graph with a standard reaching-definitions fixpoint;
  * **may this value derive from a traced input?** — :func:`tainted_uses`
    closes provenance over assignments (``y = x * 2`` taints ``y`` when
    ``x`` is tainted), which is what lets DK101/DK109 stop flagging a
    parameter name after it was rebound to a host constant, and keep
    flagging it when the rebinding still derives from the parameter.

The CFG is statement-granular: one node per simple statement, plus head
nodes for ``if``/``while`` tests and ``for`` iterators.  ``try`` bodies are
modelled conservatively — every node of the body may transfer to every
handler (an exception can fire mid-statement), so a handler's entry state is
the union of all states the body can be in.  Nested ``def``/``lambda``
bodies are opaque (each function gets its own :class:`FunctionFlow`); the
``def`` statement itself is a binding of the function name.

Everything is stdlib ``ast`` — no execution, no imports of analyzed code.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = ["Def", "FunctionFlow", "function_flow", "tainted_uses",
           "expr_uses", "edit_distance"]

_FN_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class Def:
    """One definition of a local name.

    ``kind`` is one of ``param`` / ``assign`` / ``aug`` / ``for`` /
    ``with`` / ``except`` / ``bind`` (def/class/import) / ``walrus``.
    ``value`` is the expression the bound value comes from when there is
    one (the RHS, the ``for`` iterator, the ``with`` context expression);
    ``use_nodes`` are the ``Name`` loads inside that expression, i.e. the
    dataflow inputs of this definition.
    """

    __slots__ = ("name", "stmt", "value", "kind", "use_nodes")

    def __init__(self, name: str, stmt: ast.AST, value: Optional[ast.AST],
                 kind: str, use_nodes: Optional[List[ast.Name]] = None):
        self.name = name
        self.stmt = stmt
        self.value = value
        self.kind = kind
        self.use_nodes = use_nodes if use_nodes is not None else (
            _expr_uses(value) if value is not None else [])

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        line = getattr(self.stmt, "lineno", "?")
        return f"<Def {self.name} {self.kind}@{line}>"


def _expr_uses(node: Optional[ast.AST]) -> List[ast.Name]:
    """``Name`` loads evaluated by an expression, in source order.  Skips
    nested function/lambda bodies (deferred execution) but not
    comprehensions (they run immediately and close over outer names)."""
    out: List[ast.Name] = []
    if node is None:
        return out
    stack = [node]
    while stack:
        cur = stack.pop()
        if isinstance(cur, _FN_NODES) and cur is not node:
            # default values / decorators of a nested def are evaluated in
            # the enclosing scope; its body is not
            if isinstance(cur, ast.Lambda):
                stack.extend(ast.iter_child_nodes(cur.args))
            continue
        if isinstance(cur, ast.Name) and isinstance(cur.ctx, ast.Load):
            out.append(cur)
            continue
        stack.extend(ast.iter_child_nodes(cur))
    out.sort(key=lambda n: (n.lineno, n.col_offset))
    return out


def expr_uses(node: Optional[ast.AST]) -> List[ast.Name]:
    """Public alias of :func:`_expr_uses` for checkers that need the
    ``Name`` loads of an arbitrary expression to intersect with a taint
    set."""
    return _expr_uses(node)


def _target_names(target: ast.AST) -> List[ast.Name]:
    """Plain-``Name`` binding targets of an assignment target (tuples and
    starred elements unpacked; attribute/subscript stores are not local
    defs)."""
    if isinstance(target, ast.Name):
        return [target]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[ast.Name] = []
        for el in target.elts:
            out.extend(_target_names(el))
        return out
    if isinstance(target, ast.Starred):
        return _target_names(target.value)
    return []


class _Node:
    __slots__ = ("stmt", "uses", "gen", "kills", "succ", "preds", "in_defs")

    def __init__(self, stmt: Optional[ast.AST]):
        self.stmt = stmt
        self.uses: List[ast.Name] = []
        self.gen: List[Def] = []
        self.kills: Set[str] = set()  # del-statement kills with no new def
        self.succ: List["_Node"] = []
        self.preds: List["_Node"] = []
        self.in_defs: Dict[str, frozenset] = {}


def _walrus_defs(stmt: ast.AST,
                 roots: Optional[Sequence[ast.AST]] = None) -> List[Def]:
    """``(y := f(x))`` bindings in a statement's expressions.  For compound
    statements pass ``roots`` (the head expressions only, e.g. ``stmt.test``
    / ``stmt.iter``) — a walrus inside a body/orelse statement is gen'd at
    that statement's own CFG node, and scanning the whole subtree from the
    head would make it reach before its branch executes (e.g. a walrus in
    the else arm spuriously reaching the if body)."""
    out: List[Def] = []
    stack: List[ast.AST] = (
        [r for r in roots if r is not None] if roots is not None
        else list(ast.iter_child_nodes(stmt))
    )
    while stack:
        cur = stack.pop()
        if isinstance(cur, _FN_NODES):
            continue
        if isinstance(cur, ast.NamedExpr) and isinstance(cur.target, ast.Name):
            out.append(Def(cur.target.id, stmt, cur.value, "walrus"))
        stack.extend(ast.iter_child_nodes(cur))
    return out


class _CFGBuilder:
    def __init__(self) -> None:
        self.nodes: List[_Node] = []
        self.exit = self._node(None)
        # stack of (break_frontier, continue_target) per enclosing loop
        self._loops: List[Tuple[List[_Node], _Node]] = []

    def _node(self, stmt: Optional[ast.AST]) -> _Node:
        n = _Node(stmt)
        self.nodes.append(n)
        return n

    @staticmethod
    def _connect(preds: Sequence[_Node], node: _Node) -> None:
        for p in preds:
            node_succ = p.succ
            if node not in node_succ:
                node_succ.append(node)

    def block(self, stmts: Sequence[ast.stmt], preds: List[_Node]) -> List[_Node]:
        for stmt in stmts:
            preds = self.stmt(stmt, preds)
        return preds

    def stmt(self, stmt: ast.stmt, preds: List[_Node]) -> List[_Node]:
        if isinstance(stmt, ast.If):
            test = self._node(stmt)
            test.uses = _expr_uses(stmt.test)
            test.gen = _walrus_defs(stmt, [stmt.test])
            self._connect(preds, test)
            body_out = self.block(stmt.body, [test])
            else_out = self.block(stmt.orelse, [test]) if stmt.orelse else [test]
            return body_out + else_out

        if isinstance(stmt, ast.While):
            test = self._node(stmt)
            test.uses = _expr_uses(stmt.test)
            test.gen = _walrus_defs(stmt, [stmt.test])
            self._connect(preds, test)
            breaks: List[_Node] = []
            self._loops.append((breaks, test))
            body_out = self.block(stmt.body, [test])
            self._connect(body_out, test)  # back edge
            self._loops.pop()
            out = self.block(stmt.orelse, [test]) if stmt.orelse else [test]
            return out + breaks

        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            head = self._node(stmt)
            head.uses = _expr_uses(stmt.iter)
            head.gen = [
                Def(t.id, stmt, stmt.iter, "for") for t in _target_names(stmt.target)
            ] + _walrus_defs(stmt, [stmt.iter])
            self._connect(preds, head)
            breaks = []
            self._loops.append((breaks, head))
            body_out = self.block(stmt.body, [head])
            self._connect(body_out, head)  # back edge
            self._loops.pop()
            out = self.block(stmt.orelse, [head]) if stmt.orelse else [head]
            return out + breaks

        if isinstance(stmt, ast.Try):
            start = len(self.nodes)
            body_out = self.block(stmt.body, preds)
            body_nodes = self.nodes[start:]
            handler_outs: List[_Node] = []
            for handler in stmt.handlers:
                hnode = self._node(handler)
                hnode.uses = _expr_uses(handler.type)
                if handler.name:
                    hnode.gen = [Def(handler.name, handler, None, "except")]
                # an exception may fire before, or mid-way through, any
                # statement of the body: the handler can observe every
                # state the body passes through
                self._connect(list(preds) + body_nodes, hnode)
                handler_outs.extend(self.block(handler.body, [hnode]))
            merged = (
                self.block(stmt.orelse, body_out) if stmt.orelse else body_out
            ) + handler_outs
            if stmt.finalbody:
                return self.block(stmt.finalbody, merged)
            return merged

        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            head = self._node(stmt)
            for item in stmt.items:
                head.uses.extend(_expr_uses(item.context_expr))
                if item.optional_vars is not None:
                    head.gen.extend(
                        Def(t.id, stmt, item.context_expr, "with")
                        for t in _target_names(item.optional_vars)
                    )
            head.gen.extend(
                _walrus_defs(stmt, [item.context_expr for item in stmt.items])
            )
            self._connect(preds, head)
            return self.block(stmt.body, [head])

        if isinstance(stmt, (ast.Return, ast.Raise)):
            n = self._node(stmt)
            n.uses = _expr_uses(stmt)
            # a walrus in the returned/raised expression is observable past
            # this node (a try-body raise transfers its out state to the
            # handlers)
            n.gen = _walrus_defs(stmt)
            self._connect(preds, n)
            self._connect([n], self.exit)
            return []

        if isinstance(stmt, ast.Break):
            n = self._node(stmt)
            self._connect(preds, n)
            if self._loops:
                self._loops[-1][0].append(n)
            else:  # malformed input; keep the graph connected
                self._connect([n], self.exit)
            return []

        if isinstance(stmt, ast.Continue):
            n = self._node(stmt)
            self._connect(preds, n)
            if self._loops:
                self._connect([n], self.loops_head())
            else:
                self._connect([n], self.exit)
            return []

        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            n = self._node(stmt)
            # decorators and parameter defaults run now, in this scope;
            # the body does not
            for dec in stmt.decorator_list:
                n.uses.extend(_expr_uses(dec))
            args = getattr(stmt, "args", None)
            if args is not None:
                for default in list(args.defaults) + [
                    d for d in args.kw_defaults if d is not None
                ]:
                    n.uses.extend(_expr_uses(default))
            if isinstance(stmt, ast.ClassDef):
                for base in stmt.bases:
                    n.uses.extend(_expr_uses(base))
            n.gen = [Def(stmt.name, stmt, None, "bind")]
            self._connect(preds, n)
            return [n]

        if isinstance(stmt, (ast.Import, ast.ImportFrom)):
            n = self._node(stmt)
            for alias in stmt.names:
                if alias.name == "*":
                    continue
                bound = alias.asname or alias.name.split(".")[0]
                n.gen.append(Def(bound, stmt, None, "bind"))
            self._connect(preds, n)
            return [n]

        # simple statements: Assign / AugAssign / AnnAssign / Expr /
        # Assert / Delete / Pass / Global / Nonlocal / unknown compounds
        n = self._node(stmt)
        if isinstance(stmt, ast.Assign):
            n.uses = _expr_uses(stmt.value)
            unpack = any(not isinstance(t, ast.Name) for t in stmt.targets)
            for target in stmt.targets:
                n.uses.extend(
                    u for u in _expr_uses(target)  # a[i] = v evaluates a, i
                )
                n.gen.extend(
                    Def(t.id, stmt, stmt.value,
                        "assign" if not unpack else "assign")
                    for t in _target_names(target)
                )
        elif isinstance(stmt, ast.AugAssign):
            n.uses = _expr_uses(stmt.value)
            if isinstance(stmt.target, ast.Name):
                # the target is read before it is written
                read = ast.Name(id=stmt.target.id, ctx=ast.Load())
                ast.copy_location(read, stmt.target)
                n.uses.append(read)
                n.gen = [Def(stmt.target.id, stmt, None, "aug",
                             use_nodes=list(n.uses))]
            else:
                n.uses.extend(_expr_uses(stmt.target))
        elif isinstance(stmt, ast.AnnAssign):
            n.uses = _expr_uses(stmt.value)
            if stmt.value is not None and isinstance(stmt.target, ast.Name):
                n.gen = [Def(stmt.target.id, stmt, stmt.value, "assign")]
        elif isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                if isinstance(target, ast.Name):
                    n.kills.add(target.id)
                else:
                    n.uses.extend(_expr_uses(target))
        else:
            n.uses = _expr_uses(stmt)
        n.gen = list(n.gen) + _walrus_defs(stmt)
        self._connect(preds, n)
        return [n]

    def loops_head(self) -> _Node:
        return self._loops[-1][1]


class FunctionFlow:
    """CFG + reaching definitions for one function (or lambda)."""

    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.param_defs: Dict[str, Def] = {}
        args = fn.args
        names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
        if args.vararg:
            names.append(args.vararg.arg)
        if args.kwarg:
            names.append(args.kwarg.arg)
        for name in names:
            self.param_defs[name] = Def(name, fn, None, "param")

        builder = _CFGBuilder()
        self._entry = builder._node(None)
        self._entry.gen = list(self.param_defs.values())
        body = fn.body if isinstance(fn.body, list) else [ast.Expr(fn.body)]
        if not isinstance(fn.body, list):  # Lambda: body is an expression
            ast.copy_location(body[0], fn.body)
        out = builder.block(body, [self._entry])
        builder._connect(out, builder.exit)
        self._nodes = builder.nodes
        for node in self._nodes:
            for s in node.succ:
                s.preds.append(node)

        self.defs: List[Def] = [d for n in self._nodes for d in n.gen]
        self._solve()
        self._use_defs: Dict[int, Tuple[Def, ...]] = {}
        self._use_nodes: Dict[int, ast.Name] = {}
        self._use_owner: Dict[int, _Node] = {}
        for node in self._nodes:
            env = node.in_defs
            for use in node.uses:
                self._use_defs[id(use)] = tuple(env.get(use.id, ()))
                self._use_nodes[id(use)] = use
                self._use_owner[id(use)] = node
        self._loop_map = self._index_loops()

    # ------------------------------------------------------------ solving

    def _solve(self) -> None:
        worklist = list(self._nodes)
        out_state: Dict[int, Dict[str, frozenset]] = {
            id(n): {} for n in self._nodes
        }
        while worklist:
            node = worklist.pop()
            merged: Dict[str, set] = {}
            for p in node.preds:
                for name, defs in out_state[id(p)].items():
                    merged.setdefault(name, set()).update(defs)
            in_defs = {k: frozenset(v) for k, v in merged.items()}
            out = dict(in_defs)
            for name in node.kills:
                out.pop(name, None)
            for d in node.gen:
                out[d.name] = frozenset((d,))
            node.in_defs = in_defs
            if out != out_state[id(node)]:
                out_state[id(node)] = out
                worklist.extend(node.succ)

    # ------------------------------------------------------------- queries

    def reaching(self, name_node: ast.Name) -> Tuple[Def, ...]:
        """Definitions that may produce the value this ``Name`` load reads.
        Empty for free variables (closure / global / builtin names) — those
        are trace-time constants as far as the checkers care."""
        return self._use_defs.get(id(name_node), ())

    def is_use(self, name_node: ast.Name) -> bool:
        return id(name_node) in self._use_defs

    def may_follow(self, use_a: ast.Name, use_b: ast.Name) -> bool:
        """May one run of the function evaluate ``use_a`` and then
        ``use_b``?  False exactly when the CFG node owning ``use_b`` is
        unreachable from the one owning ``use_a`` — e.g. exclusive
        ``if``/``else`` arms (back edges make loop iterations count as
        "following").  Conservatively True for nodes the CFG does not
        own (defensive: every registered use has an owner)."""
        a = self._use_owner.get(id(use_a))
        b = self._use_owner.get(id(use_b))
        if a is None or b is None or a is b:
            return True
        seen: Set[int] = {id(a)}
        stack = list(a.succ)
        while stack:
            node = stack.pop()
            if node is b:
                return True
            if id(node) in seen:
                continue
            seen.add(id(node))
            stack.extend(node.succ)
        return False

    def _index_loops(self) -> Dict[int, List[ast.AST]]:
        """id(ast node) -> enclosing For/While loops of this function (not
        descending into nested defs)."""
        out: Dict[int, List[ast.AST]] = {}

        def walk(node: ast.AST, loops: List[ast.AST]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FN_NODES):
                    continue
                inner = loops
                if isinstance(child, (ast.For, ast.AsyncFor, ast.While)):
                    inner = loops + [child]
                out[id(child)] = inner
                walk(child, inner)

        out[id(self.fn)] = []
        walk(self.fn, [])
        return out

    def enclosing_loops(self, node: ast.AST) -> List[ast.AST]:
        return self._loop_map.get(id(node), [])


def function_flow(fn: ast.AST,
                  cache: Optional[Dict[int, FunctionFlow]] = None) -> FunctionFlow:
    """Build (or fetch from ``cache``) the :class:`FunctionFlow` for ``fn``."""
    if cache is not None:
        flow = cache.get(id(fn))
        if flow is None:
            flow = cache[id(fn)] = FunctionFlow(fn)
        return flow
    return FunctionFlow(fn)


def tainted_uses(flow: FunctionFlow, seed_names: Iterable[str]) -> Set[int]:
    """ids of ``Name``-load nodes whose value may derive from the named
    parameters.

    A definition is tainted when it is one of the seed parameter defs, or
    when any ``Name`` load in its value expression may read a tainted
    definition; a use is tainted when any of its reaching definitions is
    tainted.  Free variables (closure constants, globals) never taint —
    they are trace-time constants, which is exactly the false-positive
    class this function exists to kill.
    """
    tainted: Set[int] = {
        id(flow.param_defs[name])
        for name in seed_names
        if name in flow.param_defs
    }
    if not tainted:
        return set()
    changed = True
    while changed:
        changed = False
        for d in flow.defs:
            if id(d) in tainted:
                continue
            for use in d.use_nodes:
                if any(id(r) in tainted for r in flow.reaching(use)):
                    tainted.add(id(d))
                    changed = True
                    break
    return {
        uid
        for uid, defs in flow._use_defs.items()
        if any(id(r) in tainted for r in defs)
    }


def edit_distance(a: str, b: str, cap: int = 3) -> int:
    """Levenshtein distance, early-exited at ``cap`` (returns ``cap`` when
    the true distance is >= cap) — DK114's near-miss metric."""
    if a == b:
        return 0
    if abs(len(a) - len(b)) >= cap:
        return cap
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        best = i
        for j, cb in enumerate(b, 1):
            cost = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + (ca != cb))
            cur.append(cost)
            best = min(best, cost)
        if best >= cap:
            return cap
        prev = cur
    return min(prev[-1], cap)
