import sys

from tools.dklint.cli import main

sys.exit(main())
