"""Checker registry: rule id -> checker class, populated by import side
effect of :mod:`tools.dklint.checkers`."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Type

from tools.dklint.core import Checker

_REGISTRY: Dict[str, Type[Checker]] = {}


def register(cls: Type[Checker]) -> Type[Checker]:
    if not cls.rule or not cls.rule.startswith("DK"):
        raise ValueError(f"checker {cls.__name__} must define a DKxxx rule id")
    if cls.rule in _REGISTRY:
        raise ValueError(f"duplicate checker rule {cls.rule}")
    _REGISTRY[cls.rule] = cls
    return cls


def all_rules() -> Dict[str, Type[Checker]]:
    import tools.dklint.checkers  # noqa: F401 — registration side effect

    return dict(sorted(_REGISTRY.items()))


def get_checkers(select: Optional[Sequence[str]] = None) -> List[Checker]:
    rules = all_rules()
    if select:
        wanted = {s.upper() for s in select}
        unknown = wanted - set(rules)
        if unknown:
            raise ValueError(f"unknown rule(s): {', '.join(sorted(unknown))}")
        rules = {k: v for k, v in rules.items() if k in wanted}
    return [cls() for cls in rules.values()]
