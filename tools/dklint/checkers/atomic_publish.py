"""DK118 — non-atomic publication of a cross-process-read file.

The checkpoint/telemetry/discovery directories are read by *other
processes* (serving watchers verify manifests, the daemon polls discovery
files, dktrace merges trace dumps).  A bare ``open(path, "w")`` +
``json.dump``/``fh.write`` publishes through a window where the file
exists half-written: a reader polling at the wrong moment parses torn
JSON, or worse, acts on it.  The PR-15 publication discipline is tmp +
``os.replace`` (readers see the old file or the new file, never a torn
one); this rule is its static twin.

A finding fires on an ``open`` call when, within one function:

* the file opens in a write mode (``"w"``/``"wt"``/``"wb"`` — appends are
  logs, not publications, and stay silent);
* the handle provably receives content — ``handle.write(...)`` /
  ``.writelines(...)``, or the handle is an argument to a ``*.dump``
  call (``json.dump``, ``pickle.dump``);
* and the function contains **no** ``os.replace`` / ``os.rename`` — the
  atomic-commit step that would make the tmp-file idiom whole.

Scope: the publication surfaces only — ``checkpoint.py``, ``fleet.py``,
``job_deployment.py``, anything under ``telemetry/``, and any module
whose basename mentions checkpoint/flightdeck/discovery.  Private
scratch files elsewhere may legitimately be written in place.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, List, Optional, Tuple

from tools.dklint.core import Checker, FileInfo, Finding, Project, call_name
from tools.dklint.registry import register

_SCOPE_BASENAMES = frozenset({"checkpoint.py", "fleet.py", "job_deployment.py"})
_SCOPE_MARKERS = ("checkpoint", "flightdeck", "discovery")

_WRITE_MODES = frozenset({"w", "wt", "wb", "w+", "wb+", "w+b"})

_FN_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _in_scope(fi: FileInfo) -> bool:
    base = os.path.basename(fi.relpath)
    parts = fi.relpath.replace(os.sep, "/").split("/")
    return (
        base in _SCOPE_BASENAMES
        or "telemetry" in parts
        or any(m in base for m in _SCOPE_MARKERS)
    )


def _resolved(fi: FileInfo, node: ast.Call) -> str:
    name = call_name(node) or ""
    head, _, rest = name.partition(".")
    target = fi.imports.get(head)
    if target:
        return target + ("." + rest if rest else "")
    return name


def _write_mode(call: ast.Call) -> Optional[str]:
    """The literal mode string when this ``open(...)`` opens for write."""
    mode: Optional[ast.expr] = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return None  # default "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        if mode.value in _WRITE_MODES:
            return mode.value
        return None
    return None  # non-literal mode — provenance unknown, stay silent


def _open_bindings(fn: ast.AST) -> List[Tuple[ast.Call, Optional[str]]]:
    """Write-mode ``open`` calls in ``fn`` with the name, if any, their
    handle binds to (``with open(...) as fh`` / ``fh = open(...)``)."""
    out: List[Tuple[ast.Call, Optional[str]]] = []

    def bind_name(target) -> Optional[str]:
        return target.id if isinstance(target, ast.Name) else None

    seen = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                call = item.context_expr
                if isinstance(call, ast.Call) and call_name(call) == "open" \
                        and _write_mode(call):
                    name = None
                    if item.optional_vars is not None:
                        name = bind_name(item.optional_vars)
                    out.append((call, name))
                    seen.add(id(call))
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if call_name(call) == "open" and _write_mode(call) \
                    and id(call) not in seen:
                name = bind_name(node.targets[0]) if len(node.targets) == 1 \
                    else None
                out.append((call, name))
                seen.add(id(call))
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and call_name(node) == "open" \
                and _write_mode(node) and id(node) not in seen:
            out.append((node, None))  # unbound (e.g. open(...).write(...))
    return out


def _handle_written(fn: ast.AST, handle: Optional[str],
                    open_call: ast.Call) -> bool:
    """Does the opened handle provably receive content?"""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in (
                "write", "writelines"):
            recv = func.value
            if handle is not None and isinstance(recv, ast.Name) \
                    and recv.id == handle:
                return True
            if recv is open_call:  # open(...).write(...)
                return True
        if isinstance(func, ast.Attribute) and func.attr == "dump":
            # json.dump(obj, fh) / pickle.dump(obj, fh)
            for arg in node.args:
                if handle is not None and isinstance(arg, ast.Name) \
                        and arg.id == handle:
                    return True
                if arg is open_call:
                    return True
    return False


def _has_atomic_commit(fi: FileInfo, fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and _resolved(fi, node) in (
                "os.replace", "os.rename"):
            return True
    return False


@register
class AtomicPublishChecker(Checker):
    rule = "DK118"
    name = "non-atomic-publication"
    description = (
        "open(path, 'w') + dump/write to a cross-process-read file "
        "(checkpoint/telemetry/discovery) with no os.replace in the same "
        "function — readers can see the file half-written"
    )

    def check(self, project: Project, fi: FileInfo) -> Iterable[Finding]:
        if not _in_scope(fi):
            return
        for fn in ast.walk(fi.tree):
            if not isinstance(fn, _FN_NODES):
                continue
            nested = set()
            for child in ast.walk(fn):
                if child is not fn and isinstance(child, _FN_NODES):
                    nested.update(id(s) for s in ast.walk(child))
                    nested.discard(id(child))  # the def itself scans later
            if _has_atomic_commit(fi, fn):
                continue
            for call, handle in _open_bindings(fn):
                if id(call) in nested:
                    continue  # the enclosing walk reaches it via its own def
                if not _handle_written(fn, handle, call):
                    continue
                yield Finding(
                    path=fi.relpath,
                    line=call.lineno,
                    col=call.col_offset,
                    rule=self.rule,
                    message=(
                        "non-atomic publication: this open(..., "
                        f"'{_write_mode(call)}') writes a cross-process-read "
                        "file in place — a concurrent reader can see it "
                        "half-written; write to a tmp name and os.replace "
                        "it into place in this function"
                    ),
                )
